"""Cluster transport — pooled keep-alive RPC with hedged twin reads.

The reference treats its inter-host courier as a first-class perf
component: ``UdpServer.cpp`` keeps persistent endpoints per host with
niceness-aware resend, and ``Multicast.cpp:520`` (``pickBestHost``)
sends each read to the least-loaded twin and re-routes when it dawdles.
This module is that layer for the HTTP plane:

* **Connection pool** — one keep-alive :class:`http.client.HTTPConnection`
  stack per peer address. A reused socket that turns out to be stale
  (peer restarted, idle timeout) is retried ONCE on a fresh dial;
  errors on a fresh dial propagate. Timeouts never auto-retry — the
  request may have executed.
* **Hedged reads** (Dean & Barroso, "The Tail at Scale", CACM 2013) —
  the primary goes to the currently-fastest live twin; after a hedge
  delay of ``clamp(2×EWMA(rtt), floor, cap)`` the SAME request launches
  at the next twin, first good answer wins, the loser is abandoned.
  ``transport.hedge_fired`` / ``transport.hedge_won`` count how often
  the insurance was bought and how often it paid.
* **Binary wire codec** for bulk routes — length-prefixed raw ndarray
  frames instead of base64-inside-JSON (+33% wire, megabytes through
  ``json.loads``). Negotiated per request: the client advertises
  ``Accept: application/x-osse-bin``; a node that understands replies
  binary with the matching Content-Type, an old node ignores the header
  and replies JSON, and an old client never advertises — so any
  new↔old version mix degrades to the JSON wire cleanly.

Everything observable lands in :data:`~..utils.stats.g_stats`
(``transport.*`` counters/latencies/gauges) and is served by
``/admin/transport`` on the serving side.
"""

from __future__ import annotations

import http.client
import io
import json
import struct
import threading
import time
from typing import Callable

import numpy as np

from ..utils import chaos as chaos_mod, deadline as deadline_mod, \
    priority as priority_mod, threads, trace as trace_mod
from ..utils.lockcheck import make_lock
from ..utils.log import get_logger
from ..utils.stats import g_stats

log = get_logger("transport")

#: reply header carrying the answering node's Rdb generation (its posdb
#: version) — the cache plane's cluster-wide invalidation signal
GEN_HEADER = "X-OSSE-Gen"

#: negotiated content type for the binary frame codec
BIN_CONTENT_TYPE = "application/x-osse-bin"
#: frame magic + codec version (bump on incompatible frame changes)
BIN_MAGIC = b"OSSE1"

#: hedge delay bounds: never hedge sooner than the floor (loopback EWMA
#: is microseconds — hedging every request would double cluster read
#: load), never later than the cap (the whole point is beating the
#: multi-second request timeout)
HEDGE_FLOOR_S = 0.05
HEDGE_CAP_S = 2.0
#: idle keep-alive sockets retained per peer (ThreadingHTTPServer burns
#: a thread per open connection — keep the standing footprint small)
POOL_MAX_IDLE = 4

_RETRY_ERRORS = (http.client.BadStatusLine, http.client.CannotSendRequest,
                 http.client.ResponseNotReady, ConnectionResetError,
                 ConnectionAbortedError, BrokenPipeError)


class RpcError(Exception):
    """Transport-level RPC failure (connect/send/recv/HTTP status)."""


class NotOkError(RpcError):
    """The peer ANSWERED, but the reply failed the acceptability check —
    a healthy host saying no (doc miss, refused op), not a sick one."""


class RefusedError(RpcError):
    """The peer actively refused the dial (RST, nothing listening) —
    known-dead right now, not merely slow. Callers fast-fail: no ping
    grace, twin demoted immediately (``transport.fastfail``)."""


# ---------------------------------------------------------------------------
# binary wire codec
# ---------------------------------------------------------------------------
#
# Frame layout:
#   b"OSSE1"                     magic + version
#   uint32 LE                    header length H
#   H bytes                      JSON header: the payload tree with every
#                                ndarray replaced by
#                                {"__nd__": i, "d": descr, "s": shape}
#   per buffer i, in order:      uint64 LE byte length + raw C-order bytes
#
# dtype/shape ride in the JSON header rather than per-buffer .npy
# headers: a 128-byte .npy preamble per array would put the
# binary/base64 ratio at 4/3 only asymptotically — raw buffers keep the
# ≥25% wire saving at every array size.

def encode_bin(obj) -> bytes:
    """Encode a JSON-like tree (dicts/lists/scalars/ndarrays) into one
    binary frame."""
    bufs: list[bytes] = []

    def strip(o):
        if isinstance(o, np.ndarray):
            a = np.ascontiguousarray(o)
            bufs.append(a.tobytes())
            return {"__nd__": len(bufs) - 1,
                    "d": np.lib.format.dtype_to_descr(a.dtype),
                    "s": list(a.shape)}
        if isinstance(o, dict):
            return {k: strip(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [strip(v) for v in o]
        if isinstance(o, np.generic):
            return o.item()
        return o

    header = json.dumps(strip(obj)).encode()
    parts = [BIN_MAGIC, struct.pack("<I", len(header)), header]
    for b in bufs:
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)


def decode_bin(data: bytes):
    """Decode one binary frame back into the payload tree (ndarrays are
    writable copies)."""
    if data[:len(BIN_MAGIC)] != BIN_MAGIC:
        raise ValueError("bad transport frame magic")
    off = len(BIN_MAGIC)
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    header = json.loads(data[off:off + hlen])
    off += hlen
    bufs: list[bytes] = []
    view = memoryview(data)
    while off < len(data):
        (blen,) = struct.unpack_from("<Q", data, off)
        off += 8
        bufs.append(view[off:off + blen])
        off += blen

    def undescr(d):
        # JSON round-trips descr tuples as lists; np.dtype wants the
        # tuples back for structured dtypes
        if isinstance(d, str):
            return np.dtype(d)
        def tup(e):
            return tuple(tup(x) if isinstance(x, list) else x
                         for x in e)
        return np.dtype([tup(e) for e in d])

    def build(o):
        if isinstance(o, dict):
            if "__nd__" in o and isinstance(o["__nd__"], int):
                arr = np.frombuffer(bufs[o["__nd__"]],
                                    dtype=undescr(o["d"]))
                return arr.reshape(o["s"]).copy()
            return {k: build(v) for k, v in o.items()}
        if isinstance(o, list):
            return [build(v) for v in o]
        return o

    return build(header)


def to_wire_json(obj):
    """ndarray-bearing tree → pure-JSON tree for the fallback wire.

    Arrays become base64 ``.npy`` strings — byte-compatible with the
    pre-transport ``_encode_batch`` format, so an old client decoding a
    new node's JSON pull reply sees exactly the wire it always saw."""
    import base64

    if isinstance(obj, np.ndarray):
        bio = io.BytesIO()
        np.save(bio, np.ascontiguousarray(obj))
        return base64.b64encode(bio.getvalue()).decode()
    if isinstance(obj, dict):
        return {k: to_wire_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire_json(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


def as_array(v, dtype=None) -> np.ndarray:
    """Whatever the wire delivered → ndarray: already-decoded binary
    arrays pass through, base64 .npy strings load, plain JSON lists
    convert."""
    import base64

    if isinstance(v, np.ndarray):
        arr = v
    elif isinstance(v, str):
        arr = np.load(io.BytesIO(base64.b64decode(v)))
    else:
        arr = np.asarray(v)
    return arr.astype(dtype) if dtype is not None else arr


def encode_body(payload, accept_bin: bool) -> tuple[bytes, str]:
    """Serialize one RPC body per the negotiated codec: binary when the
    peer advertised it, legacy JSON otherwise."""
    if accept_bin:
        return encode_bin(payload), BIN_CONTENT_TYPE
    return (json.dumps(to_wire_json(payload)).encode(),
            "application/json")


def decode_body(data: bytes, content_type: str):
    if (content_type or "").split(";")[0].strip() == BIN_CONTENT_TYPE:
        return decode_bin(data)
    return json.loads(data or b"{}")


# ---------------------------------------------------------------------------
# pooled + hedged transport
# ---------------------------------------------------------------------------

class _PeerState:
    """Per-address pool + health signals."""

    __slots__ = ("idle", "ewma", "lock")

    def __init__(self):
        self.idle: list[http.client.HTTPConnection] = []
        #: route → RTT EWMA seconds (the pickBestHost load signal and
        #: the hedge-delay input)
        self.ewma: dict[str, float] = {}
        self.lock = make_lock("transport.peer")


class Transport:
    """Keep-alive connection pool + hedged request fan-out.

    One instance per process (see :data:`g_transport`); every cluster
    RPC — client reads/writes, node-to-node heal pulls, pings — flows
    through :meth:`request` so pooling, codec negotiation and the
    ``transport.*`` stats cover the whole plane.
    """

    def __init__(self, binary: bool = True):
        #: advertise the binary codec on requests (off = JSON-only
        #: client, the "old client" half of the mixed-version matrix)
        self.binary = binary
        self._peers: dict[str, _PeerState] = {}
        self._lock = make_lock("transport.peers")
        #: optional hook ``fn(addr, gen)`` fed every ``X-OSSE-Gen``
        #: reply header — nodes stamp their Rdb version on every reply
        #: so the caller's cache plane observes generation moves even on
        #: replies whose body carries no "gen" field (pings, reads)
        self.gen_observer: Callable[[str, int], None] | None = None

    # --- pool -------------------------------------------------------------

    def _peer(self, addr: str) -> _PeerState:
        with self._lock:
            st = self._peers.get(addr)
            if st is None:
                st = self._peers[addr] = _PeerState()
            return st

    def _checkout(self, addr: str, timeout: float
                  ) -> tuple[http.client.HTTPConnection, bool]:
        st = self._peer(addr)
        with st.lock:
            conn = st.idle.pop() if st.idle else None
        if conn is not None:
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            conn.timeout = timeout
            g_stats.count("transport.conn_reuse")
            return conn, True
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=timeout)
        g_stats.count("transport.conn_dial")
        return conn, False

    def _checkin(self, addr: str, conn: http.client.HTTPConnection
                 ) -> None:
        st = self._peer(addr)
        with st.lock:
            if len(st.idle) < POOL_MAX_IDLE:
                st.idle.append(conn)
                return
        conn.close()

    def _discard(self, conn: http.client.HTTPConnection) -> None:
        try:
            conn.close()
        except Exception as exc:  # noqa: BLE001 — already-dead socket
            log.debug("discarding connection failed: %s", exc)

    def close(self) -> None:
        with self._lock:
            peers = list(self._peers.values())
        for st in peers:
            with st.lock:
                idle, st.idle = st.idle, []
            for c in idle:
                self._discard(c)

    # --- health signals ---------------------------------------------------

    def ewma_s(self, addr: str, route: str) -> float:
        st = self._peer(addr)
        with st.lock:
            return st.ewma.get(route, 0.0)

    def _observe(self, addr: str, route: str, dt: float) -> None:
        st = self._peer(addr)
        with st.lock:
            prev = st.ewma.get(route)
            st.ewma[route] = dt if prev is None else 0.8 * prev + 0.2 * dt
            now = st.ewma[route]
        g_stats.record_ms(f"transport.rtt.{addr}", 1000.0 * dt)
        g_stats.gauge(f"transport.ewma_ms.{addr}{route}", 1000.0 * now)

    def penalize(self, addr: str, route: str, dt: float) -> None:
        """Bump a peer's load signal without a completed request — the
        hedge fired because this peer sat on the request, or a read
        failed while the host still answers pings. Keeps a wedged twin
        from staying 'fastest' forever just because its EWMA never gets
        a slow sample (the abandoned request never reports)."""
        st = self._peer(addr)
        with st.lock:
            st.ewma[route] = st.ewma.get(route, 0.0) + dt

    def hedge_delay_s(self, addr: str, route: str) -> float:
        return min(max(2.0 * self.ewma_s(addr, route), HEDGE_FLOOR_S),
                   HEDGE_CAP_S)

    def stats(self) -> dict:
        """Point-in-time pool/EWMA snapshot (the /admin/transport body;
        counters and histograms live in g_stats)."""
        out = {}
        with self._lock:
            items = list(self._peers.items())
        for addr, st in items:
            with st.lock:
                out[addr] = {
                    "idle_conns": len(st.idle),
                    "ewma_ms": {route: 1000.0 * v
                                for route, v in st.ewma.items()},
                }
        return out

    # --- single request ---------------------------------------------------

    def request(self, addr: str, path: str, payload: dict,
                timeout: float, niceness: int = 0,
                span: "trace_mod.Span | None" = None) -> dict:
        """One RPC over a pooled connection.

        A send/recv failure on a REUSED socket retries once on a fresh
        dial (the peer closed an idle keep-alive socket under us — the
        request never reached it). Fresh-dial failures and timeouts
        propagate as :class:`RpcError`: a timed-out request may have
        executed, so only idempotent layers above (hedging, the Msg1
        retry queue) decide about re-sending.

        Request bodies are ALWAYS JSON — an old node would reject a
        binary body outright. Only the REPLY codec is negotiated: the
        ``Accept`` header advertises binary, and a node that doesn't
        understand it simply answers JSON.

        Tracing: inside a sampled trace the RPC gets a child span
        (``rpc/...``) and the ``X-OSSE-Trace`` header; the node ships
        its subtree back under ``"_trace"``, grafted here. ``span``
        lets :meth:`hedged` pass pre-made per-attempt spans across its
        launch threads (contextvars don't follow threads)."""
        sp = span if span is not None else \
            trace_mod.begin(path.lstrip("/"), addr=addr)
        try:
            out = self._request_inner(addr, path, payload, timeout,
                                      niceness, sp)
        except Exception as e:  # noqa: BLE001
            if sp is not None:
                sp.tag(error=repr(e))
            raise
        finally:
            if sp is not None:
                sp.finish()
        if sp is not None and isinstance(out, dict):
            sub = out.pop("_trace", None)
            if sub is not None:
                sp.graft(sub)
        return out

    def _request_inner(self, addr, path, payload, timeout, niceness,
                       sp) -> dict:
        body = json.dumps(to_wire_json(payload)).encode()
        headers = {"Content-Type": "application/json",
                   "X-Niceness": str(niceness)}
        if self.binary:
            headers["Accept"] = BIN_CONTENT_TYPE
        if sp is not None:
            headers[trace_mod.TRACE_HEADER] = trace_mod.header_for(sp)
        dl = deadline_mod.current()
        if dl is not None:
            # budget, not an absolute clock — wall clocks don't agree
            # across hosts (the node rebuilds a local Deadline from it)
            headers[deadline_mod.DEADLINE_HEADER] = dl.header_value()
        tier = priority_mod.current_tier()
        if tier is not None:
            # the front door's priority verdict rides every scatter
            # leg, so node planes honor the tier too (crawlbot work
            # yields inside each host, not just at the coordinator)
            headers[priority_mod.PRIORITY_HEADER] = tier
        tenant = priority_mod.current_tenant()
        if tenant is not None:
            # ...and its quota verdict: a node's gate bills the leg to
            # the same tenant ledger the coordinator admitted against
            headers[priority_mod.TENANT_HEADER] = tenant
        t0 = time.monotonic()
        for attempt in (0, 1):
            conn, reused = self._checkout(addr, timeout)
            try:
                if chaos_mod.g_chaos.enabled:
                    chaos_mod.g_chaos.leg_fault(addr, path, timeout)
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except _RETRY_ERRORS as e:
                self._discard(conn)
                if reused and attempt == 0:
                    # stale keep-alive socket — transparent reconnect
                    g_stats.count("transport.conn_retry")
                    continue
                g_stats.count("transport.error")
                raise RpcError(f"{addr}{path}: {e!r}") from e
            except Exception as e:  # noqa: BLE001 — timeout, refused, DNS
                self._discard(conn)
                g_stats.count("transport.error")
                if isinstance(e, ConnectionRefusedError):
                    # dead-peer fast-fail: demote the twin's load signal
                    # NOW instead of letting a refused dial wait out the
                    # EWMA clamp, and raise typed so the layer above can
                    # skip the ping grace a merely-slow host gets
                    g_stats.count("transport.fastfail")
                    self.penalize(addr, path, 1.0)
                    raise RefusedError(f"{addr}{path}: {e!r}") from e
                raise RpcError(f"{addr}{path}: {e!r}") from e
            if resp.will_close:
                self._discard(conn)
            else:
                self._checkin(addr, conn)
            if resp.status != 200:
                g_stats.count("transport.error")
                try:
                    err = decode_body(
                        data, resp.headers.get("Content-Type", ""))
                except Exception:  # noqa: BLE001
                    err = {}
                raise RpcError(
                    f"{addr}{path}: HTTP {resp.status} "
                    f"{err.get('error', '')}".strip())
            self._observe(addr, path, time.monotonic() - t0)
            g_stats.count("transport.rpc")
            obs = self.gen_observer
            if obs is not None:
                gen_hdr = resp.headers.get(GEN_HEADER)
                if gen_hdr is not None:
                    try:
                        obs(addr, int(gen_hdr))
                    except Exception as exc:  # noqa: BLE001 — obs only
                        g_stats.count("transport.gen_observer_error")
                        log.warning("gen observer failed: %s", exc)
            return decode_body(data,
                               resp.headers.get("Content-Type", ""))
        raise AssertionError("unreachable")

    def probe(self, addr: str, path: str = "/rpc/ping",
              timeout: float = 1.5) -> dict | None:
        """One quiet liveness probe: the reply dict when the peer
        answers ``ok``, else ``None`` — never raises. Readiness polls
        (a fleet child mid-boot) and heartbeats call this in a loop;
        the normal error/fast-fail counters still move underneath, so
        a flapping peer stays visible in the stats plane."""
        try:
            out = self.request(addr, path, {}, timeout=timeout)
        except Exception:  # noqa: BLE001 — an absent peer is a None
            return None
        return out if out.get("ok") else None

    def broadcast(self, addrs: list[str], path: str, payload: dict,
                  timeout: float, niceness: int = 1
                  ) -> dict[str, dict | None]:
        """The same request to EVERY address concurrently — the scrape
        shape, not the race shape: no hedging, no winner, each peer's
        answer (or ``None`` on failure) keyed by address. Pooled
        connections are reused per-peer like any other RPC; background
        niceness by default so a fleet scrape never contends with
        query traffic."""
        out: dict[str, dict | None] = {}
        lock = threading.Lock()

        def one(addr: str) -> None:
            try:
                res = self.request(addr, path, payload, timeout,
                                   niceness=niceness)
            except Exception:  # noqa: BLE001 — absent peer is a None
                res = None
            with lock:
                out[addr] = res

        ts = [threads.spawn(f"scrape-{a}", one, a) for a in addrs]
        for t in ts:
            t.join(timeout + 1.0)
        with lock:
            return {a: out.get(a) for a in addrs}

    # --- hedged fan-out ---------------------------------------------------

    def hedged(self, addrs: list[str], path: str, payload: dict,
               timeout: float, niceness: int = 0,
               is_ok=None, span_parent=None
               ) -> tuple[dict | None, int, list]:
        """The same request raced across twins, tail-latency style.

        ``addrs[0]`` (caller pre-sorts fastest-live-first) launches
        immediately; each further twin launches either the moment the
        previous attempt FAILS, or after that twin's hedge delay while
        it is still in flight (``hedge_fired``). First acceptable
        answer wins (``hedge_won`` when a hedge launch beat the
        primary); losers are abandoned — their threads finish into the
        void and only their EWMA penalty remains.

        Returns ``(result, winner_index, failures)`` where failures is
        ``[(index, exception), ...]`` for attempts that COMPLETED
        badly — a still-wedged in-flight twin is not in it (slow is not
        dead; liveness stays with the heartbeat prober)."""
        if is_ok is None:
            is_ok = lambda o: bool(o.get("ok")) or "total" in o
        parent = span_parent if span_parent is not None else \
            trace_mod.current_span()
        dl = deadline_mod.current()
        tier = priority_mod.current_tier()
        tenant = priority_mod.current_tenant()
        deadline = deadline_mod.Deadline.after(timeout)
        if dl is not None and dl.at < deadline.at:
            deadline = dl  # the query budget runs out first
        cv = threading.Condition()
        #: per attempt: None = in flight, ("ok", out) or ("err", e)
        state: list = [None] * len(addrs)
        launched = [False] * len(addrs)
        launch_t = [0.0] * len(addrs)
        hedge_launch = [False] * len(addrs)
        spans: list = [None] * len(addrs)

        def run(i: int) -> None:
            try:
                # span= only when tracing: tests monkeypatch request()
                # with the plain 5-arg signature
                kw = {} if spans[i] is None else {"span": spans[i]}
                # launch threads start with empty contextvars: re-bind
                # the caller's deadline, tier AND tenant so all three
                # ride the wire
                with deadline_mod.bind(dl), \
                        priority_mod.bind_tier(tier), \
                        priority_mod.bind_tenant(tenant):
                    out = self.request(addrs[i], path, payload,
                                       timeout=timeout,
                                       niceness=niceness, **kw)
                res = ("ok", out) if is_ok(out) else \
                    ("err", NotOkError(f"{addrs[i]}{path}: not ok"))
            except Exception as e:  # noqa: BLE001
                res = ("err", e)
            with cv:
                state[i] = res
                cv.notify_all()

        def launch(i: int, hedge: bool) -> None:
            launched[i] = True
            launch_t[i] = time.monotonic()
            hedge_launch[i] = hedge
            if hedge:
                g_stats.count("transport.hedge_fired")
            if parent is not None:
                spans[i] = parent.child(path.lstrip("/"),
                                        addr=addrs[i], hedge=hedge)
            threads.spawn(f"hedge-{path.rsplit('/', 1)[-1]}-{i}",
                          run, i)

        launch(0, hedge=False)
        winner, result = -1, None
        with cv:
            while True:
                done = [i for i in range(len(addrs))
                        if state[i] is not None]
                ok = [i for i in done if state[i][0] == "ok"]
                if ok:
                    winner = ok[0]
                    result = state[winner][1]
                    break
                in_flight = [i for i in range(len(addrs))
                             if launched[i] and state[i] is None]
                next_i = next((i for i in range(len(addrs))
                               if not launched[i]), None)
                now = time.monotonic()
                if next_i is None:
                    if not in_flight or deadline.expired():
                        break  # every attempt failed (or clock ran out)
                    cv.wait(min(deadline.remaining(), 0.5))
                    continue
                if not in_flight:
                    # previous attempt(s) failed outright — immediate
                    # failover, no hedge delay (Multicast reroute)
                    launch(next_i, hedge=False)
                    continue
                # hedge timing keys off the most recent in-flight
                # launch: the delay is how long we give that attempt
                # past its own expected completion (2×EWMA) before
                # buying the next insurance request — anchoring on an
                # older attempt would cascade every remaining twin at
                # once the moment the first one dawdles
                anchor = max(in_flight, key=lambda i: launch_t[i])
                fire_at = launch_t[anchor] + self.hedge_delay_s(
                    addrs[anchor], path)
                if now >= fire_at:
                    # dawdling past the hedge delay: penalize the load
                    # signal by the time sat on it (the abandoned
                    # request will never report a latency sample)
                    for i in in_flight:
                        self.penalize(addrs[i], path, now - launch_t[i])
                    launch(next_i, hedge=True)
                    continue
                cv.wait(min(fire_at - now,
                            max(deadline.remaining(), 0.0)))
        if winner >= 0 and hedge_launch[winner]:
            g_stats.count("transport.hedge_won")
        if winner >= 0 and spans[winner] is not None:
            spans[winner].tag(won=True,
                              hedge_won=bool(hedge_launch[winner]))
        failures = [(i, state[i][1]) for i in range(len(addrs))
                    if state[i] is not None and state[i][0] == "err"]
        return result, winner, failures


#: process-wide transport (the UdpServer singleton role)
g_transport = Transport()
