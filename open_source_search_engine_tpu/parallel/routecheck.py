"""Shared kernel-route exercise corpus + parity comparators.

Used by BOTH the driver's multichip dryrun (``__graft_entry__``) and
``tests/test_mesh_routes.py`` so the corpus shape, the route
expectations and the tie-run parity semantics cannot drift apart.

The corpus is shaped so that, with the dense/cube thresholds scaled
down (``OSSE_DENSE_MIN_DF=8`` / ``OSSE_CUBE_MIN_DF=4``), specific
queries deterministically take each kernel route:

* ``zeta`` (rare) → two-phase F1 with a bounded driver;
* ``alpha`` (everywhere, single term) → F1 whose κ ladder escalates
  (matches cluster in the low selection blocks);
* ``alpha beta`` (everywhere, multi term) → direct-cube FD;
* ``boxes dogs`` → the conjugates box/boxe + the present bigram give
  the group 3 variants, quota 2 each — NOT quarter-aligned — so the
  cube run disqualifies the direct kernel → generic assembling F2.
"""

from __future__ import annotations

ROUTE_QUERIES = {
    "zeta": "f1",
    "alpha": "f1",
    "alpha beta": "fd",
    "boxes dogs": "f2",
}

#: env values that scale dense/cube row thresholds to tiny shards
ROUTE_ENV = {"OSSE_DENSE_MIN_DF": "8", "OSSE_CUBE_MIN_DF": "4"}


def route_docs(n: int, host_prefix: str = "mesh"):
    """The n-doc route-exercise corpus (distinct registrable domains —
    a single domain would both collapse under Msg51 site clustering
    and take the PQR per-domain geometric demotion, which stamps
    rank-dependent scores and breaks tie comparison)."""
    out = []
    for i in range(n):
        extra = ["boxes dogs box boxe"]
        if i % 2 == 0:
            extra.append("gamma")
        if i % 13 == 0:
            extra.append("zeta")
        body = f"alpha beta {' '.join(extra)} token{i} words here."
        out.append((f"http://{host_prefix}{i % 23}.test/doc{i}",
                    f"<html><head><title>Doc {i} alpha</title></head>"
                    f"<body><p>{body}</p></body></html>"))
    return out


def assert_tie_run_parity(r_a, r_b, label: str = "") -> None:
    """Exact score-sequence equality + docid SET equality per complete
    equal-score run. Tie order inside a run is legitimately
    selection-dependent (different kernels pick different members of a
    tie first), and a run cut by the k boundary may hold a different
    tie subset — only complete runs compare."""
    assert r_a.total_matches == r_b.total_matches, (
        f"{label}: total_matches {r_a.total_matches} != "
        f"{r_b.total_matches}")
    sa = [x.score for x in r_a.results]
    sb = [y.score for y in r_b.results]
    assert sa == sb, f"{label}: score lists disagree"
    ids_a = [x.docid for x in r_a.results]
    ids_b = [y.docid for y in r_b.results]
    i, n = 0, len(sa)
    while i < n:
        j = i
        while j < n and sa[j] == sa[i]:
            j += 1
        if j < n or r_a.total_matches <= n:
            assert set(ids_a[i:j]) == set(ids_b[i:j]), (
                f"{label}: tie run [{i},{j}) disagrees")
        i = j


def route_hits(indexes, fn):
    """Run ``fn()`` and return the per-route query-count delta summed
    over ``indexes``."""
    before = {k: sum(di.route_counts[k] for di in indexes)
              for k in ("f1", "fd", "f2")}
    out = fn()
    hits = {k: sum(di.route_counts[k] for di in indexes) - before[k]
            for k in before}
    return out, hits
