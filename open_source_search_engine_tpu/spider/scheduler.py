"""Spider scheduler — url frontier with filters, priorities, politeness.

Reference: the Spider control plane (``Spider.{h,cpp}``, SURVEY §2.6):
SpiderRequests live in **spiderdb** keyed by (firstIP, urlhash) so one
host owns all of an IP's urls; a waiting tree + per-IP politeness waits
feed **doledb**, the per-priority ready queue drained by SpiderLoop
(``SpiderLoop::spiderDoledUrls`` ``Spider.cpp:6758``); per-collection
**url filter rules** map url patterns → priority / frequency / maxhops
(Collectiondb url filter rows). Duplicate suppression via prior
SpiderReplies.

Host-side redesign: one scheduler object per node holding (a) `seen`
(urlhash set = spiderdb replies), (b) per-host ready times (the per-IP
hammer/politeness map of Msg13), (c) a priority heap (doledb). The
distributed version shards this by firstIP exactly like the reference —
the ShardedCollection routes whole-document adds; url routing rides the
same HostMap.
"""

from __future__ import annotations

import heapq
import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path

from ..utils import ghash
from ..utils.url import normalize


@dataclass
class UrlFilterRule:
    """One url-filter row (reference per-coll url filter table)."""

    pattern: str                  # substring or regex:... on the full url
    priority: int = 0             # higher = sooner
    max_hops: int | None = None   # override crawl depth
    allow: bool = True            # False = never spider
    delay_s: float = 0.25         # per-host politeness for matching urls
    _re: re.Pattern | None = None

    def matches(self, url: str) -> bool:
        if self.pattern == "*":
            return True
        if self.pattern.startswith("regex:"):
            if self._re is None:
                self._re = re.compile(self.pattern[6:])
            return bool(self._re.search(url))
        return self.pattern in url


DEFAULT_FILTERS = [UrlFilterRule("*", priority=0)]


@dataclass(order=True)
class _Doled:
    sort_key: tuple
    url: str = field(compare=False)
    hopcount: int = field(compare=False)
    priority: int = field(compare=False)
    first_ip: str = field(compare=False, default="")


@dataclass
class SpiderRequest:
    url: str
    hopcount: int = 0
    priority: int = 0
    added: float = 0.0
    first_ip: str = ""


class SpiderScheduler:
    """Frontier + politeness + dedup (spiderdb/doledb/waiting-tree)."""

    def __init__(self, filters: list[UrlFilterRule] | None = None,
                 max_hops: int = 3, same_host_only: bool = False,
                 banned=None, resolver=None):
        self.filters = filters or list(DEFAULT_FILTERS)
        self.max_hops = max_hops
        self.same_host_only = same_host_only
        #: optional url → bool hook, normally Tagdb.is_banned — banned
        #: sites never enter the frontier (the reference's urlfilters
        #: consult tagdb's manualban before doling)
        self.banned = banned
        #: host → first-IP (the reference keys EVERYTHING by firstIP,
        #: Spider.h:99-108); injectable for tests/offline crawls
        self.resolver = resolver
        self.seen: set[int] = set()          # urlhash48 (spider replies)
        self.heap: list[_Doled] = []         # doledb
        #: per-IP politeness + in-flight locks: two hosts behind one IP
        #: share a window, and an IP with a fetch IN FLIGHT never doles
        #: again until mark_done releases it — the doledb-lock (0x12)
        #: role, lock-free because one scheduler owns each IP
        self.ip_ready_at: dict[str, float] = {}
        self.ip_delay: dict[str, float] = {}
        self.ip_inflight: set[str] = set()
        self.roots: set[str] = set()         # seed hosts for same_host_only
        self.n_added = 0
        self.n_doled = 0

    def _ip_of(self, host: str) -> str:
        from ..utils import ipresolve
        if self.resolver is not None:
            return self.resolver(host)
        return ipresolve.first_ip(host)

    # --- adds (spiderdb writes) ---

    def add_url(self, url: str, hopcount: int = 0,
                _ip: str | None = None) -> bool:
        """Queue a url if filters allow and it hasn't been seen
        (``SpiderRequest`` add → waiting tree). ``_ip`` short-circuits
        resolution when the caller already knows the first-IP (durable
        reloads replay stored IPs)."""
        try:
            u = normalize(url)
        except Exception:
            return False
        if u.scheme not in ("http", "https"):
            return False
        h = ghash.hash64(u.full)
        if h in self.seen:
            return False
        rule = self._rule_for(u.full)
        if rule is None or not rule.allow:
            return False
        if self.banned is not None and self.banned(u.full):
            return False
        cap = rule.max_hops if rule.max_hops is not None else self.max_hops
        if hopcount > cap:
            return False
        if self.same_host_only and self.roots and u.host not in self.roots:
            return False
        if hopcount == 0:
            self.roots.add(u.host)
        self.seen.add(h)
        ip = _ip if _ip is not None else self._ip_of(u.host)
        self.last_added_ip = ip
        self.ip_delay.setdefault(ip, rule.delay_s)
        # lower sort key pops first: (-priority, hopcount, arrival)
        self.n_added += 1
        heapq.heappush(self.heap, _Doled(
            sort_key=(-rule.priority, hopcount, self.n_added),
            url=u.full, hopcount=hopcount, priority=rule.priority,
            first_ip=ip))
        return True

    def _rule_for(self, url: str) -> UrlFilterRule | None:
        for r in self.filters:
            if r.matches(url):
                return r
        return None

    # --- doling (doledb reads) ---

    def next_batch(self, n: int, now: float | None = None
                   ) -> list[SpiderRequest]:
        """Pop up to n urls whose FIRST-IPs are past their politeness
        window and not in flight (SpiderLoop::spiderDoledUrls + the
        per-IP wait tree; in-flight exclusion is the doledb-lock role —
        an IP is never fetched concurrently, even across hosts)."""
        now = time.monotonic() if now is None else now
        out: list[SpiderRequest] = []
        requeue: list[_Doled] = []
        batch_ips: set[str] = set()
        while self.heap and len(out) < n:
            d = heapq.heappop(self.heap)
            ip = d.first_ip or self._ip_of(normalize(d.url).host)
            if (ip in self.ip_inflight or ip in batch_ips
                    or self.ip_ready_at.get(ip, 0.0) > now):
                requeue.append(d)
                continue
            batch_ips.add(ip)
            self.ip_inflight.add(ip)
            self.n_doled += 1
            out.append(SpiderRequest(url=d.url, hopcount=d.hopcount,
                                     priority=d.priority, added=now,
                                     first_ip=ip))
        for d in requeue:
            heapq.heappush(self.heap, d)
        return out

    def release(self, url: str, now: float | None = None,
                first_ip: str | None = None) -> None:
        """Fetch attempt finished (any outcome): release the IP's
        in-flight lock and start its politeness window FROM COMPLETION
        (the reference waits spiderDelay from the reply, not the dole).

        ``first_ip`` should be the IP the request was DOLED under
        (SpiderRequest.first_ip): re-resolving here could return a
        different IP after a TTL lapse and leave the original
        in-flight entry locked forever."""
        now = time.monotonic() if now is None else now
        ip = first_ip
        if not ip:
            try:
                ip = self._ip_of(normalize(url).host)
            except Exception:
                return
        self.ip_inflight.discard(ip)
        self.ip_ready_at[ip] = now + self.ip_delay.get(ip, 0.25)

    def __len__(self) -> int:
        return len(self.heap)

    @property
    def exhausted(self) -> bool:
        return not self.heap

    # --- persistence (spiderdb -saved.dat + addsinprogress journal) ---

    def save_to(self, path: str | Path) -> None:
        """Persist frontier + seen set so a restart resumes the crawl
        (the reference persists spiderdb's tree and replays
        ``addsinprogress.dat``, ``Msg4.cpp:115``)."""
        Path(path).write_text(json.dumps({
            "seen": list(self.seen),
            "heap": [[list(d.sort_key), d.url, d.hopcount, d.priority]
                     for d in self.heap],
            "roots": sorted(self.roots),
            "n_added": self.n_added,
            "n_doled": self.n_doled,
        }))

    def load_from(self, path: str | Path) -> bool:
        p = Path(path)
        if not p.exists():
            return False
        state = json.loads(p.read_text())
        self.seen = set(state["seen"])
        self.heap = [_Doled(sort_key=tuple(k), url=u, hopcount=h,
                            priority=pr)
                     for k, u, h, pr in state["heap"]]
        heapq.heapify(self.heap)
        self.roots = set(state["roots"])
        self.n_added = state["n_added"]
        self.n_doled = state["n_doled"]
        return True
