"""Spiderdb — the crawl frontier as a real Rdb, durable and sharded.

Reference: ``Spider.h:388,468`` — SpiderRequests and SpiderReplies live
in **spiderdb**, keyed by (firstIP, urlhash) so ONE shard owns all of an
IP's urls (IP-hash sharding, ``Hostdb.cpp:~2526``); doledb is the
derived ready-queue view (``Spider.h:982``). The round-2 verdict's
words: "a crawl at reference scale cannot live in a Python heap".

Ours: a 16-byte key — ``n1 = iphash32<<32 | urlhash_hi32`` (the hash
of the url host's RESOLVED first-IP, ``utils.ipresolve`` — all of an
IP's urls colocate, so politeness and sharding are IP-granular exactly
like the reference), ``n0 = urlhash_lo31<<2 | type<<1 | delbit`` — with
a JSON payload for requests that also records the resolved IP (reloads
never re-resolve). Two record types at the
same (host, url): REQUEST (the frontier entry, written when a url is
queued) and REPLY (written when the fetch completed — the dedup
witness). The surviving frontier = requests without a reply, computed
by one columnar pass over the merged Rdb at load.

Durability: every record rides the Rdb (memtable + runs + ``saved/``
checkpoint); :meth:`DurableSpiderScheduler.checkpoint` persists after
each crawl batch, so a kill -9 loses at most the in-flight batch —
those urls re-dole on restart (fetch-twice, never lost), exactly the
reference's addsinprogress replay semantics (``Msg4.cpp:115``).

Sharding: :func:`shard_of_url` routes by the same host hash embedded in
the key, so a node cluster splits the frontier like the reference
splits spiderdb by firstIP — each node doles only its own hosts,
politeness stays correct cluster-wide with no locks (the reference
needs doledb lock messages 0x12 because any host may dole any IP;
host-ownership makes them unnecessary).
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from ..index import rdblite
from ..utils import ghash
from ..utils.url import normalize
from .scheduler import SpiderScheduler, UrlFilterRule

KEY_DTYPE = np.dtype([("n0", "<u8"), ("n1", "<u8")], align=False)

TYPE_REQUEST = 0
TYPE_REPLY = 1


def _iphash32(ip: str) -> int:
    return ghash.hash64(ip) & 0xFFFFFFFF


def first_ip_of(url: str, resolver=None) -> str:
    from ..utils import ipresolve
    host = normalize(url).host
    return resolver(host) if resolver is not None \
        else ipresolve.first_ip(host)


def shard_of_url(url: str, n_shards: int, resolver=None) -> int:
    """Owning shard for a url's frontier entry — FIRST-IP routed, the
    reference's firstIP sharding (Hostdb.cpp:~2526): one shard owns an
    IP's whole queue, so per-IP politeness needs no cluster locks."""
    return int(ghash.hash64_array(
        np.asarray([_iphash32(first_ip_of(url, resolver))], np.uint64))[0]
        % np.uint64(n_shards))


def urlhash63(url_full: str) -> int:
    """63-bit url identity carried losslessly by the key (and used for
    the seen-set so restart dedup matches exactly)."""
    return ghash.hash64(url_full) >> 1


def pack_key(url: str, rec_type: int, first_ip: str | None = None,
             resolver=None) -> np.ndarray:
    u = normalize(url)
    uh = urlhash63(u.full)
    ip = first_ip if first_ip is not None \
        else first_ip_of(url, resolver)
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64((_iphash32(ip) << 32) | (uh >> 31))
    k["n0"] = np.uint64(((uh & 0x7FFFFFFF) << 2)
                        | ((rec_type & 1) << 1) | 1)
    return k


def unpack_keys(keys: np.ndarray):
    return {
        "iphash": (keys["n1"] >> np.uint64(32)).astype(np.uint64),
        "urlhash": (((keys["n1"] & np.uint64(0xFFFFFFFF))
                     << np.uint64(31))
                    | ((keys["n0"] >> np.uint64(2))
                       & np.uint64(0x7FFFFFFF))),
        "type": ((keys["n0"] >> np.uint64(1)) & np.uint64(1)),
    }


class SpiderDb:
    """The frontier Rdb: requests + replies, one columnar load pass.

    Every write ALSO appends to an ``addsinprogress.jsonl`` journal
    (fsync'd), replayed into the memtable on open and truncated when a
    dump makes it redundant — O(1) durability per record instead of
    rewriting the memtable checkpoint per crawl batch (the reference's
    ``addsinprogress.dat``, ``Msg4.cpp:115``)."""

    def __init__(self, directory: str | Path):
        # journal=False: spiderdb keeps its own semantic jsonl journal
        # below — the generic Rdb WAL would double-write every record
        self.rdb = rdblite.Rdb("spiderdb", directory, KEY_DTYPE,
                               has_data=True, journal=False)
        self._journal_path = self.rdb.dir / "addsinprogress.jsonl"
        self._replay_journal()
        self._journal = open(self._journal_path, "a",  # noqa: SIM115
                             encoding="utf-8")

    def _replay_journal(self) -> None:
        if not self._journal_path.exists():
            return
        for line in self._journal_path.read_text(
                encoding="utf-8").splitlines():
            try:
                rec = json.loads(line)
                if rec["t"] == TYPE_REPLY:
                    self.add_reply(rec["u"], first_ip=rec.get("ip"),
                                   _journal=False)
                else:
                    self.add_request(rec["u"], rec.get("h", 0),
                                     rec.get("p", 0), rec.get("s", 0),
                                     first_ip=rec.get("ip"),
                                     _journal=False)
            except Exception:  # noqa: BLE001 — torn tail line
                continue

    def _journal_write(self, rec: dict) -> None:
        import os
        self._journal.write(json.dumps(rec) + "\n")
        self._journal.flush()
        os.fsync(self._journal.fileno())

    def add_request(self, url: str, hopcount: int, priority: int,
                    seq: int, first_ip: str | None = None,
                    _journal: bool = True) -> None:
        if _journal:
            self._journal_write({"t": TYPE_REQUEST, "u": url,
                                 "h": hopcount, "p": priority, "s": seq,
                                 "ip": first_ip})
        payload = json.dumps({"u": url, "h": hopcount, "p": priority,
                              "s": seq, "ip": first_ip}).encode()
        self.rdb.add(pack_key(url, TYPE_REQUEST,
                              first_ip=first_ip).reshape(1), [payload])

    def add_reply(self, url: str, first_ip: str | None = None,
                  _journal: bool = True) -> None:
        if _journal:
            self._journal_write({"t": TYPE_REPLY, "u": url,
                                 "ip": first_ip})
        self.rdb.add(pack_key(url, TYPE_REPLY,
                              first_ip=first_ip).reshape(1), [b"{}"])

    def load(self):
        """One merged scan → (pending requests, seen urlhashes).

        Pending = requests with no reply for the same (host, url) —
        the reference's dedup-by-prior-SpiderReply."""
        batch = self.rdb.get_all()
        if not len(batch):
            return [], set()
        f = unpack_keys(batch.keys)
        is_req = f["type"] == TYPE_REQUEST
        replied = set(f["urlhash"][~is_req].tolist())
        seen = set(f["urlhash"].tolist())
        pending = []
        for i in np.nonzero(is_req)[0]:
            if int(f["urlhash"][i]) in replied:
                continue
            try:
                rec = json.loads(batch.payload(int(i)))
                pending.append(rec)
            except Exception:  # noqa: BLE001 — torn record
                continue
        return pending, seen

    def checkpoint(self) -> None:
        """Bound journal + memtable growth: once the memtable is big
        enough, dump it to a run and truncate the journal (the dumped
        records are durable without it). Per-record durability comes
        from the journal itself, not from rewriting state here."""
        if self.rdb.mem.nbytes > self.rdb.max_memtable_bytes // 4 \
                or self._journal.tell() > (8 << 20):
            self.rdb.dump()
            self._journal.seek(0)
            self._journal.truncate()


class DurableSpiderScheduler(SpiderScheduler):
    """SpiderScheduler whose frontier state lives in spiderdb.

    Same doling/politeness/filters as the in-RAM scheduler; every
    accepted url writes a REQUEST record, every completed fetch writes
    a REPLY, and construction replays the Rdb so a restart resumes with
    the exact surviving frontier."""

    def __init__(self, directory: str | Path,
                 filters: list[UrlFilterRule] | None = None,
                 max_hops: int = 3, same_host_only: bool = False,
                 banned=None, resolver=None):
        super().__init__(filters=filters, max_hops=max_hops,
                         same_host_only=same_host_only, banned=banned,
                         resolver=resolver)
        self.db = SpiderDb(directory)
        pending, seen = self.db.load()
        #: url identities already in spiderdb (63-bit key hash — the
        #: base class's in-RAM seen-set uses a different hash width)
        self._seen63 = {int(x) for x in seen}
        # replay in original arrival order so priorities/tiebreaks
        # hold; stored IPs replay verbatim (no re-resolution)
        for rec in sorted(pending, key=lambda r: r.get("s", 0)):
            super().add_url(rec["u"], hopcount=rec.get("h", 0),
                            _ip=rec.get("ip"))

    def add_url(self, url: str, hopcount: int = 0,
                _ip: str | None = None) -> bool:
        try:
            uh = urlhash63(normalize(url).full)
        except Exception:
            return False
        if uh in self._seen63:
            return False
        ok = super().add_url(url, hopcount=hopcount, _ip=_ip)
        if ok:
            self._seen63.add(uh)
            self.db.add_request(url, hopcount, 0, self.n_added,
                                first_ip=self.last_added_ip)
        return ok

    def mark_done(self, url: str, first_ip: str | None = None) -> None:
        """The SpiderReply write: this url never re-doles."""
        self.db.add_reply(url, first_ip=first_ip)

    def checkpoint(self) -> None:
        self.db.checkpoint()

    def save(self) -> None:  # Process-savable
        self.db.checkpoint()
