"""Linkdb — the link graph store: site quality AND inlink anchor text.

Reference: ``Linkdb.{h,cpp}`` — inlink records keyed by linkee site/url
hash (``Linkdb.h:166``), harvested at index time and aggregated by Msg25
into LinkInfo (``Linkdb.h:424``): the distinct-linker-site count
("good inlinks") drives site quality via ``getSiteRank(sni)``
(``Linkdb.cpp:7110`` step table, :func:`site_rank`), and the inlink
*text* is hashed into the linkee's posdb postings at
``HASHGROUP_INLINKTEXT`` with the linker's siterank riding the
wordspamrank slot (``XmlDoc::hashIncomingLinkText``,
``XmlDoc.cpp:28957`` hashAll; weights ``Posdb.cpp:1105,1136``) — the
reference's strongest ranking signal.

Keys: (linkee site hash 32 | linkee url hash 32) in n1, (linker site
hash 32 | linker url hash 31 | delbit) in n0 — sorted by linkee site
then linkee url, so both the site-level inlink count and the url-level
anchor harvest are single range reads. Payload: the anchor text + the
linker's siterank at link time (JSON).
"""

from __future__ import annotations

import json

import numpy as np

from ..index import rdblite
from ..utils import ghash

KEY_DTYPE = np.dtype([("n0", "<u8"), ("n1", "<u8")], align=False)

#: cap on harvested anchors per linkee (reference caps LinkInfo inlinks;
#: MAX_LINKERS-style bound keeps the posting count per doc sane)
MAX_INLINKS = 128


def _h32(s: str) -> int:
    return ghash.hash64(s) & 0xFFFFFFFF


def pack_key(linkee_site: str, linkee_url: str, linker_site: str,
             linker_url: str, delbit: int = 1) -> np.ndarray:
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64((_h32(linkee_site) << 32) | _h32(linkee_url))
    k["n0"] = np.uint64((_h32(linker_site) << 32)
                        | ((ghash.hash64(linker_url) & 0x7FFFFFFF) << 1)
                        | (delbit & 1))
    return k


def shard_of_keys(keys: np.ndarray, num_shards: int) -> np.ndarray:
    """Owning shard per record from the key's linkee sitehash32 —
    MUST agree with HostMap.shard_of_site so Rebalance can re-route
    records without the site string (Rebalance.h:13 rescans raw keys)."""
    site32 = (keys["n1"] >> np.uint64(32)).astype(np.uint64)
    return (ghash.hash64_array(site32)
            % np.uint64(num_shards)).astype(np.int64)


def _range(n1_lo: int, n1_hi: int) -> tuple[np.ndarray, np.ndarray]:
    lo = np.zeros((), dtype=KEY_DTYPE)
    lo["n1"] = np.uint64(n1_lo)
    hi = np.zeros((), dtype=KEY_DTYPE)
    hi["n1"] = np.uint64(n1_hi)
    hi["n0"] = np.uint64(0xFFFFFFFFFFFFFFFF)
    return lo, hi


class Linkdb:
    """Per-node link graph database (an Rdb instance like the others)."""

    def __init__(self, directory):
        self.rdb = rdblite.Rdb("linkdb", directory, KEY_DTYPE,
                               has_data=True)

    def add_link(self, linkee_site: str, linker_site: str,
                 linker_url: str, linkee_url: str = "",
                 anchor_text: str = "", linker_siterank: int = 0) -> None:
        """Record one (linking page → linked page) edge with its anchor
        text (the linkdb record the reference's meta list carries)."""
        if linkee_site == linker_site:
            return  # internal links don't count toward site quality
        payload = json.dumps(
            {"t": anchor_text[:512], "sr": int(linker_siterank)},
            separators=(",", ":")).encode()
        self.rdb.add(pack_key(linkee_site, linkee_url, linker_site,
                              linker_url).reshape(1), [payload])

    def site_num_inlinks(self, site: str) -> int:
        """Distinct linking sites (the 'good inlinks' count Msg25 yields)."""
        h = _h32(site)
        batch = self.rdb.get_list(*_range(h << 32, (h << 32) | 0xFFFFFFFF))
        if not len(batch):
            return 0
        linker_sites = np.asarray(batch.keys["n0"]) >> np.uint64(32)
        return int(len(np.unique(linker_sites)))

    def inlinks_for_url(self, linkee_site: str, linkee_url: str
                        ) -> list[tuple[str, int]]:
        """[(anchor text, linker siterank)] for one linkee URL, one vote
        per linking site (Msg25 dedups inlinks per site), capped at
        MAX_INLINKS — the LinkInfo harvest that feeds
        ``hashIncomingLinkText``."""
        n1 = (_h32(linkee_site) << 32) | _h32(linkee_url)
        batch = self.rdb.get_list(*_range(n1, n1))
        out: list[tuple[str, int]] = []
        seen_sites: set[int] = set()
        for i in range(len(batch)):
            linker_site = int(batch.keys["n0"][i] >> np.uint64(32))
            if linker_site in seen_sites:
                continue
            try:
                rec = json.loads(batch.payload(i))
            except (ValueError, UnicodeDecodeError):
                continue
            if not rec.get("t"):
                continue  # empty anchors contribute nothing to text
            seen_sites.add(linker_site)
            out.append((rec["t"], int(rec.get("sr", 0))))
            if len(out) >= MAX_INLINKS:
                break
        return out

    def save(self) -> None:
        self.rdb.save()


#: siteNumInlinks → siterank step table (Linkdb.cpp:7110-7128)
_SITE_RANK_STEPS = [
    (0, 0), (1, 1), (2, 2), (3, 3), (4, 4), (5, 5), (9, 6), (19, 7),
    (39, 8), (79, 9), (199, 10), (499, 11), (1999, 12), (4999, 13),
    (9999, 14),
]


def site_rank(site_num_inlinks: int) -> int:
    for cap, rank in _SITE_RANK_STEPS:
        if site_num_inlinks <= cap:
            return rank
    return 15
