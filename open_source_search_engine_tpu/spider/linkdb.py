"""Linkdb — the link graph store feeding siteNumInlinks/siterank.

Reference: ``Linkdb.{h,cpp}`` — inlink records keyed by linkee site/url
hash (``Linkdb.h:166``), harvested at index time, aggregated by Msg25
into LinkInfo whose ``m_numGoodInlinks`` drives the site quality rank via
``getSiteRank(sni)`` (``Linkdb.cpp:7110`` — a step table, reproduced in
:func:`site_rank`). Link-text itself rides into posdb as
HASHGROUP_INLINKTEXT postings during the linker's indexing.

Keys here: (linkee site hash 32, linker site hash 32, linker url hash 32)
dataless — one record per (linking page → linked site) edge; distinct
linker-site count = "good inlinks" (the reference dedups inlinks per
linking site/IP the same way).
"""

from __future__ import annotations

import numpy as np

from ..index import rdblite
from ..utils import ghash

KEY_DTYPE = np.dtype([("n0", "<u4"), ("n1", "<u8")], align=False)
# n1 = linkee_sitehash32 << 32 | linker_sitehash32 ; n0 = linkerurl31 | delbit


def pack_key(linkee_site: str, linker_site: str, linker_url: str,
             delbit: int = 1) -> np.ndarray:
    n1 = ((ghash.hash64(linkee_site) & 0xFFFFFFFF) << 32) \
        | (ghash.hash64(linker_site) & 0xFFFFFFFF)
    n0 = ((ghash.hash64(linker_url) & 0x7FFFFFFF) << 1) | (delbit & 1)
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64(n1)
    k["n0"] = np.uint32(n0)
    return k


def _site_range(linkee_site: str) -> tuple[np.ndarray, np.ndarray]:
    h = ghash.hash64(linkee_site) & 0xFFFFFFFF
    lo = np.zeros((), dtype=KEY_DTYPE)
    lo["n1"] = np.uint64(h << 32)
    hi = np.zeros((), dtype=KEY_DTYPE)
    hi["n1"] = np.uint64((h << 32) | 0xFFFFFFFF)
    hi["n0"] = np.uint32(0xFFFFFFFF)
    return lo, hi


class Linkdb:
    """Per-node link graph database (an Rdb instance like the others)."""

    def __init__(self, directory):
        self.rdb = rdblite.Rdb("linkdb", directory, KEY_DTYPE)

    def add_link(self, linkee_site: str, linker_site: str,
                 linker_url: str) -> None:
        if linkee_site == linker_site:
            return  # internal links don't count toward site quality
        self.rdb.add(pack_key(linkee_site, linker_site,
                              linker_url).reshape(1))

    def site_num_inlinks(self, site: str) -> int:
        """Distinct linking sites (the 'good inlinks' count Msg25 yields)."""
        lo, hi = _site_range(site)
        batch = self.rdb.get_list(lo, hi)
        if not len(batch):
            return 0
        linker_sites = np.asarray(batch.keys["n1"]) & np.uint64(0xFFFFFFFF)
        return int(len(np.unique(linker_sites)))

    def save(self) -> None:
        self.rdb.save()


#: siteNumInlinks → siterank step table (Linkdb.cpp:7110-7128)
_SITE_RANK_STEPS = [
    (0, 0), (1, 1), (2, 2), (3, 3), (4, 4), (5, 5), (9, 6), (19, 7),
    (39, 8), (79, 9), (199, 10), (499, 11), (1999, 12), (4999, 13),
    (9999, 14),
]


def site_rank(site_num_inlinks: int) -> int:
    for cap, rank in _SITE_RANK_STEPS:
        if site_num_inlinks <= cap:
            return rank
    return 15
