"""Build plane: crawler — scheduler, fetcher, link graph, crawl loop.

The reference's Spider/Msg13/Linkdb subsystem (SURVEY §2.6) redesigned
host-side: the scheduler owns the frontier + politeness (spiderdb/doledb),
the fetcher downloads with robots awareness (Msg13), linkdb accumulates
the link graph feeding siterank, and SpiderLoop ties them to the indexer.
"""

from .fetcher import Fetcher, FetchResult, RobotsCache
from .linkdb import Linkdb, site_rank
from .loop import CrawlStats, SpiderLoop
from .scheduler import SpiderScheduler, UrlFilterRule

__all__ = [
    "Fetcher", "FetchResult", "RobotsCache", "Linkdb", "site_rank",
    "CrawlStats", "SpiderLoop", "SpiderScheduler", "UrlFilterRule",
]
