"""Fetcher — robots-aware HTTP download service (the Msg13 equivalent).

Reference: ``Msg13.{h,cpp}`` — the "download a url" service: robots.txt
fetch + cache (``s_hammerCache`` ``Msg13.h:210``), gzip, per-IP hammer
queue (politeness lives in the scheduler here), response caching, and
``HttpServer::getDoc`` as the raw client. Proxy routing (SpiderProxy) and
DNS (``Dns.cpp`` full recursive resolver) ride the OS resolver for now —
both are isolated behind this interface.

Thread-pool blocking IO instead of the reference's callback chains: the
fetch plane is embarrassingly parallel and nowhere near the query plane's
performance envelope.
"""

from __future__ import annotations

import urllib.error
import urllib.parse
import urllib.request
import urllib.robotparser
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from ..utils.log import get_logger

log = get_logger("fetch")

USER_AGENT = "osse-tpu-bot/0.1"
MAX_DOC_BYTES = 2 << 20  # cap like the reference's maxTextDocLen


@dataclass
class FetchResult:
    url: str
    status: int            # HTTP status; 0 = network error; 999 = robots
    content: str = ""
    content_type: str = ""
    error: str = ""

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300

    @property
    def is_html(self) -> bool:
        return "html" in self.content_type or self.content_type == ""


class RobotsCache:
    """robots.txt fetch + parse cache (Msg13's robots cache)."""

    def __init__(self, fetch_fn=None):
        self._cache: dict[str, urllib.robotparser.RobotFileParser] = {}
        self._fetch_fn = fetch_fn  # injectable for tests

    def allowed(self, url: str) -> bool:
        parts = urllib.parse.urlsplit(url)
        origin = f"{parts.scheme}://{parts.netloc}"
        rp = self._cache.get(origin)
        if rp is None:
            rp = urllib.robotparser.RobotFileParser()
            try:
                raw = (self._fetch_fn(origin + "/robots.txt")
                       if self._fetch_fn else
                       _raw_get(origin + "/robots.txt"))
                rp.parse(raw.splitlines())
            except Exception:
                rp.parse([])  # unreachable robots.txt = allow all
            self._cache[origin] = rp
        return rp.can_fetch(USER_AGENT, url)


def _gunzip_capped(data: bytes) -> bytes:
    """Decompress at most MAX_DOC_BYTES of output — a gzip bomb must not
    defeat the download cap (the reference likewise bounds doc length
    after its gbuncompress)."""
    return zlib.decompressobj(wbits=47).decompress(data, MAX_DOC_BYTES)


def _raw_get(url: str, timeout: float = 10.0) -> str:
    req = urllib.request.Request(url, headers={
        "User-Agent": USER_AGENT, "Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        data = r.read(MAX_DOC_BYTES)
        if r.headers.get("Content-Encoding") == "gzip":
            data = _gunzip_capped(data)
        return data.decode(
            r.headers.get_content_charset() or "utf-8", "replace")


class Fetcher:
    """Parallel robots-aware downloader."""

    def __init__(self, n_threads: int = 8, timeout: float = 10.0,
                 respect_robots: bool = True):
        self.pool = ThreadPoolExecutor(max_workers=n_threads,
                                       thread_name_prefix="fetch")
        self.timeout = timeout
        self.respect_robots = respect_robots
        self.robots = RobotsCache()

    def fetch_one(self, url: str) -> FetchResult:
        if self.respect_robots and not self.robots.allowed(url):
            return FetchResult(url=url, status=999, error="robots.txt")
        req = urllib.request.Request(url, headers={
            "User-Agent": USER_AGENT, "Accept-Encoding": "gzip"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                data = r.read(MAX_DOC_BYTES)
                if r.headers.get("Content-Encoding") == "gzip":
                    data = _gunzip_capped(data)
                charset = r.headers.get_content_charset() or "utf-8"
                return FetchResult(
                    url=r.url, status=r.status,
                    content=data.decode(charset, "replace"),
                    content_type=r.headers.get_content_type())
        except urllib.error.HTTPError as e:
            return FetchResult(url=url, status=e.code, error=str(e))
        except Exception as e:  # noqa: BLE001 — network errors are data
            return FetchResult(url=url, status=0, error=str(e))

    def fetch_many(self, urls: list[str]) -> list[FetchResult]:
        return list(self.pool.map(self.fetch_one, urls))

    def close(self) -> None:
        self.pool.shutdown(wait=False, cancel_futures=True)
