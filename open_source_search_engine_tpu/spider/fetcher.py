"""Fetcher — robots-aware HTTP download service (the Msg13 equivalent).

Reference: ``Msg13.{h,cpp}`` — the "download a url" service: robots.txt
fetch + cache (``s_hammerCache`` ``Msg13.h:210``), gzip, per-IP hammer
queue (politeness lives in the scheduler here), response caching, and
``HttpServer::getDoc`` as the raw client. Proxy routing (SpiderProxy) and
DNS (``Dns.cpp`` full recursive resolver) ride the OS resolver for now —
both are isolated behind this interface.

Thread-pool blocking IO instead of the reference's callback chains: the
fetch plane is embarrassingly parallel and nowhere near the query plane's
performance envelope.
"""

from __future__ import annotations

import re
import urllib.error
import urllib.parse
import urllib.request
import urllib.robotparser
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from ..utils.log import get_logger

log = get_logger("fetch")

USER_AGENT = "osse-tpu-bot/0.1"
MAX_DOC_BYTES = 2 << 20  # cap like the reference's maxTextDocLen


@dataclass
class FetchResult:
    url: str
    status: int            # HTTP status; 0 = network error; 999 = robots
    content: str = ""
    content_type: str = ""
    error: str = ""
    #: undecoded body for binary document types (pdf/doc/ps) — the
    #: converter plane (build/convert.py) turns it into text
    raw: bytes = b""

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300

    @property
    def is_html(self) -> bool:
        return "html" in self.content_type or self.content_type == ""


class RobotsCache:
    """robots.txt fetch + parse cache (Msg13's robots cache), held on
    the cache plane so /admin/cache sees it and memory pressure can
    shed it (a re-fetch of robots.txt is cheap; an OOM is not)."""

    ROBOTS_TTL_S = 3600.0  # re-fetch robots.txt hourly, Msg13-style

    def __init__(self, fetch_fn=None):
        from ..cache import g_cacheplane
        self._cache = g_cacheplane.register(
            "spider.robots", ttl_s=self.ROBOTS_TTL_S, max_entries=8192,
            desc="parsed robots.txt per origin (Msg13 robots cache)")
        self._fetch_fn = fetch_fn  # injectable for tests

    def allowed(self, url: str) -> bool:
        parts = urllib.parse.urlsplit(url)
        origin = f"{parts.scheme}://{parts.netloc}"
        hit, rp = self._cache.lookup(origin)
        if not hit:
            rp = urllib.robotparser.RobotFileParser()
            try:
                raw = (self._fetch_fn(origin + "/robots.txt")
                       if self._fetch_fn else
                       _raw_get(origin + "/robots.txt"))
                rp.parse(raw.splitlines())
            except Exception:
                rp.parse([])  # unreachable robots.txt = allow all
            self._cache.put(origin, rp)
        return rp.can_fetch(USER_AGENT, url)


_META_CHARSET_RE = re.compile(
    rb"""<meta[^>]+charset\s*=\s*["']?([a-zA-Z0-9_\-]+)""",
    re.IGNORECASE)


def sniff_charset(data: bytes, declared: str | None) -> str:
    """Charset resolution (the iana_charset.cpp role): HTTP header >
    BOM > <meta charset> / http-equiv sniff over the head bytes >
    utf-8 fallback. Web-reality aliases (x-sjis, ks_c_5601-1987, …)
    map through utils.unicodenorm.CHARSET_ALIASES; names neither the
    alias table nor the codec registry know fall back to
    utf-8-with-replace at decode time."""
    from ..utils.unicodenorm import resolve_charset
    cand = declared
    if not cand:
        if data[:3] == b"\xef\xbb\xbf":
            cand = "utf-8"
        elif data[:2] in (b"\xff\xfe", b"\xfe\xff"):
            cand = "utf-16"
        else:
            m = _META_CHARSET_RE.search(data[:4096])
            if m:
                cand = m.group(1).decode("ascii", "replace")
    return resolve_charset(cand) or "utf-8"


def _gunzip_capped(data: bytes) -> bytes:
    """Decompress at most MAX_DOC_BYTES of output — a gzip bomb must not
    defeat the download cap (the reference likewise bounds doc length
    after its gbuncompress)."""
    return zlib.decompressobj(wbits=47).decompress(data, MAX_DOC_BYTES)


def _raw_get(url: str, timeout: float = 10.0) -> str:
    req = urllib.request.Request(url, headers={
        "User-Agent": USER_AGENT, "Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        data = r.read(MAX_DOC_BYTES)
        if r.headers.get("Content-Encoding") == "gzip":
            data = _gunzip_capped(data)
        return data.decode(
            r.headers.get_content_charset() or "utf-8", "replace")


class ResponseCache:
    """TTL'd url → FetchResult cache (Msg13's response cache,
    ``Msg13.h:168`` — repeated fetches of one url within the TTL serve
    from cache instead of re-hammering the site), on the cache plane:
    fetched bodies are the first thing memory pressure should drop."""

    def __init__(self, ttl_s: float = 3600.0, max_entries: int = 1024):
        from ..cache import g_cacheplane
        self._cache = g_cacheplane.register(
            "spider.responses", ttl_s=ttl_s, max_entries=max_entries,
            desc="url → FetchResult bodies (Msg13 response cache)")

    def get(self, url: str) -> FetchResult | None:
        return self._cache.get(url)

    def put(self, url: str, res: FetchResult) -> None:
        self._cache.put(url, res)


class Fetcher:
    """Parallel robots-aware downloader."""

    def __init__(self, n_threads: int = 8, timeout: float = 10.0,
                 respect_robots: bool = True,
                 cache_ttl_s: float = 3600.0,
                 proxies=None):
        self.pool = ThreadPoolExecutor(max_workers=n_threads,
                                       thread_name_prefix="fetch")
        self.timeout = timeout
        self.respect_robots = respect_robots
        self.robots = RobotsCache()
        self.cache = ResponseCache(ttl_s=cache_ttl_s) \
            if cache_ttl_s > 0 else None
        #: SpiderProxy pool (spider/proxies.py) — None/empty = direct
        self.proxies = proxies

    def fetch_one(self, url: str) -> FetchResult:
        if self.cache is not None:
            hit = self.cache.get(url)
            if hit is not None:
                return hit
        if self.respect_robots and not self.robots.allowed(url):
            return FetchResult(url=url, status=999, error="robots.txt")
        # proxy assignment per target first-IP (SpiderProxy.h:27); a
        # response that reads as a ban page rotates to the next proxy
        tries = 1
        target_ip = ""
        if self.proxies:
            from ..utils import ipresolve
            target_ip = ipresolve.first_ip(
                urllib.parse.urlsplit(url).hostname or "")
            tries = 3
        banned_all = False
        for _ in range(tries):
            proxy = self.proxies.pick(target_ip) if self.proxies \
                else None
            try:
                res = self._get(url, proxy)
            finally:
                if proxy:
                    self.proxies.release(proxy)
            if proxy and res.status == 0:
                # dead/unreachable proxy: cool the pair down exactly
                # like a ban so the sticky assignment rotates away
                self.proxies.report(proxy, target_ip, 403, "")
                banned_all = True
                continue
            if proxy and self.proxies.report(
                    proxy, target_ip, res.status, res.content):
                banned_all = True
                continue  # banned pair cooled down — next proxy
            if self.cache is not None and res.ok:
                self.cache.put(url, res)
            return res
        # every proxy try banned/failed: surface an ERROR, never the
        # ban interstitial as content (the reference treats ban pages
        # as fetch failures — indexing a captcha page poisons the doc)
        return FetchResult(url=url, status=0,
                           error="ban page or dead proxy via every "
                                 "assigned proxy"
                                 if banned_all else "proxy fetch failed")

    def _get(self, url: str, proxy: str | None) -> FetchResult:
        req = urllib.request.Request(url, headers={
            "User-Agent": USER_AGENT, "Accept-Encoding": "gzip"})
        opener = urllib.request.build_opener(
            urllib.request.ProxyHandler(
                {"http": f"http://{proxy}",
                 "https": f"http://{proxy}"})) if proxy \
            else urllib.request.build_opener()
        try:
            with opener.open(req, timeout=self.timeout) as r:
                data = r.read(MAX_DOC_BYTES)
                if r.headers.get("Content-Encoding") == "gzip":
                    data = _gunzip_capped(data)
                ctype = r.headers.get_content_type()
                from ..build.convert import is_convertible
                if is_convertible(ctype, r.url):
                    # binary document: keep bytes for the converters
                    return FetchResult(
                        url=r.url, status=r.status, raw=data,
                        content_type=ctype)
                charset = sniff_charset(
                    data, r.headers.get_content_charset())
                return FetchResult(
                    url=r.url, status=r.status,
                    content=data.decode(charset, "replace"),
                    content_type=ctype)
        except urllib.error.HTTPError as e:
            return FetchResult(url=url, status=e.code, error=str(e))
        except Exception as e:  # noqa: BLE001 — network errors are data
            return FetchResult(url=url, status=0, error=str(e))

    def fetch_many(self, urls: list[str]) -> list[FetchResult]:
        return list(self.pool.map(self.fetch_one, urls))

    def close(self) -> None:
        self.pool.shutdown(wait=False, cancel_futures=True)
