"""SpiderProxy — external crawl-proxy pool with ban detection.

Reference: ``SpiderProxy.h:27`` / ``SpiderProxy.cpp`` (msg 0x54/0x55):
host #0 keeps the proxy table, assigns a proxy per (target first-IP)
so load spreads and one website sees a stable exit, counts per-proxy
outstanding downloads, and detects BAN PAGES (``SpiderProxy.cpp:1048``
``isProxyBanPage``: captcha/forbidden markers) — a banned (proxy, IP)
pair rotates out with a backoff while other IPs keep using the proxy.

Ours is the same table, minus the UDP msg plumbing (the pool object
lives beside the fetcher; the cluster's crawl plane is per-shard, so
each node owns the pool for the IPs it crawls — the reference
centralizes only because its spider shards couldn't share state).
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field

from ..utils.log import get_logger

log = get_logger("proxy")

#: a banned (proxy, ip) pair sits out this long (the reference ages
#: ban state in its proxy table)
BAN_COOLDOWN_S = 600.0

#: ban-page markers (isProxyBanPage scans the content for captcha /
#: access-denied boilerplate; status 403/429 counts on its own)
_BAN_RE = re.compile(
    r"captcha|access denied|forbidden|unusual traffic|"
    r"blocked|are you a robot", re.IGNORECASE)
_BAN_SCAN_BYTES = 4096


def looks_banned(status: int, content: str) -> bool:
    """Does this response read as a proxy/crawler ban page?"""
    if status in (403, 429):
        return True
    if status == 200 and content and \
            _BAN_RE.search(content[:_BAN_SCAN_BYTES]) and \
            len(content) < 8192:
        # short pages shouting captcha/denied are ban interstitials;
        # long real documents may legitimately contain the words
        return True
    return False


@dataclass
class _ProxyState:
    addr: str                 # "host:port"
    outstanding: int = 0      # in-flight downloads through it
    #: target-ip → ban expiry (monotonic)
    banned_until: dict = field(default_factory=dict)


class ProxyPool:
    """Per-target-IP proxy assignment + ban rotation."""

    def __init__(self, proxies: list[str] | None = None):
        self._lock = threading.Lock()
        self._proxies = [_ProxyState(p) for p in (proxies or []) if p]

    @classmethod
    def from_conf(cls, conf) -> "ProxyPool":
        raw = getattr(conf, "spider_proxies", "") or ""
        return cls([p.strip() for p in raw.split(",") if p.strip()])

    def __bool__(self) -> bool:
        return bool(self._proxies)

    def pick(self, target_ip: str) -> str | None:
        """The proxy for this target IP: sticky by (ip-hash) so a site
        sees a stable exit, skipping banned pairs, preferring the
        least-loaded among candidates (the reference counts
        outstanding downloads per proxy)."""
        with self._lock:
            if not self._proxies:
                return None
            now = time.monotonic()
            n = len(self._proxies)
            start = hash(target_ip) % n
            order = [self._proxies[(start + i) % n] for i in range(n)]
            live = [p for p in order
                    if p.banned_until.get(target_ip, 0.0) <= now]
            if not live:
                return None  # every proxy banned for this ip: direct
            best = min(live, key=lambda p: p.outstanding)
            # sticky preference: the hash-chosen proxy wins unless it
            # is markedly more loaded than the least-loaded candidate
            chosen = live[0] if live[0].outstanding \
                <= best.outstanding + 4 else best
            chosen.outstanding += 1
            return chosen.addr

    def release(self, addr: str) -> None:
        with self._lock:
            for p in self._proxies:
                if p.addr == addr and p.outstanding > 0:
                    p.outstanding -= 1
                    return

    def report(self, addr: str, target_ip: str, status: int,
               content: str = "") -> bool:
        """Feed a response back; returns True when it read as a ban
        (the pair is cooled down and the caller should retry through
        the next proxy)."""
        banned = looks_banned(status, content)
        if banned:
            with self._lock:
                for p in self._proxies:
                    if p.addr == addr:
                        p.banned_until[target_ip] = \
                            time.monotonic() + BAN_COOLDOWN_S
                        log.info("proxy %s banned for ip %s "
                                 "(status %d)", addr, target_ip,
                                 status)
                        break
        return banned

    def status(self) -> list[dict]:
        """Admin view (the reference's proxy table page)."""
        now = time.monotonic()
        with self._lock:
            return [{
                "addr": p.addr,
                "outstanding": p.outstanding,
                "banned_ips": sum(1 for t in p.banned_until.values()
                                  if t > now),
            } for p in self._proxies]
