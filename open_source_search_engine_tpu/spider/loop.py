"""Spider loop — crawl → parse → index → harvest links, end to end.

Reference: ``SpiderLoop::spiderDoledUrls`` (``Spider.cpp:6758``) doles
ready urls to XmlDoc instances (``spiderUrl9`` ``Spider.cpp:8006``); each
``XmlDoc::indexDoc`` fetches (Msg13), parses, computes link info (Msg25 →
siteNumInlinks → siterank), writes every db via Msg4, and queues
outlinks as new SpiderRequests. Crawl rounds advance when the frontier
drains.

Here: batch-synchronous rounds — dole a batch, fetch in parallel
(threads), index serially into the collection (single-writer Rdb), add
outlinks + linkdb edges. Link-derived siterank feeds docs indexed in
*later* rounds, same as the reference's incremental siteNumInlinks.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..build import docproc
from ..utils.log import get_logger
from ..utils.url import normalize
from .fetcher import Fetcher
from .linkdb import site_rank
from .scheduler import SpiderScheduler

log = get_logger("spider")


@dataclass
class CrawlStats:
    fetched: int = 0
    indexed: int = 0
    errors: int = 0
    robots_blocked: int = 0
    links_found: int = 0
    by_status: dict = field(default_factory=dict)


class SpiderLoop:
    """Drives one collection's crawl (single node or a shard's share)."""

    def __init__(self, coll_or_sharded, scheduler: SpiderScheduler | None
                 = None, fetcher: Fetcher | None = None,
                 batch_size: int = 8):
        self.target = coll_or_sharded
        # `scheduler or ...` would discard an EMPTY scheduler (len()==0
        # makes it falsy) — a durable frontier always starts empty
        self.sched = scheduler if scheduler is not None \
            else SpiderScheduler(banned=self._tagdb_banned)
        if fetcher is None:
            # SpiderProxy pool from the collection conf (spider_proxies
            # parm) — empty pool means direct fetching
            from .proxies import ProxyPool
            conf = getattr(coll_or_sharded, "conf", None)
            pool = ProxyPool.from_conf(conf) if conf is not None \
                else None
            fetcher = Fetcher(proxies=pool if pool else None)
        self.fetcher = fetcher
        self.batch_size = batch_size
        self.stats = CrawlStats()

    def add_url(self, url: str) -> bool:
        return self.sched.add_url(url)

    def _tagdb_banned(self, url: str) -> bool:
        """Frontier ban gate (tagdb manualban, urlfilters semantics)."""
        tagdb = getattr(self.target, "tagdb", None)
        return tagdb.is_banned(url) if tagdb is not None else False

    def _site_num_inlinks(self, site: str) -> int:
        if hasattr(self.target, "site_num_inlinks"):  # ShardedCollection
            return self.target.site_num_inlinks(site)
        return self.target.linkdb.site_num_inlinks(site)

    def _index(self, url: str, content: str, is_html: bool):
        """Index one page; returns the MetaList (whose .links carries the
        outlinks from the same tokenize pass — no reparse needed). The
        indexer itself records linkdb edges + inlink-text postings."""
        site = normalize(url).site
        sr = site_rank(self._site_num_inlinks(site))
        if hasattr(self.target, "index_document"):  # ShardedCollection
            return self.target.index_document(url, content,
                                              is_html=is_html, siterank=sr)
        return docproc.index_document(self.target, url, content,
                                      is_html=is_html, siterank=sr)

    def crawl_step(self) -> int:
        """One dole-fetch-index round; returns pages indexed."""
        batch = self.sched.next_batch(self.batch_size)
        if not batch:
            return 0
        results = self.fetcher.fetch_many([r.url for r in batch])
        indexed = 0
        mark_done = getattr(self.sched, "mark_done", None)
        for req, res in zip(batch, results):
            # ALWAYS release the IP's in-flight lock — politeness
            # windows start from fetch completion (per-IP discipline)
            release = getattr(self.sched, "release", None)
            if release is not None:
                release(req.url, first_ip=req.first_ip or None)
            if mark_done is not None and not (
                    res.status == 0 or res.status == 999
                    or 500 <= res.status < 600):
                # SpiderReply write — but only for COMPLETED attempts
                # (success or permanent 4xx); network errors, 5xx, and
                # robots blocks stay unreplied so the url re-doles on a
                # later crawl (the reference schedules error retries)
                mark_done(req.url, first_ip=req.first_ip or None)
            self.stats.fetched += 1
            self.stats.by_status[res.status] = \
                self.stats.by_status.get(res.status, 0) + 1
            if res.status == 999:
                self.stats.robots_blocked += 1
                continue
            if not res.ok:
                self.stats.errors += 1
                log.debug("fetch failed %s: %s %s", req.url, res.status,
                          res.error)
                continue
            content, is_html = res.content, res.is_html
            if res.raw and not content:
                # binary document (pdf/doc/ps): converter plane
                # (XmlDoc.cpp:19206 shells to pdftohtml/antiword)
                from ..build.convert import convert_to_text
                text = convert_to_text(res.raw, res.content_type,
                                       res.url)
                if not text:
                    self.stats.errors += 1
                    log.debug("unconvertible %s (%s)", req.url,
                              res.content_type)
                    continue
                content, is_html = text, False
            try:
                ml = self._index(res.url, content, is_html)
                if ml is None:  # tagdb manualban (EDOCBANNED)
                    self.stats.errors += 1
                    continue
                indexed += 1
                self.stats.indexed += 1
            except Exception as e:  # noqa: BLE001
                self.stats.errors += 1
                log.warning("index failed %s: %s", req.url, e)
                continue
            # enqueue outlinks (edges were recorded by the indexer)
            linker = normalize(res.url)
            for href, _anchor in (ml.links if res.is_html else []):
                absu = docproc.absolutize(linker.full, href)
                if not absu:
                    continue
                self.stats.links_found += 1
                self.sched.add_url(absu, hopcount=req.hopcount + 1)
        cp = getattr(self.sched, "checkpoint", None)
        if cp is not None:
            cp()  # batch-granular durability (addsinprogress semantics)
        return indexed

    def crawl(self, max_pages: int = 100, max_steps: int | None = None
              ) -> CrawlStats:
        """Crawl until the frontier drains or max_pages are indexed."""
        import time as _time
        steps = 0
        while (self.stats.indexed < max_pages and not self.sched.exhausted):
            if max_steps is not None and steps >= max_steps:
                break
            before = self.stats.fetched
            self.crawl_step()
            steps += 1
            if self.stats.fetched == before:
                # frontier non-empty but every host inside its politeness
                # window — sleep instead of spinning the heap (the
                # reference's waiting tree blocks on a sleep callback)
                _time.sleep(0.05)
        return self.stats
