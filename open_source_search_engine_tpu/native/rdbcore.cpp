// rdbcore — native host core for the Rdb-lite storage engine.
//
// Reference: the byte-level list machinery of RdbList.cpp (merge_r /
// indexMerge_r: n-way merge of sorted key runs with newest-wins dedup and
// +/- tombstone annihilation) and the key compares of types.h
// (KEYCMP over key96/key128/key144). Re-designed, not ported: our keys are
// little-endian structured records whose field order is least-significant
// first, so one generic reversed-byte compare covers every database's key
// width (posdb 18B, titledb 12B, clusterdb 16B, linkdb 12B, ...), and the
// delbit is always bit 0 of byte 0.
//
// Build: g++ -O3 -shared -fPIC rdbcore.cpp -o librdbcore.so
// (driven by native/__init__.py; pure-numpy fallback stays in rdblite.py)

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// compare keys as little-endian integers: bytes from most-significant
// (last) down; ignores the delbit (bit 0 of byte 0) so +/- versions of
// one record compare equal (the "identity" compare of annihilation)
inline int cmp_ident(const uint8_t* a, const uint8_t* b, int ks) {
  for (int i = ks - 1; i > 0; --i) {
    if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
  }
  uint8_t a0 = a[0] & 0xFEu, b0 = b[0] & 0xFEu;
  if (a0 != b0) return a0 < b0 ? -1 : 1;
  return 0;
}

}  // namespace

extern "C" {

// N-way merge of sorted runs (oldest..newest) of fixed-size keys.
// Newest-wins on identity-equal keys; surviving tombstones (delbit==0)
// are dropped unless keep_tombstones. Returns records written to out
// (caller allocates sum(counts)*key_size bytes — the worst case).
int64_t osse_merge_runs(const uint8_t** runs, const int64_t* counts,
                        int32_t n_runs, int32_t key_size,
                        int32_t keep_tombstones, uint8_t* out) {
  std::vector<int64_t> pos(n_runs, 0);
  int64_t written = 0;
  for (;;) {
    // find the smallest head; among identity-equal heads the NEWEST run
    // (highest index) supplies the surviving record
    int best = -1;
    const uint8_t* best_key = nullptr;
    for (int r = 0; r < n_runs; ++r) {
      if (pos[r] >= counts[r]) continue;
      const uint8_t* k = runs[r] + pos[r] * key_size;
      if (best < 0 || cmp_ident(k, best_key, key_size) < 0) {
        best = r;
        best_key = k;
      }
    }
    if (best < 0) break;  // all runs exhausted
    // advance every run past records identity-equal to best_key,
    // remembering the newest version
    const uint8_t* winner = nullptr;
    for (int r = 0; r < n_runs; ++r) {
      while (pos[r] < counts[r]) {
        const uint8_t* k = runs[r] + pos[r] * key_size;
        if (cmp_ident(k, best_key, key_size) != 0) break;
        winner = k;  // runs are oldest..newest; later r overrides
        ++pos[r];
      }
    }
    const bool positive = (winner[0] & 1u) != 0;
    if (positive || keep_tombstones) {
      std::memcpy(out + written * key_size, winner, key_size);
      ++written;
    }
  }
  return written;
}

// lower(side=0)/upper(side=1) bound of probe in a sorted run, comparing
// full keys (delbit included, as the least-significant bit).
int64_t osse_searchsorted(const uint8_t* run, int64_t n, int32_t key_size,
                          const uint8_t* probe, int32_t side) {
  int64_t lo = 0, hi = n;
  while (lo < hi) {
    int64_t mid = lo + ((hi - lo) >> 1);
    const uint8_t* k = run + mid * key_size;
    int c = 0;
    for (int i = key_size - 1; i >= 0; --i) {
      if (k[i] != probe[i]) {
        c = k[i] < probe[i] ? -1 : 1;
        break;
      }
    }
    if (c < 0 || (side == 1 && c == 0)) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

}  // extern "C"
