"""Native host core — C++ storage-engine primitives behind ctypes.

The reference's host plane is C++ (SURVEY §2: "everything is C++"); ours
keeps the byte-crunching primitives native too: n-way run merge with
tombstone annihilation, key binary search, and sorted-batch dedup
(``rdbcore.cpp``). Built on demand with g++ into ``librdbcore.so``;
every caller has a vectorized-numpy fallback, so the framework works
(slower) without a toolchain.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

import numpy as np

from ..utils.log import get_logger

log = get_logger("native")

_DIR = Path(__file__).parent
_SRC = _DIR / "rdbcore.cpp"
_SO = _DIR / "librdbcore.so"
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", str(_SO)],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # noqa: BLE001 — fall back to numpy
        log.warning("native build failed (numpy fallback in use): %s", e)
        return False


def get_lib():
    """The loaded librdbcore, building it on first use; None = fallback."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            log.warning("native load failed: %s", e)
            return None
        lib.osse_merge_runs.restype = ctypes.c_int64
        lib.osse_merge_runs.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p]
        lib.osse_searchsorted.restype = ctypes.c_int64
        lib.osse_searchsorted.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32]
        _lib = lib
        log.info("librdbcore loaded")
        return _lib


def available() -> bool:
    return get_lib() is not None


def merge_runs(key_arrays: list[np.ndarray],
               keep_tombstones: bool) -> np.ndarray | None:
    """Native n-way merge of sorted structured-key arrays (oldest→newest).
    Returns merged keys, or None if the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    key_dtype = key_arrays[0].dtype
    ks = key_dtype.itemsize
    bufs = [np.ascontiguousarray(a) for a in key_arrays]
    total = sum(len(a) for a in bufs)
    out = np.empty(total, dtype=key_dtype)
    RunPtrs = ctypes.c_void_p * len(bufs)
    runs = RunPtrs(*[b.ctypes.data for b in bufs])
    counts = (ctypes.c_int64 * len(bufs))(*[len(b) for b in bufs])
    n = lib.osse_merge_runs(
        runs, counts, len(bufs), ks, int(keep_tombstones),
        out.ctypes.data)
    return out[:n].copy()


def searchsorted(sorted_keys: np.ndarray, probe: np.ndarray,
                 side: str) -> int | None:
    """Native binary search of one probe key; None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    a = np.ascontiguousarray(sorted_keys)
    p = np.ascontiguousarray(probe)
    return int(lib.osse_searchsorted(
        a.ctypes.data, len(a), a.dtype.itemsize,
        p.ctypes.data, 1 if side == "right" else 0))
