"""Native host core — C++ storage-engine primitives behind ctypes.

The reference's host plane is C++ (SURVEY §2: "everything is C++"); ours
keeps the byte-crunching primitives native too: n-way run merge with
tombstone annihilation, key binary search, and sorted-batch dedup
(``rdbcore.cpp``). Built on demand with g++ into ``librdbcore.so``;
every caller has a vectorized-numpy fallback, so the framework works
(slower) without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

from ..utils.log import get_logger

log = get_logger("native")

#: OSSE_NATIVE_SAN=1 → build/load ASan+UBSan-instrumented natives
#: instead of the optimized ones. Separate ``.san.so`` artifact names so
#: the two modes never clobber each other's build cache. The sanitizer
#: runtimes must be preloaded into the (uninstrumented) Python process —
#: ``tools/native_san_check.py`` handles the LD_PRELOAD dance.
SANITIZE = os.environ.get("OSSE_NATIVE_SAN") == "1"
_SAN_FLAGS = ["-fsanitize=address,undefined", "-fno-omit-frame-pointer",
              "-g", "-O1"]

_DIR = Path(__file__).parent
_SRC = _DIR / "rdbcore.cpp"
_SO = _DIR / ("librdbcore.san.so" if SANITIZE else "librdbcore.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _gxx_cmd(opt: str, src: Path, out: Path) -> list[str]:
    flags = _SAN_FLAGS if SANITIZE else [opt]
    return ["g++", *flags, "-shared", "-fPIC", str(src), "-o", str(out)]


def _build() -> bool:
    try:
        subprocess.run(_gxx_cmd("-O3", _SRC, _SO),
                       check=True, capture_output=True, timeout=120)
        return True
    except Exception as e:  # noqa: BLE001 — fall back to numpy
        log.warning("native build failed (numpy fallback in use): %s", e)
        return False


def get_lib():
    """The loaded librdbcore, building it on first use; None = fallback."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            log.warning("native load failed: %s", e)
            return None
        lib.osse_merge_runs.restype = ctypes.c_int64
        lib.osse_merge_runs.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p]
        lib.osse_searchsorted.restype = ctypes.c_int64
        lib.osse_searchsorted.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32]
        _lib = lib
        log.info("librdbcore loaded")
        return _lib


def available() -> bool:
    return get_lib() is not None


def merge_runs(key_arrays: list[np.ndarray],
               keep_tombstones: bool) -> np.ndarray | None:
    """Native n-way merge of sorted structured-key arrays (oldest→newest).
    Returns merged keys, or None if the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    key_dtype = key_arrays[0].dtype
    ks = key_dtype.itemsize
    bufs = [np.ascontiguousarray(a) for a in key_arrays]
    total = sum(len(a) for a in bufs)
    out = np.empty(total, dtype=key_dtype)
    RunPtrs = ctypes.c_void_p * len(bufs)
    runs = RunPtrs(*[b.ctypes.data for b in bufs])
    counts = (ctypes.c_int64 * len(bufs))(*[len(b) for b in bufs])
    n = lib.osse_merge_runs(
        runs, counts, len(bufs), ks, int(keep_tombstones),
        out.ctypes.data)
    return out[:n].copy()


def searchsorted(sorted_keys: np.ndarray, probe: np.ndarray,
                 side: str) -> int | None:
    """Native binary search of one probe key; None if lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    a = np.ascontiguousarray(sorted_keys)
    p = np.ascontiguousarray(probe)
    return int(lib.osse_searchsorted(
        a.ctypes.data, len(a), a.dtype.itemsize,
        p.ctypes.data, 1 if side == "right" else 0))


# --- doccore: native HTML tokenize + term hash + rank columns ----------

_DOC_SRC = _DIR / "doccore.cpp"
_DOC_SO = _DIR / ("libdoccore.san.so" if SANITIZE else "libdoccore.so")
_doc_lib = None
_doc_tried = False


class _OsseDoc(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int64),
        ("termid", ctypes.POINTER(ctypes.c_uint64)),
        ("wordpos", ctypes.POINTER(ctypes.c_int32)),
        ("hashgroup", ctypes.POINTER(ctypes.c_uint8)),
        ("density", ctypes.POINTER(ctypes.c_uint8)),
        ("spam", ctypes.POINTER(ctypes.c_uint8)),
        ("sentence", ctypes.POINTER(ctypes.c_int32)),
        ("sect", ctypes.POINTER(ctypes.c_uint64)),
        ("nb", ctypes.c_int64),
        ("b_termid", ctypes.POINTER(ctypes.c_uint64)),
        ("b_src", ctypes.POINTER(ctypes.c_int32)),
        # POINTER(c_char), NOT c_char_p: c_char_p field access copies
        # up to the first NUL, and string_at over the declared length
        # would then read past the truncated copy (embedded NULs occur
        # in real crawled pages)
        ("words_buf", ctypes.POINTER(ctypes.c_char)),
        ("words_len", ctypes.c_int64),
        ("text_buf", ctypes.POINTER(ctypes.c_char)),
        ("text_len", ctypes.c_int64),
        ("title_buf", ctypes.POINTER(ctypes.c_char)),
        ("title_len", ctypes.c_int64),
        ("desc_buf", ctypes.POINTER(ctypes.c_char)),
        ("desc_len", ctypes.c_int64),
        ("date_buf", ctypes.POINTER(ctypes.c_char)),
        ("date_len", ctypes.c_int64),
        ("links_buf", ctypes.POINTER(ctypes.c_char)),
        ("links_len", ctypes.c_int64),
        ("nsect", ctypes.c_int64),
        ("sect_hash", ctypes.POINTER(ctypes.c_uint64)),
        ("sect_words", ctypes.POINTER(ctypes.c_int32)),
        ("sect_buf", ctypes.POINTER(ctypes.c_char)),
        ("sect_len", ctypes.c_int64),
        ("fallback", ctypes.c_int32),
    ]


def _build_doccore() -> bool:
    try:
        subprocess.run(_gxx_cmd("-O2", _DOC_SRC, _DOC_SO),
                       check=True, capture_output=True, timeout=180)
        return True
    except Exception as e:  # noqa: BLE001 — fall back to Python
        log.warning("doccore build failed (python tokenizer in use): %s",
                    e)
        return False


def get_doccore():
    """The loaded libdoccore, building on first use; None = fallback."""
    global _doc_lib, _doc_tried
    with _lock:
        if _doc_lib is not None or _doc_tried:
            return _doc_lib
        _doc_tried = True
        if not _DOC_SO.exists() or \
                _DOC_SO.stat().st_mtime < _DOC_SRC.stat().st_mtime:
            if not _build_doccore():
                return None
        try:
            lib = ctypes.CDLL(str(_DOC_SO))
        except OSError as e:
            log.warning("doccore load failed: %s", e)
            return None
        lib.osse_tokenize.restype = ctypes.POINTER(_OsseDoc)
        lib.osse_tokenize.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int32]
        lib.osse_doc_free.argtypes = [ctypes.POINTER(_OsseDoc)]
        lib.osse_hash64.restype = ctypes.c_uint64
        lib.osse_hash64.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_uint64]
        _doc_lib = lib
        log.info("libdoccore loaded")
        return _doc_lib


class NativeDocCols:
    """Columnar product of one native tokenize call (numpy copies; the
    C arena is freed before returning)."""

    __slots__ = ("termid", "wordpos", "hashgroup", "density", "spam",
                 "sentence", "sect", "b_termid", "b_src", "words",
                 "text", "title", "desc", "date", "links", "sect_hash",
                 "sect_words", "sect_content")


def _arr(ptr, n, dtype):
    """Copy n elements out of a ctypes pointer — np.frombuffer over the
    raw address (ctypeslib.as_array's per-call type synthesis measured
    ~4× slower at these sizes)."""
    if n == 0:
        return np.empty(0, dtype)
    src = np.dtype(ptr._type_)  # numpy understands ctypes scalar types
    buf = ctypes.string_at(ptr, n * src.itemsize)
    a = np.frombuffer(buf, dtype=src, count=n)
    return a.astype(dtype) if a.dtype != dtype else a.copy()


def tokenize_native(content: str, url: str | None,
                    is_html: bool) -> "NativeDocCols | None":
    """Native tokenize+hash+rank; None when the lib is unavailable."""
    lib = get_doccore()
    if lib is None:
        return None
    cb = content.encode("utf-8", "replace")
    ub = url.encode("utf-8", "replace") if url else b""
    dp = lib.osse_tokenize(cb, len(cb), ub, len(ub), int(is_html))
    try:
        d = dp.contents
        if d.fallback:
            # exotic HTML entity outside the native table: the Python
            # tokenizer (full HTML5 charref set) must own this doc so
            # both paths stay bit-identical
            return None
        out = NativeDocCols()
        n = int(d.n)
        out.termid = _arr(d.termid, n, np.uint64)
        out.wordpos = _arr(d.wordpos, n, np.int64)
        out.hashgroup = _arr(d.hashgroup, n, np.uint64)
        out.density = _arr(d.density, n, np.uint64)
        out.spam = _arr(d.spam, n, np.uint64)
        out.sentence = _arr(d.sentence, n, np.int64)
        out.sect = _arr(d.sect, n, np.uint64)
        nb = int(d.nb)
        out.b_termid = _arr(d.b_termid, nb, np.uint64)
        out.b_src = _arr(d.b_src, nb, np.int64)
        wb = ctypes.string_at(d.words_buf, d.words_len)
        out.words = wb.decode("utf-8", "replace").split("\n") if wb \
            else []
        out.text = ctypes.string_at(d.text_buf, d.text_len).decode(
            "utf-8", "replace")
        out.title = ctypes.string_at(d.title_buf, d.title_len).decode(
            "utf-8", "replace")
        out.desc = ctypes.string_at(d.desc_buf, d.desc_len).decode(
            "utf-8", "replace")
        out.date = ctypes.string_at(d.date_buf, d.date_len).decode(
            "utf-8", "replace")
        lb = ctypes.string_at(d.links_buf, d.links_len).decode(
            "utf-8", "replace")
        out.links = []
        if lb:
            for rec in lb.split("\x1e"):
                href, _, anchor = rec.partition("\x1f")
                out.links.append((href, anchor))
        ns = int(d.nsect)
        out.sect_hash = _arr(d.sect_hash, ns, np.uint64)
        out.sect_words = _arr(d.sect_words, ns, np.int64)
        sb = ctypes.string_at(d.sect_buf, d.sect_len).decode(
            "utf-8", "replace")
        out.sect_content = sb.split("\x1e") if sb else []
        return out
    finally:
        lib.osse_doc_free(dp)


def hash64_native(data: bytes, seed: int = 0) -> int | None:
    lib = get_doccore()
    if lib is None:
        return None
    return int(lib.osse_hash64(data, len(data), seed))
