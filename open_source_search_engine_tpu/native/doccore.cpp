// Native document core: HTML tokenize + term hash + rank columns.
//
// The reference's build plane is C++ (XmlDoc::hashAll, XmlDoc.cpp:28957;
// Xml.cpp/Words.cpp/Pos.cpp tokenization) and SURVEY §2 commits this
// framework to a native host build plane too. This file reproduces the
// semantics of build/tokenizer.py (_HtmlTok) and the hashing/rank layer
// of build/docproc.py — bit-exactly for ASCII documents, and with a
// documented approximation of Python's \w and str.lower() for non-ASCII
// codepoints (common Latin/Greek/Cyrillic/CJK ranges are classified;
// exotic scripts fall back to "not a word char").
//
// Everything returns as columnar arrays in one malloc'd arena so the
// Python side does a handful of ctypes reads + one vectorized key pack
// per document instead of 10^5 interpreter ops.
//
// Build: g++ -O2 -shared -fPIC doccore.cpp -o libdoccore.so

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <strings.h>
#include <string>
#include <vector>
#include <unordered_map>

// ---------------------------------------------------------------- hashing
// ghash.hash64 for short payloads: FNV-1a 64 + murmur finalizer.
static inline uint64_t fnv_avalanche(const char* data, size_t len,
                                     uint64_t seed) {
    uint64_t h = 0xCBF29CE484222325ULL ^ seed;
    for (size_t i = 0; i < len; i++) {
        h ^= (uint8_t)data[i];
        h *= 0x100000001B3ULL;
    }
    h ^= h >> 33;
    h *= 0xFF51AFD7ED558CCDULL;
    h ^= h >> 33;
    h *= 0xC4CEB9FE1A85EC53ULL;
    h ^= h >> 33;
    return h;
}

static const uint64_t TERMID_MASK = (1ULL << 48) - 1;

// ---------------------------------------------------------------- unicode
// Decode one UTF-8 codepoint at p (len remaining); returns codepoint and
// advances *adv. Invalid bytes decode as themselves (latin-1 style).
static inline uint32_t u8_decode(const char* p, size_t len, int* adv) {
    uint8_t b0 = (uint8_t)p[0];
    if (b0 < 0x80) { *adv = 1; return b0; }
    if ((b0 >> 5) == 0x6 && len >= 2 && ((uint8_t)p[1] >> 6) == 0x2) {
        *adv = 2; return ((b0 & 0x1F) << 6) | ((uint8_t)p[1] & 0x3F);
    }
    if ((b0 >> 4) == 0xE && len >= 3 && ((uint8_t)p[1] >> 6) == 0x2 &&
        ((uint8_t)p[2] >> 6) == 0x2) {
        *adv = 3;
        return ((b0 & 0x0F) << 12) | (((uint8_t)p[1] & 0x3F) << 6) |
               ((uint8_t)p[2] & 0x3F);
    }
    if ((b0 >> 3) == 0x1E && len >= 4 && ((uint8_t)p[1] >> 6) == 0x2 &&
        ((uint8_t)p[2] >> 6) == 0x2 && ((uint8_t)p[3] >> 6) == 0x2) {
        *adv = 4;
        return ((b0 & 0x07) << 18) | (((uint8_t)p[1] & 0x3F) << 12) |
               (((uint8_t)p[2] & 0x3F) << 6) | ((uint8_t)p[3] & 0x3F);
    }
    *adv = 1; return b0;  // stray byte
}

static inline int u8_encode(uint32_t cp, char* out) {
    if (cp < 0x80) { out[0] = (char)cp; return 1; }
    if (cp < 0x800) {
        out[0] = (char)(0xC0 | (cp >> 6));
        out[1] = (char)(0x80 | (cp & 0x3F)); return 2;
    }
    if (cp < 0x10000) {
        out[0] = (char)(0xE0 | (cp >> 12));
        out[1] = (char)(0x80 | ((cp >> 6) & 0x3F));
        out[2] = (char)(0x80 | (cp & 0x3F)); return 3;
    }
    out[0] = (char)(0xF0 | (cp >> 18));
    out[1] = (char)(0x80 | ((cp >> 12) & 0x3F));
    out[2] = (char)(0x80 | ((cp >> 6) & 0x3F));
    out[3] = (char)(0x80 | (cp & 0x3F)); return 4;
}

// Python \w approximation (see file header).
static inline bool is_word_cp(uint32_t cp) {
    if (cp < 0x80)
        return (cp >= '0' && cp <= '9') || (cp >= 'a' && cp <= 'z') ||
               (cp >= 'A' && cp <= 'Z') || cp == '_';
    if (cp >= 0xC0 && cp <= 0x24F) return cp != 0xD7 && cp != 0xF7;
    if (cp >= 0x386 && cp <= 0x3FF) return cp != 0x387;
    if (cp >= 0x400 && cp <= 0x4FF) return true;   // Cyrillic
    if (cp >= 0x531 && cp <= 0x586) return true;   // Armenian
    if (cp >= 0x5D0 && cp <= 0x5EA) return true;   // Hebrew
    if ((cp >= 0x620 && cp <= 0x64A) || (cp >= 0x660 && cp <= 0x669) ||
        (cp >= 0x66E && cp <= 0x6FF)) return true; // Arabic
    if (cp >= 0x900 && cp <= 0x97F) return cp != 0x964 && cp != 0x965;
    if (cp >= 0x3040 && cp <= 0x30FF) return true; // kana
    if (cp >= 0x4E00 && cp <= 0x9FFF) return true; // CJK
    if (cp >= 0xAC00 && cp <= 0xD7A3) return true; // Hangul
    return false;
}

// str.lower() approximation for the ranges above.
static inline uint32_t lower_cp(uint32_t cp) {
    if (cp < 0x80) return (cp >= 'A' && cp <= 'Z') ? cp + 0x20 : cp;
    if (cp >= 0xC0 && cp <= 0xDE && cp != 0xD7) return cp + 0x20;
    if (cp >= 0x100 && cp <= 0x137) return (cp & 1) ? cp : cp + 1;
    if (cp >= 0x139 && cp <= 0x148) return (cp & 1) ? cp + 1 : cp;
    if (cp >= 0x14A && cp <= 0x177) return (cp & 1) ? cp : cp + 1;
    if (cp >= 0x179 && cp <= 0x17E) return (cp & 1) ? cp + 1 : cp;
    if (cp >= 0x391 && cp <= 0x3A9 && cp != 0x3A2) return cp + 0x20;
    if (cp >= 0x410 && cp <= 0x42F) return cp + 0x20;
    if (cp >= 0x400 && cp <= 0x40F) return cp + 0x50;
    return cp;
}

// ------------------------------------------------------------- constants
// Mirrors of tokenizer.py / posdb.py values.
enum {
    HG_BODY = 0, HG_TITLE = 1, HG_HEADING = 2, HG_INLIST = 3,
    HG_INMETATAG = 4, HG_INLINKTEXT = 5, HG_INTAG = 6,
    HG_INURL = 9, HG_INMENU = 10,
};
static const int SENT_GAP = 2;
static const int BLOCK_GAP = 4;
static const int32_t MAXWORDPOS = 0x3FFFF;
static const int MAXDENSITYRANK = 31;
static const int MAXWORDSPAMRANK = 15;

static bool in_set(const char* tag, const char* const* set) {
    for (int i = 0; set[i]; i++)
        if (!strcmp(tag, set[i])) return true;
    return false;
}

static const char* const HEADING_TAGS[] = {"h1","h2","h3","h4","h5","h6",0};
static const char* const SKIP_TAGS[] = {"script","style","noscript",
                                        "template","svg",0};
static const char* const LIST_TAGS[] = {"li","dd","dt",0};
static const char* const MENU_TAGS[] = {"nav","menu",0};
static const char* const BLOCK_TAGS[] = {
    "p","div","br","tr","td","table","ul","ol","section","article",
    "header","footer","blockquote","pre","h1","h2","h3","h4","h5","h6",
    "li","title",0};
static const char* const SECTION_TAGS[] = {
    "div","section","article","header","footer","aside","nav","menu",
    "table","ul","ol","dl","form","blockquote","p","li","tr","td","th",
    "dd","dt","pre","h1","h2","h3","h4","h5","h6",0};

// ------------------------------------------------------------ result ABI
extern "C" {
typedef struct {
    // word-token columns (doc words + url words)
    int64_t n;
    uint64_t* termid;
    int32_t*  wordpos;
    uint8_t*  hashgroup;
    uint8_t*  density;
    uint8_t*  spam;
    int32_t*  sentence;
    uint64_t* sect;      // per-token section path hash (0 = none)
    // bigram tokens: termid + index of the first word
    int64_t nb;
    uint64_t* b_termid;
    int32_t*  b_src;
    // lowercased words, '\n'-joined (for speller/langid)
    char* words_buf;   int64_t words_len;
    // visible text (whitespace-normalized), title, meta desc/date
    char* text_buf;    int64_t text_len;
    char* title_buf;   int64_t title_len;
    char* desc_buf;    int64_t desc_len;
    char* date_buf;    int64_t date_len;
    // links: href '\x1f' anchor, records '\x1e'-joined
    char* links_buf;   int64_t links_len;
    // sections: path hash + '\x1e'-joined per-section word content
    int64_t nsect;
    uint64_t* sect_hash;
    int32_t*  sect_words;   // word count per section
    char* sect_buf;    int64_t sect_len;
    // 1 = exotic entity seen: caller must rerun via the Python path
    // (full HTML5 charref table) to keep bit-identical output
    int32_t fallback;
} osse_doc;
}

// ------------------------------------------------------------- tokenizer
namespace {

struct Tok {
    std::string word;      // lowercased
    int32_t pos;
    uint8_t hg;
    int32_t sent;
    uint64_t sect;
};

struct SectFrame {
    std::string tag;
    uint64_t hash;
    std::unordered_map<std::string, int> counters;
};

struct Parser {
    std::vector<Tok> toks;
    std::string title, desc, date, text;
    std::vector<std::pair<std::string, std::string>> links;
    int32_t pos = 0;
    int32_t sent = 0;
    int skip_depth = 0, title_depth = 0, heading_depth = 0;
    int list_depth = 0, menu_depth = 0;
    bool fallback = false;  // exotic entity seen → punt to Python path
    bool in_anchor = false;
    std::string anchor_href, anchor_words;
    std::vector<SectFrame> sect_stack;
    std::unordered_map<std::string, int> root_ordinals;

    uint64_t section_id() const {
        if (sect_stack.empty()) return 0;
        size_t i = sect_stack.size() > 1 ? 1 : 0;
        return sect_stack[i].hash;
    }

    void sect_push(const std::string& tag) {
        uint64_t parent = 0;
        std::unordered_map<std::string, int>* counters = &root_ordinals;
        if (!sect_stack.empty()) {
            parent = sect_stack.back().hash;
            counters = &sect_stack.back().counters;
        }
        int ordinal = (*counters)[tag]++;
        // _sect_hash: hash64(f"{parent_hash}:{tag}:{ordinal}")
        char buf[96];
        int n = snprintf(buf, sizeof buf, "%llu:%s:%d",
                         (unsigned long long)parent, tag.c_str(), ordinal);
        sect_stack.push_back({tag, fnv_avalanche(buf, (size_t)n, 0), {}});
    }

    void sect_pop(const std::string& tag) {
        for (int i = (int)sect_stack.size() - 1; i >= 0; i--)
            if (sect_stack[i].tag == tag) {
                sect_stack.resize(i);
                return;
            }
    }

    // word scan of a byte range: callback per word (lowercased utf-8)
    template <class F>
    void scan_words(const char* s, size_t len, F&& emit) {
        std::string w;
        size_t i = 0;
        while (i < len) {
            int adv;
            uint32_t cp = u8_decode(s + i, len - i, &adv);
            if (is_word_cp(cp)) {
                char enc[4];
                int m = u8_encode(lower_cp(cp), enc);
                w.append(enc, m);
            } else if (!w.empty()) {
                emit(w);
                w.clear();
            }
            i += adv;
        }
        if (!w.empty()) emit(w);
    }

    // _emit_words: sentence-split + word scan with Pos.cpp advance
    void emit_words(const char* s, size_t len, uint8_t hg) {
        uint64_t sid = section_id();
        size_t i = 0;
        bool last_chunk_done = false;
        while (!last_chunk_done) {
            // chunk = up to the next run of [.!?;:]
            size_t j = i;
            while (j < len) {
                char c = s[j];
                if (c == '.' || c == '!' || c == '?' || c == ';' ||
                    c == ':')
                    break;
                j++;
            }
            // words of the chunk
            bool any = false;
            int32_t p = pos;
            scan_words(s + i, j - i, [&](const std::string& w) {
                toks.push_back({w, p < MAXWORDPOS ? p : MAXWORDPOS, hg,
                                sent, sid});
                p++;
                any = true;
            });
            if (any) pos = p;
            if (j >= len) last_chunk_done = true;
            else {
                // swallow the punctuation run
                while (j < len && (s[j] == '.' || s[j] == '!' ||
                                   s[j] == '?' || s[j] == ';' ||
                                   s[j] == ':'))
                    j++;
                if (j >= len) {
                    // trailing punctuation: one final empty chunk
                    pos += SENT_GAP;
                    sent += 1;
                    last_chunk_done = true;
                }
            }
            if (!last_chunk_done) {
                pos += SENT_GAP;
                sent += 1;
            }
            i = j;
        }
        // python: always adds the gap per chunk then undoes the last —
        // net effect reproduced above (the final chunk adds no gap)
    }

    void handle_data(const char* s, size_t len) {
        if (skip_depth) return;
        if (title_depth) {
            title.append(s, len);
            emit_words(s, len, HG_TITLE);
            return;
        }
        uint8_t hg = HG_BODY;
        if (heading_depth) hg = HG_HEADING;
        else if (list_depth) hg = HG_INLIST;
        else if (menu_depth) hg = HG_INMENU;
        if (in_anchor) {
            scan_words(s, len, [&](const std::string& w) {
                if (!anchor_words.empty()) anchor_words += ' ';
                anchor_words += w;
            });
        }
        if (!text.empty()) text += ' ';
        text.append(s, len);
        emit_words(s, len, hg);
    }
};

// lowercase ASCII in place (tag/attr names)
static void ascii_lower(std::string& s) {
    for (char& c : s)
        if (c >= 'A' && c <= 'Z') c += 0x20;
}

// ---- HTML entity table ------------------------------------------------
// Python's convert_charrefs resolves the FULL HTML5 table; we carry the
// Latin-1 named set + the common typographic symbols and set a
// ``fallback`` flag on anything else — the caller then reruns the doc
// through the Python tokenizer, preserving the bit-identical contract
// instead of silently diverging.
struct Ent { const char* name; uint32_t cp; };
static const Ent ENTS[] = {
    {"amp",'&'},{"AMP",'&'},{"lt",'<'},{"LT",'<'},{"gt",'>'},
    {"GT",'>'},{"quot",'"'},{"QUOT",'"'},{"apos",'\''},
    {"nbsp",0xA0},{"iexcl",0xA1},{"cent",0xA2},{"pound",0xA3},
    {"curren",0xA4},{"yen",0xA5},{"brvbar",0xA6},{"sect",0xA7},
    {"uml",0xA8},{"copy",0xA9},{"COPY",0xA9},{"ordf",0xAA},
    {"laquo",0xAB},{"not",0xAC},{"shy",0xAD},{"reg",0xAE},
    {"REG",0xAE},{"macr",0xAF},{"deg",0xB0},{"plusmn",0xB1},
    {"sup2",0xB2},{"sup3",0xB3},{"acute",0xB4},{"micro",0xB5},
    {"para",0xB6},{"middot",0xB7},{"cedil",0xB8},{"sup1",0xB9},
    {"ordm",0xBA},{"raquo",0xBB},{"frac14",0xBC},{"frac12",0xBD},
    {"frac34",0xBE},{"iquest",0xBF},
    {"Agrave",0xC0},{"Aacute",0xC1},{"Acirc",0xC2},{"Atilde",0xC3},
    {"Auml",0xC4},{"Aring",0xC5},{"AElig",0xC6},{"Ccedil",0xC7},
    {"Egrave",0xC8},{"Eacute",0xC9},{"Ecirc",0xCA},{"Euml",0xCB},
    {"Igrave",0xCC},{"Iacute",0xCD},{"Icirc",0xCE},{"Iuml",0xCF},
    {"ETH",0xD0},{"Ntilde",0xD1},{"Ograve",0xD2},{"Oacute",0xD3},
    {"Ocirc",0xD4},{"Otilde",0xD5},{"Ouml",0xD6},{"times",0xD7},
    {"Oslash",0xD8},{"Ugrave",0xD9},{"Uacute",0xDA},{"Ucirc",0xDB},
    {"Uuml",0xDC},{"Yacute",0xDD},{"THORN",0xDE},{"szlig",0xDF},
    {"agrave",0xE0},{"aacute",0xE1},{"acirc",0xE2},{"atilde",0xE3},
    {"auml",0xE4},{"aring",0xE5},{"aelig",0xE6},{"ccedil",0xE7},
    {"egrave",0xE8},{"eacute",0xE9},{"ecirc",0xEA},{"euml",0xEB},
    {"igrave",0xEC},{"iacute",0xED},{"icirc",0xEE},{"iuml",0xEF},
    {"eth",0xF0},{"ntilde",0xF1},{"ograve",0xF2},{"oacute",0xF3},
    {"ocirc",0xF4},{"otilde",0xF5},{"ouml",0xF6},{"divide",0xF7},
    {"oslash",0xF8},{"ugrave",0xF9},{"uacute",0xFA},{"ucirc",0xFB},
    {"uuml",0xFC},{"yacute",0xFD},{"thorn",0xFE},{"yuml",0xFF},
    {"hellip",0x2026},{"mdash",0x2014},{"ndash",0x2013},
    {"lsquo",0x2018},{"rsquo",0x2019},{"ldquo",0x201C},
    {"rdquo",0x201D},{"bull",0x2022},{"trade",0x2122},
    {"euro",0x20AC},{"dagger",0x2020},{"Dagger",0x2021},
    {"permil",0x2030},{"prime",0x2032},{"Prime",0x2033},
    {"minus",0x2212},
    {0, 0},
};

static uint32_t ent_lookup(const std::string& name) {
    for (int k = 0; ENTS[k].name; k++)
        if (name == ENTS[k].name) return ENTS[k].cp;
    return 0;
}

// decode HTML entities (html.parser convert_charrefs). Sets *fallback
// when an entity outside our table (or a no-semicolon form Python's
// html.unescape would resolve) is seen — the caller must rerun the doc
// through the Python path for exact parity.
static std::string decode_entities(const char* s, size_t len,
                                   bool* fallback) {
    std::string out;
    out.reserve(len);
    size_t i = 0;
    while (i < len) {
        if (s[i] != '&') { out += s[i++]; continue; }
        size_t j = i + 1, end = len < i + 34 ? len : i + 34;
        bool numeric = j < end && s[j] == '#';
        while (j < end && (isalnum((uint8_t)s[j]) ||
                           (numeric && j == i + 1)))
            j++;
        bool has_semi = j < len && s[j] == ';';
        std::string ent(s + i + 1, j - i - 1);
        if (ent.empty()) { out += s[i++]; continue; }
        if (ent[0] == '#') {
            // python resolves numeric charrefs even without ';'
            bool hex =
                ent.size() > 1 && (ent[1] == 'x' || ent[1] == 'X');
            // digit-less forms (&#;, &#x;) are not charrefs — literal,
            // exactly what html.unescape's charref regex requires
            bool has_digits = hex
                ? (ent.size() > 2 && isxdigit((uint8_t)ent[2]))
                : (ent.size() > 1 && isdigit((uint8_t)ent[1]));
            if (!has_digits) { out += s[i++]; continue; }
            uint32_t cp = hex
                ? (uint32_t)strtoul(ent.c_str() + 2, 0, 16)
                : (uint32_t)strtoul(ent.c_str() + 1, 0, 10);
            // html.unescape maps NUL, surrogate code points and
            // beyond-Unicode values to U+FFFD
            if (cp == 0 || (cp >= 0xD800 && cp <= 0xDFFF)
                    || cp > 0x10FFFF)
                cp = 0xFFFD;
            char enc[4];
            out.append(enc, u8_encode(cp, enc));
            i = has_semi ? j + 1 : j;
            continue;
        }
        if (has_semi) {
            uint32_t cp = ent_lookup(ent);
            if (cp) {
                char enc[4];
                out.append(enc, u8_encode(cp, enc));
                i = j + 1;
                continue;
            }
            *fallback = true;  // unknown named entity with ';'
            out += s[i++];
            continue;
        }
        // no semicolon: html.unescape still resolves legacy names by
        // LONGEST PREFIX — any known-name prefix means divergence
        for (int k = 0; ENTS[k].name; k++)
            if (ent.compare(0, strlen(ENTS[k].name), ENTS[k].name)
                    == 0) {
                *fallback = true;
                break;
            }
        out += s[i++];
    }
    return out;
}

struct Attr { std::string name, val; };

// parse attributes between p and end (after the tag name).
// *slash_in_val is set when the byte just before '>' was consumed as
// part of an UNQUOTED attribute value (html.parser keeps it in the
// value: <a href=foo/> has value "foo/" and is NOT self-closing, while
// <a href="foo"/> and <a checked/> are) — the caller must not treat
// that trailing '/' as a self-close marker.
static void parse_attrs(const char* p, const char* end,
                        std::vector<Attr>& out, bool* fallback,
                        bool* slash_in_val) {
    while (p < end) {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                           *p == '\r' || *p == '/'))
            p++;
        if (p >= end) break;
        const char* ns = p;
        while (p < end && *p != '=' && *p != ' ' && *p != '\t' &&
               *p != '\n' && *p != '\r' && *p != '/')
            p++;
        std::string name(ns, p - ns);
        ascii_lower(name);
        std::string val;
        const char* q = p;
        while (q < end && (*q == ' ' || *q == '\t' || *q == '\n' ||
                           *q == '\r'))
            q++;
        if (q < end && *q == '=') {
            q++;
            while (q < end && (*q == ' ' || *q == '\t' || *q == '\n' ||
                               *q == '\r'))
                q++;
            if (q < end && (*q == '"' || *q == '\'')) {
                char quote = *q++;
                const char* vs = q;
                while (q < end && *q != quote) q++;
                val = decode_entities(vs, q - vs, fallback);
                if (q < end) q++;
            } else {
                const char* vs = q;
                while (q < end && *q != ' ' && *q != '\t' &&
                       *q != '\n' && *q != '\r')
                    q++;
                val = decode_entities(vs, q - vs, fallback);
                if (q == end && q > vs && q[-1] == '/')
                    *slash_in_val = true;
            }
            p = q;
        }
        if (!name.empty()) out.push_back({name, val});
    }
}

static void handle_starttag(Parser& P, const std::string& tag,
                            std::vector<Attr>& attrs) {
    if (in_set(tag.c_str(), SKIP_TAGS)) { P.skip_depth++; return; }
    if (P.skip_depth) return;
    if (tag == "title") P.title_depth++;
    else if (in_set(tag.c_str(), HEADING_TAGS)) P.heading_depth++;
    else if (in_set(tag.c_str(), LIST_TAGS)) P.list_depth++;
    else if (in_set(tag.c_str(), MENU_TAGS)) P.menu_depth++;
    else if (tag == "a") {
        P.in_anchor = false;
        P.anchor_href.clear();
        P.anchor_words.clear();
        for (auto& a : attrs)
            if (a.name == "href") {
                P.anchor_href = a.val;
                P.in_anchor = true;
            }
    } else if (tag == "meta") {
        // python: d = dict(attrs) (last-wins), then
        // name = d.get("name") or d.get("property")
        std::string name_attr, prop_attr, content;
        for (auto& a : attrs) {
            if (a.name == "name") name_attr = a.val;
            else if (a.name == "property") prop_attr = a.val;
            else if (a.name == "content") content = a.val;
        }
        std::string name = !name_attr.empty() ? name_attr : prop_attr;
        ascii_lower(name);
        if (!content.empty() &&
            (name == "article:published_time" || name == "date" ||
             name == "pubdate" || name == "og:published_time" ||
             name == "dc.date")) {
            if (P.date.empty()) P.date = content;
        }
        if ((name == "description" || name == "keywords") &&
            !content.empty()) {
            if (name == "description") P.desc = content;
            P.sent += 1;
            P.emit_words(content.data(), content.size(), HG_INMETATAG);
            P.sent += 1;
        }
    }
    if (in_set(tag.c_str(), SECTION_TAGS)) P.sect_push(tag);
    if (in_set(tag.c_str(), BLOCK_TAGS)) {
        P.pos += BLOCK_GAP;
        P.sent += 1;
    }
}

static void handle_endtag(Parser& P, const std::string& tag) {
    if (in_set(tag.c_str(), SKIP_TAGS)) {
        if (P.skip_depth) P.skip_depth--;
        return;
    }
    if (P.skip_depth) return;
    if (in_set(tag.c_str(), SECTION_TAGS)) P.sect_pop(tag);
    if (tag == "title") { if (P.title_depth) P.title_depth--; }
    else if (in_set(tag.c_str(), HEADING_TAGS)) {
        if (P.heading_depth) P.heading_depth--;
    } else if (in_set(tag.c_str(), LIST_TAGS)) {
        if (P.list_depth) P.list_depth--;
    } else if (in_set(tag.c_str(), MENU_TAGS)) {
        if (P.menu_depth) P.menu_depth--;
    } else if (tag == "a" && P.in_anchor) {
        P.links.push_back({P.anchor_href, P.anchor_words});
        P.in_anchor = false;
        P.anchor_href.clear();
        P.anchor_words.clear();
    }
    if (in_set(tag.c_str(), BLOCK_TAGS)) {
        P.pos += BLOCK_GAP;
        P.sent += 1;
    }
}

static void parse_html(Parser& P, const char* s, size_t len) {
    size_t i = 0;
    auto flush_text = [&](const char* ts, size_t tlen) {
        if (!tlen) return;
        if (memchr(ts, '&', tlen)) {
            std::string dec = decode_entities(ts, tlen, &P.fallback);
            P.handle_data(dec.data(), dec.size());
        } else {
            P.handle_data(ts, tlen);
        }
    };
    while (i < len) {
        const char* lt = (const char*)memchr(s + i, '<', len - i);
        if (!lt) { flush_text(s + i, len - i); break; }
        size_t ti = (size_t)(lt - s);
        flush_text(s + i, ti - i);
        i = ti;
        // stray '<' not opening a tag: html.parser emits it as data
        // and resumes at the next character
        {
            char nxt = (i + 1 < len) ? s[i + 1] : 0;
            bool tagish = (nxt >= 'a' && nxt <= 'z') ||
                          (nxt >= 'A' && nxt <= 'Z') || nxt == '/' ||
                          nxt == '!' || nxt == '?';
            if (!tagish) {
                P.handle_data("<", 1);
                i += 1;
                continue;
            }
        }
        // comment / doctype / processing instruction
        if (i + 3 < len && s[i + 1] == '!' && s[i + 2] == '-' &&
            s[i + 3] == '-') {
            const char* e = (const char*)memmem(s + i + 4, len - i - 4,
                                                "-->", 3);
            i = e ? (size_t)(e - s) + 3 : len;
            continue;
        }
        if (i + 1 < len && (s[i + 1] == '!' || s[i + 1] == '?')) {
            const char* e = (const char*)memchr(s + i, '>', len - i);
            i = e ? (size_t)(e - s) + 1 : len;
            continue;
        }
        const char* gt = (const char*)memchr(s + i, '>', len - i);
        if (!gt) break;  // unterminated tag: drop the tail
        size_t tag_end = (size_t)(gt - s);
        const char* p = s + i + 1;
        bool closing = (p < gt && *p == '/');
        if (closing) p++;
        const char* ns = p;
        while (p < gt && *p != ' ' && *p != '\t' && *p != '\n' &&
               *p != '\r' && *p != '/')
            p++;
        std::string tag(ns, p - ns);
        ascii_lower(tag);
        bool selfclose = tag_end > i && s[tag_end - 1] == '/';
        if (tag.empty()) { i = tag_end + 1; continue; }
        if (closing) {
            handle_endtag(P, tag);
        } else {
            std::vector<Attr> attrs;
            bool slash_in_val = false;
            parse_attrs(p, gt, attrs, &P.fallback, &slash_in_val);
            // <a href=foo/> is NOT self-closing: html.parser consumes
            // the '/' as the tail of the unquoted value — treating it
            // as a self-close would synthesize an endtag Python never
            // sees (and drop the anchor's text from the link harvest)
            if (slash_in_val) selfclose = false;
            handle_starttag(P, tag, attrs);
            if (selfclose) handle_endtag(P, tag);
            // raw-content elements: skip straight to the close tag
            // (html.parser CDATA mode for script/style)
            if (!selfclose && (tag == "script" || tag == "style")) {
                std::string close = "</" + tag;
                const char* e = nullptr;
                for (size_t k = tag_end + 1; k + close.size() <= len;
                     k++) {
                    if (s[k] == '<' &&
                        !strncasecmp(s + k, close.c_str(),
                                     close.size())) {
                        e = s + k;
                        break;
                    }
                }
                if (e) {
                    const char* ce =
                        (const char*)memchr(e, '>', len - (e - s));
                    handle_endtag(P, tag);
                    i = ce ? (size_t)(ce - s) + 1 : len;
                    continue;
                }
                i = len;  // unterminated script: drop the tail
                continue;
            }
        }
        i = tag_end + 1;
    }
}

// ---------------------------------------------------------------- ranks
// _density_ranks: per-sentence counts for body/heading/inlinktext,
// whole-hashgroup counts for the rest.
static void density_ranks(const std::vector<Tok>& toks,
                          std::vector<uint8_t>& out) {
    std::unordered_map<int32_t, int32_t> sent_counts;
    std::unordered_map<uint8_t, int32_t> hg_counts;
    for (auto& t : toks) {
        if (t.hg == HG_BODY || t.hg == HG_HEADING ||
            t.hg == HG_INLINKTEXT)
            sent_counts[t.sent]++;
        else
            hg_counts[t.hg]++;
    }
    out.resize(toks.size());
    for (size_t i = 0; i < toks.size(); i++) {
        const Tok& t = toks[i];
        int32_t c = (t.hg == HG_BODY || t.hg == HG_HEADING ||
                     t.hg == HG_INLINKTEXT)
                        ? sent_counts[t.sent]
                        : hg_counts[t.hg];
        int dr = MAXDENSITYRANK - (c - 1);
        out[i] = (uint8_t)(dr < 1 ? 1 : (dr > MAXDENSITYRANK
                                             ? MAXDENSITYRANK : dr));
    }
}

// _spam_ranks over tdoc.words — which INCLUDES the url tokens (they
// are appended to doc.words before docproc snapshots doc_words)
static void spam_ranks(const std::vector<Tok>& toks,
                       std::vector<uint8_t>& out) {
    size_t n_doc = toks.size();
    out.assign(toks.size(), MAXWORDSPAMRANK);
    if (n_doc < 40) return;
    std::unordered_map<std::string, int32_t> counts;
    for (size_t i = 0; i < n_doc; i++) counts[toks[i].word]++;
    for (size_t i = 0; i < n_doc; i++) {
        double frac = (double)counts[toks[i].word] / (double)n_doc;
        if (frac > 0.125) {
            int docked = (int)(MAXWORDSPAMRANK * (1.0 - frac) * 0.8);
            out[i] = (uint8_t)(docked < 2 ? 2 : docked);
        }
    }
}

template <class T>
static T* copy_vec(const std::vector<T>& v) {
    T* p = (T*)malloc(v.size() * sizeof(T) + 1);
    if (!v.empty()) memcpy(p, v.data(), v.size() * sizeof(T));
    return p;
}

static char* copy_str(const std::string& s, int64_t* len) {
    char* p = (char*)malloc(s.size() + 1);
    memcpy(p, s.data(), s.size());
    p[s.size()] = 0;
    *len = (int64_t)s.size();
    return p;
}

}  // namespace

// ------------------------------------------------------------ public API
extern "C" {

osse_doc* osse_tokenize(const char* content, int64_t content_len,
                        const char* url, int64_t url_len, int is_html) {
    Parser P;
    if (is_html) {
        parse_html(P, content, (size_t)content_len);
    } else {
        if (!P.text.empty()) P.text += ' ';
        P.text.append(content, (size_t)content_len);
        P.emit_words(content, (size_t)content_len, HG_BODY);
    }
    size_t n_doc = P.toks.size();
    // url words: pos 0, INURL, sentence 0, no section
    if (url && url_len > 0) {
        std::string u(url, (size_t)url_len);
        P.scan_words(u.data(), u.size(), [&](const std::string& w) {
            P.toks.push_back({w, 0, HG_INURL, 0, 0});
        });
    }
    const std::vector<Tok>& toks = P.toks;
    size_t n = toks.size();

    std::vector<uint8_t> density, spam;
    density_ranks(toks, density);
    spam_ranks(toks, spam);

    // term ids + word buffer
    std::vector<uint64_t> termid(n);
    std::string words_buf;
    words_buf.reserve(n * 8);
    for (size_t i = 0; i < n; i++) {
        termid[i] = fnv_avalanche(toks[i].word.data(),
                                  toks[i].word.size(), 0) & TERMID_MASK;
        if (i) words_buf += '\n';
        words_buf += toks[i].word;
    }

    // bigrams: consecutive, same sentence + hashgroup, phrasable hg
    std::vector<uint64_t> b_termid;
    std::vector<int32_t> b_src;
    for (size_t i = 0; i + 1 < n; i++) {
        if (toks[i].sent != toks[i + 1].sent) continue;
        if (toks[i].hg != toks[i + 1].hg) continue;
        if (toks[i].hg == HG_INURL || toks[i].hg == HG_INMETATAG)
            continue;
        // bigram_id: hash64(w2, seed=hash64(w1)) & TERMID_MASK
        uint64_t h1 = fnv_avalanche(toks[i].word.data(),
                                    toks[i].word.size(), 0);
        b_termid.push_back(fnv_avalanche(toks[i + 1].word.data(),
                                         toks[i + 1].word.size(), h1) &
                           TERMID_MASK);
        b_src.push_back((int32_t)i);
    }

    // sections: per-path word content (order = first appearance)
    std::vector<uint64_t> sect_hash;
    std::vector<int32_t> sect_words;
    std::string sect_buf;
    {
        std::unordered_map<uint64_t, size_t> idx;
        std::vector<std::string> content_strs;
        for (size_t i = 0; i < n_doc; i++) {
            uint64_t sid = toks[i].sect;
            if (!sid) continue;
            auto it = idx.find(sid);
            size_t k;
            if (it == idx.end()) {
                k = content_strs.size();
                idx[sid] = k;
                sect_hash.push_back(sid);
                sect_words.push_back(0);
                content_strs.push_back(std::string());
            } else
                k = it->second;
            if (!content_strs[k].empty()) content_strs[k] += ' ';
            content_strs[k] += toks[i].word;
            sect_words[k]++;
        }
        for (size_t k = 0; k < content_strs.size(); k++) {
            if (k) sect_buf += '\x1e';
            sect_buf += content_strs[k];
        }
    }

    // links buffer
    std::string links_buf;
    for (size_t k = 0; k < P.links.size(); k++) {
        if (k) links_buf += '\x1e';
        links_buf += P.links[k].first;
        links_buf += '\x1f';
        links_buf += P.links[k].second;
    }

    // whitespace-normalize text (re.sub(r"\s+", " ", text).strip())
    std::string norm;
    norm.reserve(P.text.size());
    bool in_ws = true;
    {
        const char* tp = P.text.data();
        size_t tl = P.text.size(), ti2 = 0;
        while (ti2 < tl) {
            int adv;
            uint32_t cp = u8_decode(tp + ti2, tl - ti2, &adv);
            bool ws = cp == ' ' || cp == '\t' || cp == '\n' ||
                      cp == '\r' || cp == '\f' || cp == '\v' ||
                      cp == 0x85 || cp == 0xA0 || cp == 0x1680 ||
                      (cp >= 0x2000 && cp <= 0x200A) || cp == 0x2028 ||
                      cp == 0x2029 || cp == 0x202F || cp == 0x205F ||
                      cp == 0x3000;
            if (ws) {
                if (!in_ws) norm += ' ';
                in_ws = true;
            } else {
                norm.append(tp + ti2, adv);
                in_ws = false;
            }
            ti2 += adv;
        }
    }
    while (!norm.empty() && norm.back() == ' ') norm.pop_back();

    osse_doc* d = (osse_doc*)calloc(1, sizeof(osse_doc));
    d->fallback = P.fallback ? 1 : 0;
    d->n = (int64_t)n;
    std::vector<int32_t> wp(n);
    std::vector<uint8_t> hg(n);
    std::vector<int32_t> sent(n);
    std::vector<uint64_t> sect(n);
    for (size_t i = 0; i < n; i++) {
        wp[i] = toks[i].pos;
        hg[i] = toks[i].hg;
        sent[i] = toks[i].sent;
        sect[i] = toks[i].sect;
    }
    d->termid = copy_vec(termid);
    d->wordpos = copy_vec(wp);
    d->hashgroup = copy_vec(hg);
    d->density = copy_vec(density);
    d->spam = copy_vec(spam);
    d->sentence = copy_vec(sent);
    d->sect = copy_vec(sect);
    d->nb = (int64_t)b_termid.size();
    d->b_termid = copy_vec(b_termid);
    d->b_src = copy_vec(b_src);
    d->words_buf = copy_str(words_buf, &d->words_len);
    d->text_buf = copy_str(norm, &d->text_len);
    d->title_buf = copy_str(P.title, &d->title_len);
    d->desc_buf = copy_str(P.desc, &d->desc_len);
    d->date_buf = copy_str(P.date, &d->date_len);
    d->links_buf = copy_str(links_buf, &d->links_len);
    d->nsect = (int64_t)sect_hash.size();
    d->sect_hash = copy_vec(sect_hash);
    d->sect_words = copy_vec(sect_words);
    d->sect_buf = copy_str(sect_buf, &d->sect_len);
    return d;
}

void osse_doc_free(osse_doc* d) {
    if (!d) return;
    free(d->termid); free(d->wordpos); free(d->hashgroup);
    free(d->density); free(d->spam); free(d->sentence); free(d->sect);
    free(d->b_termid); free(d->b_src);
    free(d->words_buf); free(d->text_buf); free(d->title_buf);
    free(d->desc_buf); free(d->date_buf); free(d->links_buf);
    free(d->sect_hash); free(d->sect_words); free(d->sect_buf);
    free(d);
}

// standalone hash entry points (parity tests against utils/ghash.py)
uint64_t osse_hash64(const char* data, int64_t len, uint64_t seed) {
    return fnv_avalanche(data, (size_t)len, seed);
}

}  // extern "C"
