"""SLO tracker — declared objectives, rolling error budgets, burn rate.

The reference never had this; it is the piece PagePerf/Statsdb stop
short of: turning the measurement substrate into *enforceable*
objectives. An objective declares what "good" means (``query p99 <
500ms``, ``availability 99.9%``) over a rolling window; the tracker
consumes the merged cluster stream (cumulative histogram/counter
reads), differences successive reads into (ts, Δgood, Δbad) deltas,
and derives:

- ``burn_rate``  — observed bad fraction / allowed bad fraction. 1.0
  means the error budget is being spent exactly as fast as it accrues;
  above 1 the objective is burning down.
- ``budget_remaining`` — share of the window's error budget left,
  clamped to [0, 1].

Both export as ``slo.<name>.burn_rate`` / ``slo.<name>.budget_remaining``
gauges, and any objective with burn > 1 raises the process-wide degrade
signal (``g_slo.degraded()``) the cache/membudget planes can observe to
shed optional work before the tail melts.

Evaluation is pull-based: the serve loop (or a test, with an injected
``now``) calls ``evaluate()`` with the latest counters + latency
recorders — local ``g_stats`` on a single host, the scraped-and-merged
fleet view on a coordinator.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field

from .stats import LatencyStat, Stats, g_stats


@dataclass
class SloObjective:
    """One declared objective over a rolling window.

    ``kind="latency"``: of the samples in ``metric``'s histogram, the
    fraction above ``threshold_ms`` must stay under ``1 - target``.
    ``kind="availability"``: of ``good_counter + bad_counter`` events,
    the bad fraction must stay under ``1 - target``.
    """
    name: str
    kind: str                      # "latency" | "availability"
    target: float                  # e.g. 0.99 (p99) or 0.999 (99.9%)
    window_s: float = 300.0
    metric: str = ""               # latency: histogram name
    threshold_ms: float = 0.0      # latency: the "< 500ms" bound
    good_counter: str = ""         # availability: success counter
    bad_counter: str = ""          # availability: failure counter
    # cumulative reads at the last evaluate (for delta computation)
    _last: tuple[int, int] | None = field(default=None, repr=False)
    # rolling (ts, d_good, d_bad) deltas inside the window
    _deltas: deque = field(default_factory=deque, repr=False)

    def _cumulative(self, counters: dict,
                    latencies: dict) -> tuple[int, int]:
        """(total, bad) cumulative reads from the current stream."""
        if self.kind == "latency":
            lat = latencies.get(self.metric)
            if lat is None:
                return 0, 0
            if not isinstance(lat, LatencyStat):
                lat = LatencyStat.from_wire(lat)
            return lat.count, lat.count_over(self.threshold_ms)
        good = int(counters.get(self.good_counter, 0))
        bad = int(counters.get(self.bad_counter, 0))
        return good + bad, bad

    def observe(self, counters: dict, latencies: dict,
                now: float) -> dict:
        total, bad = self._cumulative(counters, latencies)
        if self._last is None:
            d_total, d_bad = total, bad
        else:
            # counters reset (bench isolation) read as negative deltas;
            # treat a rewind as a fresh stream
            d_total = total - self._last[0]
            d_bad = bad - self._last[1]
            if d_total < 0 or d_bad < 0:
                d_total, d_bad = total, bad
        self._last = (total, bad)
        if d_total > 0 or d_bad > 0:
            self._deltas.append((now, d_total, d_bad))
        cutoff = now - self.window_s
        while self._deltas and self._deltas[0][0] < cutoff:
            self._deltas.popleft()

        w_total = sum(d[1] for d in self._deltas)
        w_bad = sum(d[2] for d in self._deltas)
        allowed_frac = max(1e-9, 1.0 - self.target)
        if w_total <= 0:
            burn, budget = 0.0, 1.0
        else:
            bad_frac = w_bad / w_total
            burn = bad_frac / allowed_frac
            budget = max(0.0, 1.0 - w_bad / (allowed_frac * w_total))
        return {
            "name": self.name, "kind": self.kind,
            "target": self.target, "window_s": self.window_s,
            "window_total": w_total, "window_bad": w_bad,
            "burn_rate": burn, "budget_remaining": budget,
            "burning": burn > 1.0,
        }


class SloTracker:
    """Registry of objectives + the process-wide degrade signal."""

    def __init__(self, registry: Stats | None = None):
        self._lock = threading.Lock()
        self.objectives: dict[str, SloObjective] = {}
        self.registry = registry if registry is not None else g_stats
        self._burning: set[str] = set()
        self._status: dict[str, dict] = {}

    def declare(self, obj: SloObjective) -> SloObjective:
        with self._lock:
            self.objectives[obj.name] = obj
        return obj

    def declare_latency(self, name: str, metric: str,
                        threshold_ms: float, target: float,
                        window_s: float = 300.0) -> SloObjective:
        """``declare_latency("query_p99", "cluster.query", 500, 0.99)``
        reads as: query p99 < 500ms."""
        return self.declare(SloObjective(
            name=name, kind="latency", target=target,
            window_s=window_s, metric=metric,
            threshold_ms=threshold_ms))

    def declare_availability(self, name: str, good_counter: str,
                             bad_counter: str, target: float,
                             window_s: float = 300.0) -> SloObjective:
        return self.declare(SloObjective(
            name=name, kind="availability", target=target,
            window_s=window_s, good_counter=good_counter,
            bad_counter=bad_counter))

    def evaluate(self, counters: dict | None = None,
                 latencies: dict | None = None,
                 now: float | None = None) -> dict[str, dict]:
        """Run every objective against the given stream (defaults to
        the local registry) and export the gauges. ``now`` is
        injectable so tests can march the window forward without
        sleeping."""
        if counters is None or latencies is None:
            with self.registry._lock:
                counters = dict(self.registry.counters)
                latencies = dict(self.registry.latencies)
        if now is None:
            import time
            now = time.time()
        out: dict[str, dict] = {}
        with self._lock:
            objs = list(self.objectives.values())
        for obj in objs:
            st = obj.observe(counters, latencies, now)
            out[obj.name] = st
            self.registry.gauge(f"slo.{obj.name}.burn_rate",
                                st["burn_rate"])
            self.registry.gauge(f"slo.{obj.name}.budget_remaining",
                                st["budget_remaining"])
        with self._lock:
            self._burning = {n for n, st in out.items()
                             if st["burning"]}
            self._status = out
        self.registry.gauge("slo.degraded", float(len(self._burning)))
        return out

    def degraded(self, name: str | None = None) -> bool:
        """The degrade signal: is any objective (or ``name``
        specifically) burning its budget faster than it accrues? Cheap
        enough for cache/membudget planes to poll on their hot paths."""
        with self._lock:
            if name is not None:
                return name in self._burning
            return bool(self._burning)

    def status(self) -> dict[str, dict]:
        """Last evaluation per objective (for /admin/perf + bench)."""
        with self._lock:
            return dict(self._status)

    def reset(self) -> None:
        with self._lock:
            self.objectives.clear()
            self._burning.clear()
            self._status.clear()


#: process-wide singleton, parallel to ``g_stats``/``g_tracer``
g_slo = SloTracker()
