"""Distributed query tracing — sampled span trees across shards.

The reference engine answers "why was this query slow?" with per-stage
timing logged inside ``PosdbTable::intersectLists10_r`` and at the
Msg39/Msg3a boundary.  Once a query fans out over the hedged cluster
transport that style of logging stops composing — the interesting time
is on another host, inside a hedge attempt that may not even have won.
This module is the Dapper-style fix (Sigelman et al., 2010):

* **Span trees** — ``g_tracer.start(name)`` opens a trace whose root
  span rides a :mod:`contextvars` context; ``span(name, **tags)``
  context managers hang child spans off whatever span is current.
  Timestamps come from the monotonic ``time.perf_counter`` clock and
  serialize as millisecond offsets from the trace start.
* **Head-based sampling** — the keep/drop decision is made once, at
  trace start (``trace_sample`` parm, default 1 in 64).  Unsampled
  traces still time their root (so the slow-query net below works) but
  every ``span()`` inside them is a no-op: the unsampled path must be
  cheap enough to leave on in production (see ``BENCH_TRACE=1``).
* **Slow-query log** — any trace slower than ``slow_query_ms`` is kept
  regardless of the sampling coin flip and appended as one JSON line to
  ``slowlog.jsonl`` (next to ``statsdb.jsonl``).  An unsampled slow
  trace keeps only its root-span skeleton — enough to know it happened
  and how long it took.
* **Cross-host propagation** — the transport stamps outgoing RPCs with
  an ``X-OSSE-Trace: <trace_id>:<parent_span_id>`` header; node
  handlers ``adopt()`` it, run their handler under a local root span,
  and ship the finished subtree back inside the reply (``"_trace"``
  key).  The client-side RPC span ``graft()``\\ s that subtree so the
  coordinator ends up holding ONE tree spanning every host the query
  touched.  Remote offsets are rebased onto the local RPC span's start,
  so cross-host clock skew never enters the picture (the network time
  shows up as the gap between the RPC bar and its remote children).

Threads are the sharp edge: a fresh ``threading.Thread`` starts with an
EMPTY contextvars context, so the trace does NOT follow work into
thread pools or hedge threads on its own.  Pass the parent span
explicitly (``begin(name, parent=sp)``) or re-attach it in the worker
(``with attach(sp): ...``) — the cluster client and batchers do both.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import threading
import time
import uuid
from collections import deque

from .log import get_logger
from .stats import g_stats

log = get_logger("perf")

#: HTTP header carrying "<trace_id>:<parent_span_id>" across hosts
TRACE_HEADER = "X-OSSE-Trace"
#: finished sampled/slow traces kept in memory for /admin/traces
RING_KEEP = 128
#: default head-sampling rate: keep 1 trace in N (0 disables tracing)
DEFAULT_SAMPLE_N = 64
#: default slow-query threshold (ms); slower traces always kept
DEFAULT_SLOW_MS = 1000.0

_ids = itertools.count(1)

#: current span (None outside any SAMPLED trace)
_ctx: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "osse_trace_span", default=None)
#: current trace id — set even for UNSAMPLED traces so log prefixes
#: and debug=1 echoes work without paying for span bookkeeping
_tid_ctx: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "osse_trace_id", default=None)


class Span:
    """One timed node in a trace tree.

    Times are raw ``perf_counter`` seconds; offsets become milliseconds
    only at :meth:`to_dict`.  ``finish`` is idempotent — abandoned
    hedge attempts may finish long after the trace exported, and a
    still-unfinished span exports with ``abandoned: true`` and a
    duration running to the export instant.
    """

    __slots__ = ("trace_id", "span_id", "name", "host", "tags",
                 "children", "_grafts", "_t0", "_t1")

    def __init__(self, trace_id: str, name: str, host: str = "",
                 tags: dict | None = None):
        self.trace_id = trace_id
        self.span_id = f"{next(_ids):x}"
        self.name = name
        self.host = host
        self.tags = dict(tags) if tags else {}
        self.children: list[Span] = []
        #: remote subtrees (already-serialized dicts) from RPC replies
        self._grafts: list[dict] = []
        self._t0 = time.perf_counter()
        self._t1: float | None = None

    def tag(self, **kw) -> "Span":
        self.tags.update(kw)
        return self

    def finish(self) -> None:
        if self._t1 is None:
            self._t1 = time.perf_counter()

    def child(self, name: str, **tags) -> "Span":
        sp = Span(self.trace_id, name, host=self.host, tags=tags)
        self.children.append(sp)
        return sp

    def graft(self, subtree: dict) -> None:
        """Hang a remote host's exported subtree under this span."""
        if isinstance(subtree, dict):
            self._grafts.append(subtree)

    def record(self, name: str, t0: float, t1: float | None = None,
               **tags) -> "Span":
        """Attach an already-measured interval as a completed child —
        for call sites that timed themselves with ``perf_counter``."""
        sp = self.child(name, **tags)
        sp._t0 = t0
        sp._t1 = time.perf_counter() if t1 is None else t1
        return sp

    def to_dict(self, base_t0: float, end: float) -> dict:
        start_ms = (self._t0 - base_t0) * 1000.0
        t1 = self._t1
        d = {
            "id": self.span_id,
            "name": self.name,
            "host": self.host,
            "start_ms": round(start_ms, 3),
            "dur_ms": round(((end if t1 is None else t1) - self._t0)
                            * 1000.0, 3),
            "tags": dict(self.tags),
        }
        if t1 is None:
            d["tags"]["abandoned"] = True
        kids = [c.to_dict(base_t0, end) for c in self.children]
        # remote subtrees arrive with offsets relative to THEIR root;
        # rebase onto this (RPC) span's start so the waterfall lines up
        # without ever comparing two hosts' clocks
        kids.extend(_shift(g, start_ms) for g in self._grafts)
        if kids:
            d["children"] = kids
        return d


def _shift(node: dict, delta_ms: float) -> dict:
    out = dict(node)
    out["start_ms"] = round(node.get("start_ms", 0.0) + delta_ms, 3)
    if node.get("children"):
        out["children"] = [_shift(c, delta_ms) for c in node["children"]]
    return out


def span_count(node: dict) -> int:
    return 1 + sum(span_count(c) for c in node.get("children", ()))


# ---------------------------------------------------------------------------
# context helpers
# ---------------------------------------------------------------------------

def current_span() -> Span | None:
    return _ctx.get()


def current_trace_id() -> str | None:
    tid = _tid_ctx.get()
    if tid is not None:
        return tid
    sp = _ctx.get()
    return sp.trace_id if sp is not None else None


def begin(name: str, parent: Span | None = None, **tags) -> Span | None:
    """Open a child span WITHOUT making it current — for handing work
    to another thread.  Caller owns ``finish()``."""
    p = parent if parent is not None else _ctx.get()
    return None if p is None else p.child(name, **tags)


class attach:
    """Re-establish ``sp`` as the current span inside a worker thread
    (fresh threads start with an empty contextvars context)."""

    __slots__ = ("sp", "_tok", "_tok2")

    def __init__(self, sp: Span | None):
        self.sp = sp

    def __enter__(self) -> Span | None:
        if self.sp is None:
            self._tok = None
            return None
        self._tok = _ctx.set(self.sp)
        self._tok2 = _tid_ctx.set(self.sp.trace_id)
        return self.sp

    def __exit__(self, *exc) -> None:
        if self._tok is not None:
            _ctx.reset(self._tok)
            _tid_ctx.reset(self._tok2)


class span:
    """``with span("query.pack", npass=i):`` — child of the current
    span, no-op (yields None) outside a sampled trace."""

    __slots__ = ("name", "tags", "sp", "_tok")

    def __init__(self, name: str, **tags):
        self.name = name
        self.tags = tags

    def __enter__(self) -> Span | None:
        p = _ctx.get()
        if p is None:
            self.sp = None
            self._tok = None
            return None
        self.sp = p.child(self.name, **self.tags)
        self._tok = _ctx.set(self.sp)
        return self.sp

    def __exit__(self, *exc) -> None:
        if self.sp is not None:
            _ctx.reset(self._tok)
            self.sp.finish()


class timed_span:
    """A span that ALSO feeds ``g_stats.record_ms(name)`` — the query
    path uses this everywhere a ``g_stats.timed`` used to live, so the
    aggregate plane and the trace plane cannot drift apart."""

    __slots__ = ("name", "_cm", "_t0")

    def __init__(self, name: str, **tags):
        self.name = name
        self._cm = span(name, **tags)

    def __enter__(self) -> Span | None:
        self._t0 = time.perf_counter()
        return self._cm.__enter__()

    def __exit__(self, *exc) -> None:
        self._cm.__exit__(*exc)
        # exemplar: when this interval ran under a SAMPLED trace, pin
        # its trace id to the histogram bucket it lands in — the
        # /admin/perf p99 cell links to the concrete /admin/traces
        # waterfall (Dapper's aggregate→trace bridge)
        sp = self._cm.sp
        g_stats.record_ms(
            self.name, (time.perf_counter() - self._t0) * 1000.0,
            exemplar=sp.trace_id if sp is not None else None)


def record(name: str, t0: float, t1: float | None = None, **tags) -> None:
    """Attach an already-measured ``perf_counter`` interval to the
    current span AND to ``g_stats`` — like ``timed_span`` but for
    intervals the caller timed itself (device-time attribution after a
    block-until-ready). Feeding both planes here is what keeps ad-hoc
    ``perf_counter`` deltas off the query path (the ``adhoc-timing``
    lint rule)."""
    end = time.perf_counter() if t1 is None else t1
    p = _ctx.get()
    if p is not None:
        p.record(name, t0, end, **tags)
    g_stats.record_ms(name, (end - t0) * 1000.0,
                      exemplar=p.trace_id if p is not None else None)


def tag(**kw) -> None:
    """Merge tags into the current span, if any."""
    p = _ctx.get()
    if p is not None:
        p.tags.update(kw)


def header_for(sp: Span | None) -> str | None:
    return None if sp is None else f"{sp.trace_id}:{sp.span_id}"


def parse_header(value: str) -> tuple[str, str] | None:
    tid, sep, psid = (value or "").partition(":")
    if not sep or not tid:
        return None
    return tid, psid


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------

class _LiveTrace:
    """Handle yielded by :meth:`Tracer.start` while the trace runs."""

    __slots__ = ("trace_id", "name", "sampled", "root")

    def __init__(self, trace_id: str, name: str, sampled: bool,
                 root: Span):
        self.trace_id = trace_id
        self.name = name
        self.sampled = sampled
        self.root = root

    def export(self) -> dict:
        end = (time.perf_counter() if self.root._t1 is None
               else self.root._t1)
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "sampled": self.sampled,
            "ts": time.time(),
            "dur_ms": round((end - self.root._t0) * 1000.0, 3),
            "root": self.root.to_dict(self.root._t0, end),
        }


class _Adopted:
    """Handle yielded by :meth:`Tracer.adopt` on the node side."""

    __slots__ = ("root",)

    def __init__(self, root: Span):
        self.root = root

    def export(self) -> dict:
        self.root.finish()
        return self.root.to_dict(self.root._t0, self.root._t1)


class _StartCM:
    def __init__(self, tracer: "Tracer", name: str, trace_id, sampled,
                 tags):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.sampled = sampled
        self.tags = tags
        self.trace: _LiveTrace | None = None

    def __enter__(self) -> _LiveTrace | None:
        tr = self.tracer
        if tr.sample_n <= 0:
            return None
        sampled = self.sampled
        if sampled is None:
            with tr._lock:
                tr._n += 1
                n = tr._n
            sampled = tr.sample_n == 1 or n % tr.sample_n == 0
        tid = self.trace_id or uuid.uuid4().hex[:16]
        root = Span(tid, self.name, host=tr.host, tags=self.tags)
        self.trace = _LiveTrace(tid, self.name, bool(sampled), root)
        self._tok = _ctx.set(root if sampled else None)
        self._tok2 = _tid_ctx.set(tid)
        g_stats.count("trace.started")
        if sampled:
            g_stats.count("trace.sampled")
        return self.trace

    def __exit__(self, *exc) -> None:
        t = self.trace
        if t is None:
            return
        _ctx.reset(self._tok)
        _tid_ctx.reset(self._tok2)
        t.root.finish()
        self.tracer._finish(t)


class Tracer:
    """Process-wide trace collector: sampling decision, finished-trace
    ring, slow-query log.  One instance (:data:`g_tracer`); the serving
    layer configures it from the ``trace_sample`` / ``slow_query_ms``
    parms and points ``slowlog_path`` next to ``statsdb.jsonl``."""

    def __init__(self, sample_n: int = DEFAULT_SAMPLE_N,
                 slow_ms: float = DEFAULT_SLOW_MS):
        self.sample_n = sample_n
        self.slow_ms = slow_ms
        self.slowlog_path = None
        self.host = ""
        self.ring: deque[dict] = deque(maxlen=RING_KEEP)
        self._lock = threading.Lock()
        self._n = 0

    def configure(self, sample_n: int | None = None,
                  slow_ms: float | None = None,
                  slowlog_path=None, host: str | None = None) -> None:
        if sample_n is not None:
            self.sample_n = int(sample_n)
        if slow_ms is not None:
            self.slow_ms = float(slow_ms)
        if slowlog_path is not None:
            self.slowlog_path = slowlog_path
        if host is not None:
            self.host = host

    def start(self, name: str, trace_id: str | None = None,
              sampled: bool | None = None, **tags) -> _StartCM:
        """Open a root trace.  ``sampled=None`` → head-sampling coin
        flip; ``True`` forces a full trace (debug=1, tests)."""
        return _StartCM(self, name, trace_id, sampled, tags)

    def adopt(self, trace_id: str, parent_span_id: str, name: str,
              host: str = "") -> "attach":
        """Node-side: continue a remote trace under a local root span.
        Adopted traces never enter the local ring or slowlog — they
        ship back to the coordinator inside the RPC reply instead."""
        root = Span(trace_id, name, host=host or self.host)
        if parent_span_id:
            root.tags["parent"] = parent_span_id
        return _AdoptCM(root)

    def recent(self) -> list[dict]:
        return list(self.ring)

    def find(self, trace_id: str) -> dict | None:
        for t in reversed(self.ring):
            if t["trace_id"] == trace_id:
                return t
        return None

    def _finish(self, t: _LiveTrace) -> None:
        dur_ms = (t.root._t1 - t.root._t0) * 1000.0
        slow = self.slow_ms > 0 and dur_ms >= self.slow_ms
        if not (t.sampled or slow):
            return
        exported = t.export()
        exported["slow"] = slow
        self.ring.append(exported)
        if slow:
            g_stats.count("trace.slow")
            self._slowlog_append(exported)

    def _slowlog_append(self, exported: dict) -> None:
        path = self.slowlog_path
        if path is None:
            return
        try:
            with self._lock:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(exported) + "\n")
        except Exception as exc:  # noqa: BLE001 — never break serving
            g_stats.count("trace.slowlog_errors")
            log.debug("slowlog append failed: %s", exc)

    def slowlog_tail(self, n: int = 50) -> list[dict]:
        """Last ``n`` slowlog entries, skipping torn trailing lines
        (kill-9 mid-append leaves a partial JSON line)."""
        path = self.slowlog_path
        if path is None:
            return []
        try:
            lines = open(path, encoding="utf-8").read().splitlines()
        except OSError:
            return []
        out = []
        for line in lines[-n:]:
            try:
                out.append(json.loads(line))
            except Exception:  # noqa: BLE001
                continue
        return out


class _AdoptCM:
    """Context manager for :meth:`Tracer.adopt` — an :class:`attach`
    that also yields the adopted-trace handle."""

    __slots__ = ("adopted", "_att")

    def __init__(self, root: Span):
        self.adopted = _Adopted(root)
        self._att = attach(root)

    def __enter__(self) -> _Adopted:
        self._att.__enter__()
        return self.adopted

    def __exit__(self, *exc) -> None:
        self._att.__exit__(*exc)
        self.adopted.root.finish()


#: process-wide tracer
g_tracer = Tracer()
