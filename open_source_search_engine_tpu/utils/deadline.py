"""Per-query deadline propagation — the ``Deadline`` helper.

The coordinator gives every query one time budget at the serve edge.
That budget must travel with the work: across the scatter fan-out, over
the wire to shard nodes, and down to the device dispatch — so a shard
abandons work the coordinator has already timed out instead of burning
a device wave on an answer nobody is waiting for.

Mechanics:

* A :class:`Deadline` is one monotonic point in time. It crosses
  threads explicitly via :class:`bind` (contextvars don't follow pool
  threads) and crosses hosts as **remaining budget** in the
  ``X-OSSE-Deadline`` header — wall clocks don't agree between hosts,
  budgets do (the gRPC deadline-propagation trick).
* Checkpoints call :func:`check_abandon` — at node dequeue
  (``ShardNodeServer.do_POST``), before device dispatch
  (``engine.search_device_batch`` / the resident loop's issue step) —
  which counts ``deadline.abandoned`` and tags the active trace span.
* :func:`note_met` counts ``deadline.met`` where a query finishes
  inside its budget.

The osselint ``bare-deadline`` rule fences this module in: raw
``time.monotonic() + timeout`` arithmetic on query/parallel/serve paths
must come through here, so the header stamping and the abandon
counters can never be bypassed by one more hand-rolled deadline.
"""

from __future__ import annotations

import contextvars
import time

from . import trace as trace_mod
from .stats import g_stats

#: wire header carrying the remaining budget (decimal seconds) on
#: scatter legs
DEADLINE_HEADER = "X-OSSE-Deadline"


class DeadlineExceeded(RuntimeError):
    """Work was abandoned because the coordinator's deadline passed."""


class Deadline:
    """One monotonic instant work must finish by."""

    __slots__ = ("at",)

    def __init__(self, at_monotonic: float):
        self.at = float(at_monotonic)

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(time.monotonic() + float(budget_s))

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.at - time.monotonic() <= 0.0

    def clamp(self, timeout_s: float) -> float:
        """A sub-call timeout bounded by what's left of the budget
        (floored at 0 — callers treat 0 as already-too-late)."""
        return max(0.0, min(float(timeout_s), self.remaining()))

    def header_value(self) -> str:
        return f"{max(self.remaining(), 0.0):.4f}"

    @classmethod
    def from_header(cls, value: str | None) -> "Deadline | None":
        if not value:
            return None
        try:
            return cls.after(float(value))
        except (TypeError, ValueError):
            return None

    def __repr__(self) -> str:  # noqa: D105
        return f"Deadline(remaining={self.remaining():.3f}s)"


_ctx: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "osse_deadline", default=None)


def current() -> Deadline | None:
    """The deadline bound in this context (None = unbudgeted work)."""
    return _ctx.get()


class bind:
    """Carry a Deadline across a scope. Worker threads don't inherit
    contextvars — capture ``current()`` where the deadline is known and
    ``bind()`` it where the work actually runs (the trace plane's
    ``attach`` pattern)."""

    def __init__(self, dl: Deadline | None):
        self._dl = dl
        self._tok = None

    def __enter__(self) -> Deadline | None:
        self._tok = _ctx.set(self._dl)
        return self._dl

    def __exit__(self, *exc) -> bool:
        _ctx.reset(self._tok)
        return False


def check_abandon(where: str, dl: Deadline | None = None) -> bool:
    """True when the (given or current) deadline has passed — the
    caller abandons. Counts ``deadline.abandoned`` (plus a per-site
    counter) and tags the active trace span so abandoned work shows in
    query waterfalls."""
    if dl is None:
        dl = _ctx.get()
    if dl is None or not dl.expired():
        return False
    g_stats.count("deadline.abandoned")
    g_stats.count(f"deadline.abandoned.{where}")
    trace_mod.tag(deadline="abandoned", deadline_where=where)
    return True


def note_met(dl: Deadline | None = None) -> None:
    """Count a budgeted query that finished inside its budget."""
    if dl is None:
        dl = _ctx.get()
    if dl is not None and not dl.expired():
        g_stats.count("deadline.met")
