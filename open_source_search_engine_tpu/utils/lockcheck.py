"""Runtime lock-order / lock-discipline auditor (``OSSE_LOCKCHECK=1``).

The static half of the analysis plane (``tools/osselint.py``) catches
rule-shaped bugs lexically; this module catches the ones only execution
reveals, in the spirit of ThreadSanitizer/RacerD's lock-set analysis:

* **Held-lock sets** — every :class:`TrackedLock` acquire/release
  maintains a per-thread stack of held locks.
* **Acquisition-order graph** — acquiring B while holding A records the
  edge A→B; a new edge that closes a path back to its source is a
  **potential deadlock** (two threads interleaving the two orders can
  each block on the other), reported once per edge with the acquiring
  stack, counted as ``lockcheck.cycle`` in ``g_stats``.
* **Hold-time histograms** — every release records the hold duration as
  ``lock.<name>.held_ms`` in the stats plane, so ``/admin/stats`` shows
  which mutex is the contention ceiling.
* **Blocking-call probes** — with the auditor on, ``time.sleep`` and
  socket connect/send/recv are wrapped; performing one while holding a
  tracked lock is recorded (``lockcheck.blocking_under_lock``) with the
  offending lock names and call site. This is the runtime twin of the
  static ``blocking-under-lock`` rule (which only sees *lexical*
  nesting).

Everything is opt-in: with ``OSSE_LOCKCHECK`` unset, :func:`make_lock`
and :func:`make_rlock` return plain ``threading`` primitives and this
module costs one import. Locks are identified by NAME, not instance —
every ``GenCache._lock`` is one node ``cache.gencache`` — because the
ordering convention is per lock *role*; same-name edges (two instances
of one role) are ignored rather than reported as self-deadlocks.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any

from . import schedcheck as _schedcheck
from .log import get_logger
from .stats import g_stats

log = get_logger("lockcheck")

#: process-wide opt-in, read once at import (the tracked locks are
#: constructed at module/instance init; flipping mid-run cannot retrofit
#: them)
ENABLED = os.environ.get("OSSE_LOCKCHECK") == "1"


def enabled() -> bool:
    return ENABLED


def _stack_tail(skip: int = 3, limit: int = 5) -> str:
    """Compact ``file:line`` chain of the acquiring frames (diagnostic
    payload on edges/events; only built when the auditor is on)."""
    frames = traceback.extract_stack()[:-skip][-limit:]
    return " < ".join(f"{os.path.basename(f.filename)}:{f.lineno}"
                      for f in reversed(frames))


class LockCheckRegistry:
    """One audit domain: held sets, the order graph, recorded events.

    The process singleton is :data:`g_lockcheck`; tests construct their
    own so assertions never see another test's edges.
    """

    def __init__(self):
        self._tl = threading.local()
        # the registry's own mutex is deliberately a PLAIN lock:
        # auditing the auditor would recurse
        self._mu = threading.Lock()
        #: src name -> {dst name, ...}: "src was held when dst was taken"
        self.edges: dict[str, set[str]] = {}
        #: (src, dst) -> "thread | stack" of the first observation
        self.edge_info: dict[tuple[str, str], str] = {}
        #: cycle paths ([name, ..., name]) — potential deadlocks
        self.cycles: list[list[str]] = []
        #: per cycle, "src->dst" → "thread | stack" for EVERY edge on
        #: the loop (both acquisition orders of a 2-cycle), so a
        #: schedcheck failure timeline cross-references by lock name
        self.cycle_stacks: list[dict[str, str]] = []
        #: blocking-call-under-lock events
        self.blocking: list[dict] = []

    # --- per-thread held set ---------------------------------------------

    def _held_list(self) -> list:
        h = getattr(self._tl, "held", None)
        if h is None:
            h = self._tl.held = []
        return h

    def held(self) -> list[str]:
        """Names of locks the CURRENT thread holds, outermost first."""
        return [name for name, _t0 in self._held_list()]

    # --- graph ------------------------------------------------------------

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS src→dst over the order graph (caller holds ``_mu``)."""
        stack, seen = [(src, [src])], {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, name: str) -> None:
        held = self._held_list()
        new_edges = [(h, name) for h, _ in held
                     if h != name and name not in
                     self.edges.get(h, ())]
        if new_edges:
            info = f"{threading.current_thread().name} | {_stack_tail()}"
            with self._mu:
                for src, dst in new_edges:
                    if dst in self.edges.setdefault(src, set()):
                        continue
                    # adding src→dst closes a potential-deadlock loop
                    # iff dst already reaches src
                    back = self._find_path(dst, src)
                    self.edges[src].add(dst)
                    self.edge_info[(src, dst)] = info
                    if back is not None:
                        cycle = back + [dst]
                        self.cycles.append(cycle)
                        pairs = list(zip(cycle, cycle[1:]))
                        self.cycle_stacks.append(
                            {f"{a}->{b}": self.edge_info.get((a, b), "?")
                             for a, b in pairs})
                        g_stats.count("lockcheck.cycle")
                        log.error(
                            "lock-order cycle (potential deadlock): "
                            "%s — new edge %s→%s at %s",
                            " → ".join(cycle), src, dst, info)
        held.append((name, time.perf_counter()))

    def note_release(self, name: str) -> None:
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _, t0 = held.pop(i)
                g_stats.record_ms(f"lock.{name}.held_ms",
                                  1000.0 * (time.perf_counter() - t0))
                return

    def note_blocking(self, what: str) -> None:
        """A blocking call ran on this thread; if it holds tracked
        locks, that's a latency bug (every other thread wanting those
        locks waits out the sleep/IO)."""
        held = self.held()
        if not held:
            return
        g_stats.count("lockcheck.blocking_under_lock")
        ev = {"call": what, "held": held, "where": _stack_tail(skip=4)}
        with self._mu:
            if len(self.blocking) < 256:
                self.blocking.append(ev)
        log.warning("blocking %s while holding %s at %s", what,
                    "+".join(held), ev["where"])

    # --- reporting --------------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {
                "edges": {s: sorted(d) for s, d in
                          sorted(self.edges.items())},
                "edge_info": {f"{s}->{d}": v for (s, d), v in
                              self.edge_info.items()},
                "cycles": [list(c) for c in self.cycles],
                "cycle_stacks": [dict(s) for s in self.cycle_stacks],
                "blocking": list(self.blocking),
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.edge_info.clear()
            self.cycles.clear()
            self.cycle_stacks.clear()
            self.blocking.clear()


#: process-wide audit domain
g_lockcheck = LockCheckRegistry()


class TrackedLock:
    """``threading.Lock`` wrapper feeding a :class:`LockCheckRegistry`.

    Supports the full lock protocol (``acquire``/``release``/context
    manager) so it drops in anywhere a plain mutex lives, including as
    the lock behind a ``threading.Condition``.
    """

    _inner_factory = staticmethod(threading.Lock)

    def __init__(self, name: str,
                 registry: LockCheckRegistry | None = None):
        self.name = name
        self.registry = registry or g_lockcheck
        self._inner = self._inner_factory()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        # try-then-block so contention is observable: a failed fast
        # acquire counts ``lock.<name>.contended`` before parking —
        # with held_ms it answers "which mutex is the ceiling AND who
        # queues on it"
        got = self._inner.acquire(False)
        if not got:
            g_stats.count(f"lock.{self.name}.contended")
            if blocking:
                got = self._inner.acquire(True, timeout)
        if got:
            self.registry.note_acquire(self.name)
        return got

    def release(self) -> None:
        self.registry.note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class TrackedRLock(TrackedLock):
    """Re-entrant variant: only the OUTERMOST acquire/release touch the
    held set (inner re-entries add no ordering information and would
    distort hold times)."""

    _inner_factory = staticmethod(threading.RLock)

    def __init__(self, name: str,
                 registry: LockCheckRegistry | None = None):
        super().__init__(name, registry)
        self._depth = threading.local()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        got = self._inner.acquire(False)
        if not got:
            g_stats.count(f"lock.{self.name}.contended")
            if blocking:
                got = self._inner.acquire(True, timeout)
        if got:
            d = getattr(self._depth, "n", 0)
            self._depth.n = d + 1
            if d == 0:
                self.registry.note_acquire(self.name)
        return got

    def release(self) -> None:
        d = getattr(self._depth, "n", 0)
        self._depth.n = d - 1
        if d == 1:
            self.registry.note_release(self.name)
        self._inner.release()


def make_lock(name: str):
    """A mutex for the hot-lock roster: plain ``threading.Lock`` when
    the auditor is off (zero overhead), :class:`TrackedLock` under
    ``OSSE_LOCKCHECK=1``, and a cooperatively scheduled lock when the
    calling thread is inside an active ``schedcheck.explore``."""
    if _schedcheck._active is not None:
        sched = _schedcheck.maybe_lock(name)
        if sched is not None:
            return sched
    return TrackedLock(name) if ENABLED else threading.Lock()


def make_rlock(name: str):
    if _schedcheck._active is not None:
        sched = _schedcheck.maybe_rlock(name)
        if sched is not None:
            return sched
    return TrackedRLock(name) if ENABLED else threading.RLock()


def make_condition(name: str):
    """A condition variable for the hot-lock roster. Under
    ``OSSE_LOCKCHECK=1`` the inner lock is tracked (wait/notify hold
    times and ordering edges land under ``name``); under an active
    schedcheck exploration it is a scheduled condition."""
    if _schedcheck._active is not None:
        sched = _schedcheck.maybe_condition(name)
        if sched is not None:
            return sched
    if ENABLED:
        return threading.Condition(TrackedLock(name))
    return threading.Condition()


def make_event(name: str):
    """An event for the roster — plain off-exploration (events carry no
    lock-ordering information), scheduled inside one."""
    if _schedcheck._active is not None:
        sched = _schedcheck.maybe_event(name)
        if sched is not None:
            return sched
    return threading.Event()


# --- blocking-call probes ---------------------------------------------------

_probes_installed = False
_orig: dict[str, Any] = {}


def install_probes(registry: LockCheckRegistry | None = None) -> None:
    """Wrap ``time.sleep`` and socket connect/send/recv to flag calls
    made while holding a tracked lock. Idempotent; opt-in only."""
    global _probes_installed
    if _probes_installed:
        return
    import socket as socket_mod
    reg = registry or g_lockcheck

    def _wrap(module: Any, attr: str, what: str) -> None:
        fn = getattr(module, attr)
        _orig[what] = (module, attr, fn)

        def probe(*a: Any, **kw: Any):
            reg.note_blocking(what)
            return fn(*a, **kw)

        probe.__name__ = f"lockcheck_{attr}"
        setattr(module, attr, probe)

    _wrap(time, "sleep", "time.sleep")
    # socket.socket is the Python subclass of _socket.socket, so method
    # overrides stick; every http.client/urllib byte ultimately crosses
    # one of these three
    _wrap(socket_mod.socket, "connect", "socket.connect")
    _wrap(socket_mod.socket, "sendall", "socket.sendall")
    _wrap(socket_mod.socket, "recv", "socket.recv")
    _probes_installed = True


def uninstall_probes() -> None:
    global _probes_installed
    for module, attr, fn in _orig.values():
        setattr(module, attr, fn)
    _orig.clear()
    _probes_installed = False


if ENABLED:
    install_probes()
