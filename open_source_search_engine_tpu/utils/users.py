"""Per-user admin accounts — the Users.cpp role.

The reference keeps a user table (``Users.cpp`` / ``users.txt``) with
per-user passwords and permission bits beside the master password.
Ours: ``users.txt`` in the instance base dir, one
``name:pbkdf2-hash:role`` line per user (roles ``admin`` > ``spider``
> ``query``), managed programmatically or by editing the file.
Passwords never store in the clear; verification is constant-time.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import secrets
from pathlib import Path

ROLES = ("query", "spider", "admin")
_ITER = 50_000


def _hash(pwd: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", pwd.encode(), salt, _ITER)


class Users:
    def __init__(self, base_dir: str | Path):
        self.path = Path(base_dir) / "users.txt"
        self._users: dict[str, tuple[bytes, bytes, str]] = {}
        self.load()

    def load(self) -> None:
        self._users.clear()
        if not self.path.exists():
            return
        for line in self.path.read_text().splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                name, salt_hex, hash_hex, role = line.split(":")
                if role not in ROLES:
                    continue
                self._users[name] = (bytes.fromhex(salt_hex),
                                     bytes.fromhex(hash_hex), role)
            except ValueError:
                continue

    def save(self) -> None:
        lines = ["# name:salt:pbkdf2_sha256:role"]
        for name, (salt, h, role) in sorted(self._users.items()):
            lines.append(f"{name}:{salt.hex()}:{h.hex()}:{role}")
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text("\n".join(lines) + "\n")
        os.replace(tmp, self.path)

    def add(self, name: str, pwd: str, role: str = "query") -> None:
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}")
        if ":" in name or not name:
            raise ValueError("bad user name")
        salt = secrets.token_bytes(16)
        self._users[name] = (salt, _hash(pwd, salt), role)
        self.save()

    def remove(self, name: str) -> bool:
        if name in self._users:
            del self._users[name]
            self.save()
            return True
        return False

    def check(self, name: str, pwd: str,
              min_role: str = "admin") -> bool:
        """Constant-time credential check at ≥ the required role."""
        rec = self._users.get(name)
        if rec is None:
            # burn comparable time so user enumeration stays blind
            _hash(pwd, b"\x00" * 16)
            return False
        salt, want, role = rec
        ok = hmac.compare_digest(_hash(pwd, salt), want)
        return ok and ROLES.index(role) >= ROLES.index(min_role)

    def names(self) -> list[tuple[str, str]]:
        return [(n, r) for n, (_, _, r) in sorted(self._users.items())]
