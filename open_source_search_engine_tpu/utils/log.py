"""Typed, runtime-togglable logging (reference: ``Log.cpp/h``).

Gigablast logs carry a type ("query:", "spider:", "rdb:", ...) and each type
can be toggled at runtime from the admin Log page. We reproduce that on top
of :mod:`logging`: one logger per subsystem under the ``osse`` root, with a
registry that the admin API can flip.
"""

from __future__ import annotations

import logging
import sys

_ROOT = "osse"

#: Log types mirroring the reference's log-subtype table
#: (``html/developer.html``; ``Log.h``).
LOG_TYPES = (
    "query", "spider", "build", "rdb", "net", "admin", "speller",
    "repair", "perf", "topics", "udp", "http", "dns", "mem",
)

_configured = False


class _TraceIdFilter(logging.Filter):
    """Stamp each record with the active trace id (``utils.trace``
    contextvar) so coordinator and node log lines for one query grep
    together by id. Outside a trace the prefix collapses to nothing
    and the line format is unchanged."""

    def filter(self, record: logging.LogRecord) -> bool:
        from . import trace  # late: log must import before tracing does
        tid = trace.current_trace_id()
        record.traceid = f" [{tid}]" if tid else ""
        return True


def _configure() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(name)s %(levelname).1s%(traceid)s %(message)s")
    )
    handler.addFilter(_TraceIdFilter())
    root = logging.getLogger(_ROOT)
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    _configured = True


def get_logger(log_type: str = "admin") -> logging.Logger:
    """Return the logger for a subsystem log type (e.g. ``"query"``)."""
    _configure()
    return logging.getLogger(f"{_ROOT}.{log_type}")


def set_log_type_enabled(log_type: str, enabled: bool) -> None:
    """Runtime toggle for one log type — the reference's Log admin page."""
    _configure()
    logging.getLogger(f"{_ROOT}.{log_type}").setLevel(
        logging.DEBUG if enabled else logging.WARNING
    )
