"""Unicode normalization — the UCNormalizer.cpp role.

The reference normalizes documents before hashing (``UCNormalizer.cpp``
+ ``ucdata/`` tables) so "é" composed and "e"+combining-acute index as
one term. Here NFC runs at the tokenizer/query seam: both the native
(C++) and Python tokenizers receive ALREADY-normalized text, so their
outputs stay identical and query terms match indexed terms regardless
of the source encoding's composition habits.

``nfc`` is a thin, fast-path wrapper: ASCII text (the overwhelming
majority byte-wise) skips the normalizer entirely via str.isascii —
a C-speed scan.
"""

from __future__ import annotations

import unicodedata


def nfc(text: str) -> str:
    if not text or text.isascii():
        return text
    return unicodedata.normalize("NFC", text)


#: IANA / web-reality charset aliases Python's codecs don't know by
#: that spelling (iana_charset.cpp maps ~100 of these; Python's codec
#: registry covers the decoders themselves)
CHARSET_ALIASES = {
    "x-sjis": "shift_jis",
    "x-euc-jp": "euc_jp",
    "iso-8859-8-i": "iso-8859-8",
    "unicode-1-1-utf-8": "utf-8",
    "unicode": "utf-16",
    "ks_c_5601-1987": "cp949",
    "ks_c_5601": "cp949",
    "macintosh": "mac_roman",
    "x-mac-roman": "mac_roman",
    "iso-latin-1": "latin-1",
    "8859-1": "latin-1",
    "win-1251": "cp1251",
    "windows-874": "cp874",
    "x-gbk": "gbk",
    "gb_2312-80": "gb2312",
    "ansi": "cp1252",
    "none": "utf-8",
}


def resolve_charset(name: str | None) -> str | None:
    """codecs-resolvable encoding name for a declared charset, or
    None when it is unknown (caller falls back to utf-8+replace)."""
    import codecs
    if not name:
        return None
    cand = CHARSET_ALIASES.get(name.strip().lower(), name)
    try:
        codecs.lookup(cand)
        return cand
    except LookupError:
        return None
