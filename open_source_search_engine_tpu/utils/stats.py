"""Metrics — counters, latency histograms, and time-series samples.

Reference: ``Stats.cpp/h`` (in-RAM per-message latency stats drawn on
PagePerf, ``Stats.h:38`` ``addStat_r``) + ``Statsdb`` (an actual Rdb of
per-second multi-metric samples graphed on PageStatsdb, ``Statsdb.h:24``).

One registry: named counters, named latency recorders (count/sum/min/max
+ fixed log2 histogram — enough to derive p50/p99 without storing every
sample), and a bounded per-second time-series ring. All host-side and
lock-cheap; the device never sees this.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

_BUCKETS = 24  # log2 ms buckets: <1ms ... >2^22ms


@dataclass
class LatencyStat:
    count: int = 0
    total_ms: float = 0.0
    min_ms: float = float("inf")
    max_ms: float = 0.0
    histo: list[int] = field(default_factory=lambda: [0] * _BUCKETS)

    def add(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        self.min_ms = min(self.min_ms, ms)
        self.max_ms = max(self.max_ms, ms)
        b = 0
        v = ms
        while v >= 1.0 and b < _BUCKETS - 1:
            v /= 2.0
            b += 1
        self.histo[b] += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from the log2 histogram (bucket upper
        bound)."""
        if not self.count:
            return 0.0
        want = q * self.count
        seen = 0
        for b, n in enumerate(self.histo):
            seen += n
            if seen >= want:
                return float(2 ** b)
        return self.max_ms

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "avg_ms": self.total_ms / self.count if self.count else 0.0,
            "min_ms": 0.0 if self.count == 0 else self.min_ms,
            "max_ms": self.max_ms,
            "p50_ms": self.quantile(0.50),
            "p99_ms": self.quantile(0.99),
        }


class Stats:
    """Process-wide metrics registry (``g_stats`` equivalent)."""

    def __init__(self, timeseries_window: int = 600):
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {}
        self.latencies: dict[str, LatencyStat] = {}
        #: last-written point-in-time values (per-host RTT, pool sizes —
        #: the PagePerf gauge row; counters monotonically grow, gauges
        #: overwrite)
        self.gauges: dict[str, float] = {}
        #: per-second samples: (epoch_s, {metric: value}) ring
        self.timeseries: deque = deque(maxlen=timeseries_window)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def record_ms(self, name: str, ms: float) -> None:
        with self._lock:
            self.latencies.setdefault(name, LatencyStat()).add(ms)

    def timed(self, name: str):
        """Context manager: ``with g_stats.timed("query"): ...``."""
        return _Timer(self, name)

    def sample(self, **metrics: float) -> None:
        """Append a Statsdb-style timestamped sample row."""
        with self._lock:
            self.timeseries.append((time.time(), dict(metrics)))

    def reset(self) -> None:
        """Zero counters + latency histograms (bench/test isolation)."""
        with self._lock:
            self.counters.clear()
            self.latencies.clear()
            self.gauges.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "latencies": {k: v.to_dict()
                              for k, v in self.latencies.items()},
                "gauges": dict(self.gauges),
            }

    def prefixed(self, prefix: str) -> dict:
        """Snapshot filtered to one subsystem's namespace — the admin
        pages' per-plane view (``/admin/cache`` wants ``cache.*`` only)."""
        with self._lock:
            return {
                "counters": {k: v for k, v in self.counters.items()
                             if k.startswith(prefix)},
                "latencies": {k: v.to_dict()
                              for k, v in self.latencies.items()
                              if k.startswith(prefix)},
                "gauges": {k: v for k, v in self.gauges.items()
                           if k.startswith(prefix)},
            }

    def series(self, last_s: float = 600.0) -> list:
        cutoff = time.time() - last_s
        with self._lock:
            return [(t, m) for t, m in self.timeseries if t >= cutoff]


class _Timer:
    def __init__(self, stats: Stats, name: str):
        self.stats, self.name = stats, name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stats.record_ms(self.name,
                             1000.0 * (time.perf_counter() - self.t0))
        return False


#: process-wide singleton (reference ``g_stats``)
g_stats = Stats()
