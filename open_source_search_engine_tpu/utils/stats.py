"""Metrics — counters, mergeable latency histograms, gauges, and
time-series samples.

Reference: ``Stats.cpp/h`` (in-RAM per-message latency stats drawn on
PagePerf, ``Stats.h:38`` ``addStat_r``) + ``Statsdb`` (an actual Rdb of
per-second multi-metric samples graphed on PageStatsdb, ``Statsdb.h:24``).

One registry: named counters, named latency recorders, gauges, and a
bounded per-second time-series ring. All host-side and lock-cheap; the
device never sees this.

The latency recorder is an HDR-style **log-linear histogram**: log2
major buckets (one per power of two, down to sub-millisecond) each split
into ``_SUB`` linear sub-buckets, so relative error is bounded by
``1/_SUB`` everywhere instead of a full power of two. Two recorders for
the same metric on different hosts merge by bucket-wise addition —
fleet percentiles come from the merged distribution, never from
averaging per-node percentiles (Dean & Barroso, *The Tail at Scale*).
Each recorder also keeps a bounded set of **exemplars**: occasionally a
sampled trace id is pinned to the bucket its latency landed in, so an
aggregate tail cell can link back to one concrete trace (Dapper).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

# Log-linear bucket geometry. Major bucket = binary exponent of the
# value in ms, clamped to [_E_MIN, _E_MAX]; each major bucket splits
# into _SUB linear sub-buckets. _E_MIN = -10 resolves to ~1µs —
# sub-millisecond cache hits land in real buckets instead of a 1ms
# floor — and _E_MAX = 22 tops out past an hour, same ceiling as the
# old log2 table.
_SUB = 16
_E_MIN = -10
_E_MAX = 22
_N_MAJOR = _E_MAX - _E_MIN + 1
_NBUCKETS = _N_MAJOR * _SUB
_MAX_EXEMPLARS = 8


def _bucket_index(ms: float) -> int:
    if ms <= 0.0:
        return 0
    m, e = math.frexp(ms)          # ms = m * 2**e, m in [0.5, 1)
    if e < _E_MIN:
        return 0
    if e > _E_MAX:
        return _NBUCKETS - 1
    sub = int((m - 0.5) * 2.0 * _SUB)
    if sub >= _SUB:                # m == 1-epsilon rounding guard
        sub = _SUB - 1
    return (e - _E_MIN) * _SUB + sub


def _bucket_bounds(idx: int) -> tuple[float, float]:
    """[lo, hi) value range of bucket ``idx`` in ms."""
    major, sub = divmod(idx, _SUB)
    e = major + _E_MIN
    width = 2.0 ** e               # major bucket spans [2**(e-1), 2**e)
    lo = width * (0.5 + sub / (2.0 * _SUB))
    hi = width * (0.5 + (sub + 1) / (2.0 * _SUB))
    return lo, hi


class LatencyStat:
    """One metric's mergeable log-linear histogram + summary moments."""

    __slots__ = ("count", "total_ms", "min_ms", "max_ms", "buckets",
                 "exemplars")

    def __init__(self):
        self.count: int = 0
        self.total_ms: float = 0.0
        self.min_ms: float = float("inf")
        self.max_ms: float = 0.0
        #: sparse histogram: bucket index -> sample count
        self.buckets: dict[int, int] = {}
        #: bucket index -> (trace_id, ms) — bounded, newest-wins
        self.exemplars: dict[int, tuple[str, float]] = {}

    def add(self, ms: float, exemplar: str | None = None) -> None:
        self.count += 1
        self.total_ms += ms
        if ms < self.min_ms:
            self.min_ms = ms
        if ms > self.max_ms:
            self.max_ms = ms
        idx = _bucket_index(ms)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        if exemplar is not None:
            if idx not in self.exemplars and \
                    len(self.exemplars) >= _MAX_EXEMPLARS:
                # full: keep the exemplar for the slowest buckets (the
                # tail is what /admin/perf links from)
                low = min(self.exemplars)
                if idx > low:
                    del self.exemplars[low]
                    self.exemplars[idx] = (exemplar, ms)
            else:
                self.exemplars[idx] = (exemplar, ms)

    def merge(self, other: "LatencyStat") -> "LatencyStat":
        """Bucket-wise merge of another recorder into this one."""
        self.count += other.count
        self.total_ms += other.total_ms
        if other.min_ms < self.min_ms:
            self.min_ms = other.min_ms
        if other.max_ms > self.max_ms:
            self.max_ms = other.max_ms
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        for idx, ex in other.exemplars.items():
            self.exemplars.setdefault(idx, ex)
        return self

    def quantile(self, q: float) -> float:
        """Quantile from the histogram, linearly interpolated within
        the crossing bucket and clamped to the observed [min, max]."""
        if not self.count:
            return 0.0
        want = q * self.count
        seen = 0
        for idx in sorted(self.buckets):
            n = self.buckets[idx]
            if seen + n >= want:
                lo, hi = _bucket_bounds(idx)
                frac = (want - seen) / n
                v = lo + frac * (hi - lo)
                return min(max(v, self.min_ms), self.max_ms)
            seen += n
        return self.max_ms

    def count_over(self, ms: float) -> int:
        """Samples above ``ms``, interpolating within the crossing
        bucket — the numerator of a latency SLO (`p99 < 500ms` means
        "fraction over 500ms must stay under 1%")."""
        thr = _bucket_index(ms)
        total = 0
        for idx, n in self.buckets.items():
            if idx > thr:
                total += n
            elif idx == thr:
                lo, hi = _bucket_bounds(idx)
                frac = (hi - ms) / (hi - lo) if hi > lo else 0.0
                total += int(round(n * max(0.0, min(1.0, frac))))
        return total

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "avg_ms": self.total_ms / self.count if self.count else 0.0,
            "min_ms": 0.0 if self.count == 0 else self.min_ms,
            "max_ms": self.max_ms,
            "p50_ms": self.quantile(0.50),
            "p99_ms": self.quantile(0.99),
        }

    def to_wire(self) -> dict:
        """Compact JSON-safe form: sparse buckets + moments + exemplars.
        This is what ``/rpc/stats`` ships and ``merge`` reconstitutes —
        raw buckets, not percentiles, so the coordinator can merge."""
        return {
            "count": self.count,
            "total_ms": self.total_ms,
            "min_ms": self.min_ms if self.count else 0.0,
            "max_ms": self.max_ms,
            "buckets": sorted(self.buckets.items()),
            "exemplars": [[idx, tid, ms] for idx, (tid, ms)
                          in sorted(self.exemplars.items())],
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "LatencyStat":
        st = cls()
        st.count = int(wire.get("count", 0))
        st.total_ms = float(wire.get("total_ms", 0.0))
        st.min_ms = float(wire.get("min_ms", 0.0)) if st.count \
            else float("inf")
        st.max_ms = float(wire.get("max_ms", 0.0))
        st.buckets = {int(i): int(n)
                      for i, n in wire.get("buckets", [])}
        st.exemplars = {int(i): (str(tid), float(ms))
                        for i, tid, ms in wire.get("exemplars", [])}
        return st


class Stats:
    """Process-wide metrics registry (``g_stats`` equivalent)."""

    def __init__(self, timeseries_window: int = 600):
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {}
        self.latencies: dict[str, LatencyStat] = {}
        #: last-written point-in-time values (per-host RTT, pool sizes —
        #: the PagePerf gauge row; counters monotonically grow, gauges
        #: overwrite)
        self.gauges: dict[str, float] = {}
        #: per-second samples: (epoch_s, {metric: value}) ring
        self.timeseries: deque = deque(maxlen=timeseries_window)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def record_ms(self, name: str, ms: float,
                  exemplar: str | None = None) -> None:
        with self._lock:
            self.latencies.setdefault(name, LatencyStat()).add(
                ms, exemplar=exemplar)

    def timed(self, name: str):
        """Context manager: ``with g_stats.timed("query"): ...``."""
        return _Timer(self, name)

    def sample(self, **metrics: float) -> None:
        """Append a Statsdb-style timestamped sample row."""
        with self._lock:
            self.timeseries.append((time.time(), dict(metrics)))

    def reset(self) -> None:
        """Zero counters + latency histograms (bench/test isolation).

        Gauges survive: they are point-in-time state written once (pool
        sizes, RTT seeds) that other planes keep reading — use
        ``reset_gauges()`` when a test really needs a blank slate."""
        with self._lock:
            self.counters.clear()
            self.latencies.clear()

    def reset_gauges(self) -> None:
        with self._lock:
            self.gauges.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "latencies": {k: v.to_dict()
                              for k, v in self.latencies.items()},
                "gauges": dict(self.gauges),
            }

    def wire(self) -> dict:
        """Mergeable snapshot: raw histogram buckets instead of derived
        percentiles — the ``/rpc/stats`` payload a coordinator scrape
        merges into fleet distributions."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "latencies": {k: v.to_wire()
                              for k, v in self.latencies.items()},
                "gauges": dict(self.gauges),
                "timeseries": [(t, dict(m))
                               for t, m in list(self.timeseries)[-60:]],
            }

    def prefixed(self, prefix: str) -> dict:
        """Snapshot filtered to one subsystem's namespace — the admin
        pages' per-plane view (``/admin/cache`` wants ``cache.*`` only)."""
        with self._lock:
            return {
                "counters": {k: v for k, v in self.counters.items()
                             if k.startswith(prefix)},
                "latencies": {k: v.to_dict()
                              for k, v in self.latencies.items()
                              if k.startswith(prefix)},
                "gauges": {k: v for k, v in self.gauges.items()
                           if k.startswith(prefix)},
            }

    def series(self, last_s: float = 600.0) -> list:
        cutoff = time.time() - last_s
        with self._lock:
            return [(t, m) for t, m in self.timeseries if t >= cutoff]


def merge_wire(parts: list[dict]) -> dict:
    """Merge per-host ``Stats.wire()`` payloads into one fleet view:
    counters sum, histograms merge bucket-wise, gauges keep the last
    writer (point-in-time state has no meaningful sum)."""
    counters: dict[str, int] = {}
    lats: dict[str, LatencyStat] = {}
    gauges: dict[str, float] = {}
    for part in parts:
        for k, v in part.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, w in part.get("latencies", {}).items():
            st = LatencyStat.from_wire(w)
            if k in lats:
                lats[k].merge(st)
            else:
                lats[k] = st
        gauges.update(part.get("gauges", {}))
    return {"counters": counters, "latencies": lats, "gauges": gauges}


class _Timer:
    def __init__(self, stats: Stats, name: str):
        self.stats, self.name = stats, name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stats.record_ms(self.name,
                             1000.0 * (time.perf_counter() - self.t0))
        return False


#: process-wide singleton (reference ``g_stats``)
g_stats = Stats()
