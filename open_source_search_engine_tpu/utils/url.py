"""URL parsing / normalization / site extraction.

Reference: ``Url.cpp/h`` (2.6k LoC — parse, normalize, punycode),
``Domains.cpp`` (TLD table), ``SiteGetter.cpp`` (site boundary detection:
the "site" is normally the host, but can be a subdirectory for hosting
domains). We use :mod:`urllib.parse` plus a compact multi-label-TLD list;
IDN is handled by Python's built-in ``idna`` codec (reference:
``Punycode.cpp``).
"""

from __future__ import annotations

from dataclasses import dataclass
from urllib.parse import urljoin, urlsplit, urlunsplit

# common multi-label public suffixes (reference Domains.cpp carries the full
# TLD table; extend as needed)
_TWO_LABEL_TLDS = {
    "co.uk", "org.uk", "ac.uk", "gov.uk", "co.jp", "ne.jp", "or.jp",
    "com.au", "net.au", "org.au", "co.nz", "com.br", "com.cn", "com.mx",
    "co.in", "co.kr", "com.tw", "com.sg", "co.za", "com.ar", "com.tr",
}

DEFAULT_PORTS = {"http": 80, "https": 443}


@dataclass(frozen=True)
class Url:
    """Parsed, normalized URL (reference ``class Url``, ``Url.h``)."""

    scheme: str
    host: str
    port: int
    path: str
    query: str

    @property
    def full(self) -> str:
        host = f"[{self.host}]" if ":" in self.host else self.host
        netloc = host
        if self.port and self.port != DEFAULT_PORTS.get(self.scheme):
            netloc = f"{host}:{self.port}"
        return urlunsplit((self.scheme, netloc, self.path, self.query, ""))

    @property
    def domain(self) -> str:
        """Registrable domain: ``www.a.foo.co.uk`` → ``foo.co.uk``
        (reference ``Url::getDomain`` via the Domains.cpp TLD walk)."""
        labels = self.host.split(".")
        if len(labels) <= 2:
            return self.host
        if ".".join(labels[-2:]) in _TWO_LABEL_TLDS and len(labels) >= 3:
            return ".".join(labels[-3:])
        return ".".join(labels[-2:])

    @property
    def site(self) -> str:
        """Site boundary — host for now (reference ``SiteGetter.cpp`` can
        pick subdirectory sites for hosting domains; tagdb can override)."""
        return self.host

    @property
    def tld(self) -> str:
        labels = self.host.split(".")
        if ".".join(labels[-2:]) in _TWO_LABEL_TLDS:
            return ".".join(labels[-2:])
        return labels[-1] if labels else ""


def normalize(raw: str, base: str | None = None) -> Url:
    """Parse + normalize a URL (reference ``Url::set`` normalization rules:
    lowercase scheme/host, strip fragment, default path "/", resolve
    relative against base, IDN→punycode, strip default port)."""
    if base:
        raw = urljoin(base, raw)
    parts = urlsplit(raw.strip())
    scheme = (parts.scheme or "http").lower()
    host = (parts.hostname or "").lower().rstrip(".")
    try:
        host = host.encode("idna").decode("ascii") if host else host
    except UnicodeError:
        pass
    try:
        port = parts.port or DEFAULT_PORTS.get(scheme, 0)
    except ValueError:  # non-numeric or out-of-range port in a crawled href
        port = DEFAULT_PORTS.get(scheme, 0)
    path = parts.path or "/"
    # collapse duplicate slashes, resolve . / .. segments
    segs: list[str] = []
    for seg in path.split("/"):
        if seg in ("", "."):
            continue
        if seg == "..":
            if segs:
                segs.pop()
            continue
        segs.append(seg)
    path = "/" + "/".join(segs) + ("/" if path.endswith("/") and segs else "")
    if not segs:
        path = "/"
    return Url(scheme, host, port, path, parts.query)
