"""Deterministic thread-schedule explorer (``OSSE_SCHED=1``).

The reference engine dodged interleaving bugs by construction —
Gigablast's ``Loop.cpp`` ran every state machine on ONE callback-driven
thread, so "schedule" meant "callback order" and races were impossible
by design. Our port reintroduced real threads (resident loop, tenancy
single-flight, admission waiters, SWR refreshers), and every
concurrency bug shipped so far was an interleaving bug found late.
This module makes schedules a *tested input* instead of an accident,
in the spirit of loom / rr / CHESS:

* Threads spawned via ``utils.threads`` and primitives built via
  ``utils.lockcheck.make_lock/make_rlock/make_condition/make_event``
  become **cooperatively scheduled** while an exploration is active:
  real OS threads, but exactly ONE runs at a time, handing a token at
  every yield point (lock acquire/release, condition wait/notify,
  event set/wait, thread spawn/join, and explicit
  :func:`sched_point` marks on shared-state accesses).
* The controller picks the next runnable thread from a **seeded PRNG**
  with **preemption-bound** exploration (bounded context switches per
  run, à la CHESS): one seed = one exact interleaving, replayable
  forever. Forced switches (current thread blocked/finished) are
  deterministic — first ready thread in registration order — so ALL
  nondeterminism lives in the recorded preemption decisions.
* Blocking waits with timeouts use **virtual time**: ``time.monotonic``
  is patched for the duration of a schedule, and when no thread is
  runnable the clock jumps to the earliest pending timeout. A run with
  no runnable thread and no pending timeout is reported as a deadlock,
  with every thread's wait target.
* :func:`explore` runs N distinct seeded schedules and, on failure,
  **shrinks** the failing seed's preemption decisions to a minimal set
  (greedy delta-debugging over the decision list), then raises
  :class:`ScheduleFailure` whose message is the minimal thread/lock
  timeline.

Arming follows the jitwatch/lockcheck contract: with ``OSSE_SCHED``
unset this module is a true no-op — the factories in ``lockcheck`` and
``threads`` check one module global and hand back plain primitives,
and even with the env var set, instrumentation only engages inside an
active :func:`explore` for threads it registered. Tier-1 behavior is
identical with and without the flag.

Cross-reference: scheduled locks feed ``lockcheck``'s acquisition-order
graph (when ``OSSE_LOCKCHECK=1``) under the same lock NAMES, so a
schedcheck failure timeline and a lockcheck cycle report line up
lock-for-lock.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable

#: process-wide opt-in, read once at import (the jitwatch/lockcheck
#: contract: unset ⇒ this module costs one import and one bool check)
ENABLED = os.environ.get("OSSE_SCHED") == "1"

#: real clock captured before any schedule patches ``time.monotonic``
_REAL_MONOTONIC = time.monotonic

#: probability a yield point spends one of the run's preemption budget
_PREEMPT_P = 0.35

#: the active exploration, if any (set only inside :func:`explore`)
_active: "Controller | None" = None


class SchedDeadlock(RuntimeError):
    """No runnable thread and no pending virtual timeout."""


class ScheduleFailure(AssertionError):
    """A seeded schedule broke an invariant; message is the shrunk
    thread/lock timeline (AssertionError so pytest renders it)."""

    def __init__(self, seed: int, error: BaseException,
                 trace: list[str], decisions: list[tuple[int, str]],
                 schedules_run: int, preemption_bound: int):
        self.seed = seed
        self.error = error
        self.trace = list(trace)
        self.decisions = list(decisions)
        self.schedules_run = schedules_run
        self.preemption_bound = preemption_bound
        super().__init__(self._render())

    def _render(self) -> str:
        head = (f"schedule failure: seed {self.seed} (found after "
                f"{self.schedules_run} schedule(s), bound "
                f"{self.preemption_bound}) — {type(self.error).__name__}: "
                f"{self.error}")
        dec = ", ".join(f"step {s}→{n}" for s, n in self.decisions) or "none"
        body = "\n".join(f"  {line}" for line in self.trace)
        return (f"{head}\nminimal preemptions: {dec}\n"
                f"thread/lock timeline:\n{body}")


class _SchedKilled(BaseException):
    """Internal: unwind a cooperating thread after the run is aborted.
    BaseException so scenario ``except Exception`` blocks can't eat it."""


class _TState:
    """Scheduler-side record for one cooperating thread."""

    __slots__ = ("name", "index", "event", "status", "waiting",
                 "deadline", "timed_out", "done")

    def __init__(self, name: str, index: int):
        self.name = name
        self.index = index
        self.event = threading.Event()
        self.status = "ready"            # ready | blocked | done
        self.waiting: tuple[str, str] | None = None
        self.deadline: float | None = None
        self.timed_out = False
        self.done = False


class Controller:
    """One schedule: a seeded token-passing scheduler.

    There is no controller *thread* — scheduling decisions are made by
    whichever cooperating thread holds the token, inside its yield
    point, under ``_mu``. That keeps switches at two Event operations
    and makes the decision sequence a pure function of (seed, program).
    """

    def __init__(self, seed: int, preemption_bound: int,
                 script: dict[int, str] | None = None):
        self.seed = seed
        self.bound = preemption_bound
        self.rng = random.Random(seed)
        #: replay mode: step → thread name to preempt to (shrinker)
        self.script = script
        self.step = 0
        self.preemptions = 0
        self.trace: list[str] = []
        self.decisions: list[tuple[int, str]] = []
        self.killed = False
        self.finished = False
        self.failure: BaseException | None = None
        self.clock_offset = 0.0
        self._mu = threading.Lock()
        self._states: dict[str, _TState] = {}
        self._order: list[str] = []
        self._by_ident: dict[int, _TState] = {}
        self._real_threads: list[threading.Thread] = []

    # --- registration -----------------------------------------------------

    def register(self, name: str) -> _TState:
        with self._mu:
            base, n = name, 2
            while name in self._states:
                name, n = f"{base}~{n}", n + 1
            st = _TState(name, len(self._order))
            self._states[name] = st
            self._order.append(name)
            return st

    def attach(self, st: _TState) -> None:
        """Bind the CURRENT OS thread to ``st`` (run from that thread)."""
        with self._mu:
            self._by_ident[threading.get_ident()] = st

    def attach_main(self) -> _TState:
        st = self.register("main")
        self.attach(st)
        return st

    def me(self) -> _TState | None:
        return self._by_ident.get(threading.get_ident())

    def now(self) -> float:
        return _REAL_MONOTONIC() + self.clock_offset

    # --- scheduling core --------------------------------------------------

    def _pick_locked(self, me: _TState) -> _TState:
        """Choose the next thread to run (caller holds ``_mu``)."""
        while True:
            ready = [self._states[n] for n in self._order
                     if self._states[n].status == "ready"]
            if ready:
                break
            timed = [s for s in self._states.values()
                     if s.status == "blocked" and s.deadline is not None]
            if not timed:
                raise SchedDeadlock(self._deadlock_msg_locked())
            # virtual time: jump to the earliest timeout and fire it
            s = min(timed, key=lambda t: (t.deadline, t.index))
            if s.deadline > self.now():
                self.clock_offset += (s.deadline - self.now()) + 1e-4
            s.timed_out, s.status = True, "ready"
            s.waiting = s.deadline = None
            self.trace.append(f"     ~ virtual timeout fires → {s.name}")
        if me.status != "ready":
            return ready[0]              # forced switch: deterministic
        others = [s for s in ready if s is not me]
        if not others:
            return me
        if self.script is not None:      # scripted replay (shrinker)
            want = self.script.get(self.step)
            for s in others:
                if s.name == want:
                    self.preemptions += 1
                    self.decisions.append((self.step, s.name))
                    self.trace.append(f"     ── preempt → {s.name}")
                    return s
            return me
        if self.preemptions < self.bound and self.rng.random() < _PREEMPT_P:
            s = others[self.rng.randrange(len(others))]
            self.preemptions += 1
            self.decisions.append((self.step, s.name))
            self.trace.append(f"     ── preempt → {s.name}")
            return s
        return me

    def _deadlock_msg_locked(self) -> str:
        waits = "; ".join(
            f"{s.name} awaits {s.waiting[0]} {s.waiting[1]}"
            for n in self._order
            for s in [self._states[n]] if s.status == "blocked")
        return f"deadlock: no runnable thread ({waits or 'no waiters'})"

    def _park(self, me: _TState) -> None:
        me.event.wait()
        me.event.clear()
        if self.killed:
            raise _SchedKilled()

    def yield_point(self, kind: str, target: str) -> None:
        """One scheduling opportunity for the calling thread."""
        me = self.me()
        if me is None or self.finished:
            return
        if self.killed:
            raise _SchedKilled()
        with self._mu:
            self.step += 1
            self.trace.append(
                f"{self.step:4d} {me.name:<16} {kind:<10} {target}")
            nxt = self._pick_locked(me)
            if nxt is me:
                return
            nxt.event.set()
        self._park(me)

    def block_on(self, kind: str, target: str,
                 deadline: float | None = None) -> bool:
        """Block the calling thread on (kind, target) until a waker
        marks it ready or the virtual ``deadline`` fires. Returns True
        when woken, False on timeout."""
        me = self.me()
        if me is None or self.finished:
            return True
        if self.killed:
            raise _SchedKilled()
        with self._mu:
            self.step += 1
            self.trace.append(
                f"{self.step:4d} {me.name:<16} {'block':<10} {kind} {target}")
            me.status, me.waiting = "blocked", (kind, target)
            me.deadline, me.timed_out = deadline, False
            nxt = self._pick_locked(me)
            nxt.event.set()
        self._park(me)
        return not me.timed_out

    def make_ready(self, states: list[_TState]) -> None:
        """Mark blocked threads runnable (called by the token holder;
        the woken threads run only when a later pick selects them)."""
        with self._mu:
            for s in states:
                if s.status == "blocked":
                    s.status = "ready"
                    s.waiting = s.deadline = None

    def wake_waiters(self, kind: str, target: str) -> None:
        with self._mu:
            for s in self._states.values():
                if s.status == "blocked" and s.waiting == (kind, target):
                    s.status = "ready"
                    s.waiting = s.deadline = None

    def finish(self, st: _TState) -> None:
        """The OS thread behind ``st`` is exiting; hand the token on."""
        with self._mu:
            st.status, st.done = "done", True
            if self.killed or self.finished:
                return
            self.trace.append(f"     ✓ {st.name} done")
            for s in self._states.values():
                if s.status != "blocked" or s.waiting is None:
                    continue
                if s.waiting == ("join", st.name):
                    s.status, s.waiting, s.deadline = "ready", None, None
                elif s.waiting == ("drain", "all") and all(
                        o.done for o in self._states.values() if o is not s):
                    s.status, s.waiting, s.deadline = "ready", None, None
            try:
                nxt = self._pick_locked(st)
            except SchedDeadlock as exc:
                self._fail_locked(exc)
                return
            nxt.event.set()

    def drain_remaining(self) -> None:
        """Run every other cooperating thread to completion (main calls
        this after the scenario body returns — leftover threads that
        can never finish surface as a deadlock/leak failure)."""
        me = self.me()
        while True:
            with self._mu:
                if all(s.done for s in self._states.values() if s is not me):
                    return
            self.block_on("drain", "all")

    # --- failure ----------------------------------------------------------

    def _fail_locked(self, exc: BaseException) -> None:
        if self.failure is None:
            self.failure = exc
        self.killed = True
        for s in self._states.values():
            s.event.set()

    def fail(self, exc: BaseException) -> None:
        with self._mu:
            self._fail_locked(exc)


# --- scheduled primitives ---------------------------------------------------


def _lockcheck():
    from . import lockcheck
    return lockcheck


class SchedLock:
    """Cooperatively scheduled mutex. Single-runner discipline means
    owner/waiter state needs no lock of its own — only the token holder
    touches it. Acquires/releases feed lockcheck's order graph under
    the same NAME so failure timelines and cycle reports line up."""

    _reentrant = False

    def __init__(self, ctl: Controller, name: str):
        self._ctl = ctl
        self.name = name
        self._owner: _TState | None = None
        self._depth = 0

    def _note(self, what: str) -> None:
        lc = _lockcheck()
        if lc.ENABLED:
            getattr(lc.g_lockcheck, what)(self.name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ctl = self._ctl
        me = ctl.me()
        if me is None or ctl.finished:
            return True                  # exploration over: degrade
        ctl.yield_point("acquire", self.name)
        dl = (ctl.now() + timeout) if (blocking and timeout is not None
                                       and timeout > 0) else None
        while True:
            if self._owner is None or (self._reentrant
                                       and self._owner is me):
                self._owner = me
                self._depth += 1
                if self._depth == 1:
                    self._note("note_acquire")
                return True
            if not blocking:
                return False
            if not ctl.block_on("lock", self.name, deadline=dl):
                return False

    def release(self) -> None:
        ctl = self._ctl
        me = ctl.me()
        if me is None or ctl.finished:
            self._owner, self._depth = None, 0
            return
        if self._owner is not me:
            raise RuntimeError(f"release of un-held lock {self.name}")
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            self._note("note_release")
            ctl.wake_waiters("lock", self.name)
            ctl.yield_point("release", self.name)

    def locked(self) -> bool:
        return self._owner is not None

    def _release_all(self) -> int:
        """Condition.wait support: drop the lock whatever the depth."""
        depth, self._depth, self._owner = self._depth, 0, None
        self._note("note_release")
        self._ctl.wake_waiters("lock", self.name)
        return depth

    def _reacquire(self, me: _TState, depth: int) -> None:
        ctl = self._ctl
        while self._owner is not None and self._owner is not me:
            ctl.block_on("lock", self.name)
        self._owner, self._depth = me, depth
        self._note("note_acquire")

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class SchedRLock(SchedLock):
    _reentrant = True


class SchedCondition:
    """Cooperatively scheduled ``threading.Condition`` equivalent."""

    def __init__(self, ctl: Controller, name: str,
                 lock: SchedLock | None = None):
        self._ctl = ctl
        self.name = name
        self._lock = lock if lock is not None else SchedLock(ctl, name)
        self._waiters: list[_TState] = []

    def acquire(self, *a: Any, **kw: Any) -> bool:
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "SchedCondition":
        self._lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    def wait(self, timeout: float | None = None) -> bool:
        ctl = self._ctl
        me = ctl.me()
        if me is None or ctl.finished:
            return True
        if self._lock._owner is not me:
            raise RuntimeError(f"wait() on un-acquired condition {self.name}")
        depth = self._lock._release_all()
        self._waiters.append(me)
        dl = (ctl.now() + max(timeout, 0.0)) if timeout is not None else None
        woken = ctl.block_on("cond", self.name, deadline=dl)
        if me in self._waiters:          # timed out before any notify
            self._waiters.remove(me)
        self._lock._reacquire(me, depth)
        return woken

    def notify(self, n: int = 1) -> None:
        ctl = self._ctl
        me = ctl.me()
        if me is None or ctl.finished:
            return
        woken, self._waiters = self._waiters[:n], self._waiters[n:]
        ctl.make_ready(woken)
        ctl.yield_point("notify", self.name)

    def notify_all(self) -> None:
        self.notify(n=len(self._waiters) or 1)


class SchedEvent:
    """Cooperatively scheduled ``threading.Event`` equivalent."""

    def __init__(self, ctl: Controller, name: str):
        self._ctl = ctl
        self.name = name
        self._flag = False
        self._waiters: list[_TState] = []

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        ctl = self._ctl
        me = ctl.me()
        if me is None or ctl.finished:
            return
        if self._waiters:
            woken, self._waiters = self._waiters, []
            ctl.make_ready(woken)
        ctl.yield_point("event-set", self.name)

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: float | None = None) -> bool:
        ctl = self._ctl
        me = ctl.me()
        if me is None or ctl.finished:
            return self._flag
        ctl.yield_point("event-wait", self.name)
        while not self._flag:
            dl = (ctl.now() + max(timeout, 0.0)) if timeout is not None \
                else None
            self._waiters.append(me)
            woken = ctl.block_on("event", self.name, deadline=dl)
            if me in self._waiters:
                self._waiters.remove(me)
            if not woken:
                return self._flag
        return True


class SchedThread(threading.Thread):
    """A cooperating thread: real OS thread, but it runs only while it
    holds the scheduler token, and ``join`` is a scheduled wait."""

    def __init__(self, ctl: Controller, name: str,
                 target: Callable[..., Any], args: tuple, kwargs: dict):
        self._ctl = ctl
        self._target0 = target
        self._args0 = args
        self._kwargs0 = kwargs
        self._st: _TState | None = None
        super().__init__(target=self._run_coop, daemon=True, name=name)

    def start(self) -> None:
        ctl = self._ctl
        self._st = ctl.register(self.name)
        ctl._real_threads.append(self)
        super().start()
        ctl.yield_point("spawn", self._st.name)

    def _run_coop(self) -> None:
        ctl, st = self._ctl, self._st
        ctl.attach(st)
        try:
            st.event.wait()              # first scheduling of this thread
            st.event.clear()
            if ctl.killed:
                return
            self._target0(*self._args0, **self._kwargs0)
        except _SchedKilled:
            pass
        except BaseException as exc:     # invariant broke on this thread
            ctl.fail(exc)
        finally:
            ctl.finish(st)

    def join(self, timeout: float | None = None) -> None:
        ctl, st = self._ctl, self._st
        me = ctl.me()
        if st is None or st.done or me is None or ctl.finished:
            super().join(timeout if timeout is not None else 5.0)
            return
        dl = (ctl.now() + max(timeout, 0.0)) if timeout is not None else None
        while not st.done:
            if not ctl.block_on("join", st.name, deadline=dl):
                return                   # timed out (virtual)


# --- factory hooks (called by lockcheck/threads) ----------------------------


def _controlled() -> Controller | None:
    """The active controller, iff the CALLING thread cooperates in it.
    Threads outside the exploration (pytest workers, leaked daemons)
    keep getting plain primitives even mid-run."""
    ctl = _active
    if ctl is None or ctl.finished or ctl.me() is None:
        return None
    return ctl


def maybe_lock(name: str) -> SchedLock | None:
    ctl = _controlled()
    return SchedLock(ctl, name) if ctl is not None else None


def maybe_rlock(name: str) -> SchedRLock | None:
    ctl = _controlled()
    return SchedRLock(ctl, name) if ctl is not None else None


def maybe_condition(name: str) -> SchedCondition | None:
    ctl = _controlled()
    return SchedCondition(ctl, name) if ctl is not None else None


def maybe_event(name: str) -> SchedEvent | None:
    ctl = _controlled()
    return SchedEvent(ctl, name) if ctl is not None else None


def maybe_thread(name: str, target: Callable[..., Any], args: tuple,
                 kwargs: dict) -> SchedThread | None:
    ctl = _controlled()
    if ctl is None:
        return None
    return SchedThread(ctl, name, target, args, kwargs)


def sched_point(name: str) -> None:
    """Mark a shared-state access as a scheduling opportunity. No-op
    outside an active exploration (safe to leave in production code,
    though scenarios usually put these on test doubles)."""
    ctl = _controlled()
    if ctl is not None:
        ctl.yield_point("point", name)


def settle(grace: float = 0.01) -> None:
    """Scenario barrier: park the calling thread behind every other
    runnable thread until the system quiesces (everyone blocked or
    done), then resume via a virtual timeout. Deterministic — forced
    switches pick in registration order — and a no-op outside an
    active exploration."""
    ctl = _controlled()
    if ctl is not None:
        ctl.block_on("settle", "grace", deadline=ctl.now() + grace)


# --- exploration harness ----------------------------------------------------


def _run_one(fn: Callable[[], None], seed: int, bound: int,
             script: dict[int, str] | None = None
             ) -> tuple[Controller, BaseException | None]:
    """Run ``fn`` under one exact schedule; returns (controller, failure)."""
    global _active
    if _active is not None:
        raise RuntimeError("explore() does not nest")
    ctl = Controller(seed, bound, script)
    _active = ctl
    time.monotonic = lambda: _REAL_MONOTONIC() + ctl.clock_offset
    ctl.attach_main()
    failure: BaseException | None = None
    try:
        fn()
        ctl.drain_remaining()
    except (_SchedKilled, SchedDeadlock) as exc:
        failure = ctl.failure if ctl.failure is not None else exc
    except BaseException as exc:
        failure = ctl.failure if ctl.failure is not None else exc
    finally:
        if failure is None and ctl.failure is not None:
            failure = ctl.failure
        with ctl._mu:
            ctl.killed = ctl.finished = True
            for s in ctl._states.values():
                s.event.set()
        time.monotonic = _REAL_MONOTONIC
        _active = None
        for th in ctl._real_threads:
            th.join(timeout=5.0)
    return ctl, failure


def _shrink(fn: Callable[[], None], seed: int, bound: int,
            decisions: list[tuple[int, str]], max_replays: int = 48
            ) -> tuple[Controller, BaseException] | None:
    """Greedy delta-debugging over the preemption decisions: drop one
    decision at a time, keep removals that still fail. Returns the
    minimal failing (controller, failure), or None if even the full
    scripted replay no longer fails (scenario nondeterminism)."""
    script = list(decisions)
    best: tuple[Controller, BaseException] | None = None
    ctl, failure = _run_one(fn, seed, bound, script=dict(script))
    if failure is None:
        return None
    best = (ctl, failure)
    replays, improved = 1, True
    while improved and replays < max_replays:
        improved = False
        for i in range(len(script)):
            trial = script[:i] + script[i + 1:]
            ctl, failure = _run_one(fn, seed, bound, script=dict(trial))
            replays += 1
            if failure is not None:
                script, best, improved = trial, (ctl, failure), True
                break
            if replays >= max_replays:
                break
    return best


def explore(fn: Callable[[], None], schedules: int | None = None,
            preemption_bound: int | None = None, seed: int = 0) -> dict:
    """Run ``fn`` under N distinct seeded schedules.

    ``fn`` builds its own world (threads via ``utils.threads``,
    primitives via the ``lockcheck`` factories) and asserts its
    invariants; any assertion/exception on any cooperating thread, a
    deadlock, or a leaked never-finishing thread fails the schedule.
    The failing seed is shrunk to a minimal preemption trace and
    raised as :class:`ScheduleFailure`.

    Defaults: ``schedules`` from ``OSSE_SCHED_BUDGET`` (64),
    ``preemption_bound`` from ``OSSE_SCHED_PREEMPTIONS`` (3).
    """
    if not ENABLED:
        raise RuntimeError(
            "schedcheck is not armed — run under OSSE_SCHED=1 (the "
            "factories bind to plain primitives otherwise)")
    if schedules is None:
        schedules = int(os.environ.get("OSSE_SCHED_BUDGET", "64"))
    if preemption_bound is None:
        preemption_bound = int(os.environ.get("OSSE_SCHED_PREEMPTIONS", "3"))
    yield_points = 0
    for i in range(schedules):
        s = seed + i
        ctl, failure = _run_one(fn, s, preemption_bound)
        yield_points += ctl.step
        if failure is None:
            continue
        shrunk = _shrink(fn, s, preemption_bound, ctl.decisions)
        if shrunk is None:               # replay diverged; report as-was
            shrunk = (ctl, failure)
        sctl, sfailure = shrunk
        raise ScheduleFailure(
            seed=s, error=sfailure, trace=sctl.trace,
            decisions=sctl.decisions, schedules_run=i + 1,
            preemption_bound=preemption_bound)
    return {"schedules": schedules, "preemption_bound": preemption_bound,
            "yield_points": yield_points, "failures": 0}


def trace_of(fn: Callable[[], None], seed: int,
             preemption_bound: int = 3) -> list[str]:
    """The exact event trace of ONE seeded schedule (determinism probe:
    same seed ⇒ byte-identical trace). Raises nothing — a failing
    schedule's partial trace is still the deterministic artifact."""
    if not ENABLED:
        raise RuntimeError("schedcheck is not armed — set OSSE_SCHED=1")
    ctl, _failure = _run_one(fn, seed, preemption_bound)
    return list(ctl.trace)
