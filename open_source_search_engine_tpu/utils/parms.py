"""Typed parameter registry — the single source of truth for every knob.

Reference: ``Parms.cpp/h`` (23k LoC). One declarative ``Parm[]`` table maps
each parameter to its cgi name, xml tag, type, object offset, default and
flags; the table drives (a) config-file load/save, (b) the admin UI, (c) the
URL API, and (d) cluster-wide live parameter broadcast from host0
(``Parms.h:497`` ``broadcastParmList``, msgType 0x3f ``Parms.cpp:21683``).

Here the same single-table idea: :data:`PARMS` declares every parameter
once; :class:`Conf` (global scope, reference ``Conf.h:49`` / ``gb.conf``)
and :class:`CollectionConf` (per-collection, reference ``coll.conf`` /
``CollectionRec``) are dict-backed objects generated from it, with JSON
round-trip and an ``on_update`` hook. The cluster broadcast (0x3f) is
``parallel.cluster.ClusterClient.broadcast_parm`` /
``attach_conf``: sequenced updates delivered to every node through the
ordered retry-forever write queues and applied via ``/rpc/parm``
(persisted per node, so they survive restarts).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

GLOBAL = "global"
COLL = "coll"


@dataclass(frozen=True)
class Parm:
    """One row of the parameter table (reference ``Parms.h`` ``class Parm``)."""

    name: str              # python/config attribute name
    cgi: str               # URL-api query parameter name
    type: type             # bool / int / float / str
    default: Any
    scope: str             # GLOBAL (gb.conf) or COLL (coll.conf)
    desc: str = ""
    # reference PF_REBUILD/PF_NOSYNC-style flags
    broadcast: bool = True  # sync to cluster on change (0x3f equivalent)


def _p(name, cgi, typ, default, scope, desc="", broadcast=True) -> Parm:
    return Parm(name, cgi, typ, default, scope, desc, broadcast)


#: The parameter table. Kept deliberately small-but-real for round 1; grows
#: alongside features. Reference rows cited per entry.
PARMS: list[Parm] = [
    # --- global (Conf.h / gb.conf) ---
    _p("http_port", "hport", int, 8000, GLOBAL, "HTTP serving port (hosts.conf port column)"),
    _p("max_mem", "maxmem", int, 4 << 30, GLOBAL, "memory budget per instance (Conf::m_maxMem, Mem.cpp:255); enforced by utils.membudget"),
    _p("checkify", "checkify", bool, False, GLOBAL, "on-device checkify guardrails on kernel routes (query.devcheck; OSSE_CHECKIFY=1 equivalent)"),
    _p("num_shards", "nshards", int, 1, GLOBAL, "index shards == mesh size (hosts.conf 'index-splits:')"),
    _p("num_mirrors", "nmirrors", int, 0, GLOBAL, "replicas per shard (hosts.conf 'num-mirrors:', Hostdb.cpp:336)"),
    _p("working_dir", "wdir", str, "./data", GLOBAL, "data directory (hosts.conf 'working-dir:')"),
    _p("autosave_minutes", "autosave", int, 5, GLOBAL, "autosave frequency (Process.cpp:1299)"),
    _p("spider_enabled", "se", bool, True, GLOBAL, "master spider switch (Conf::m_spideringEnabled)"),
    _p("query_max_terms", "qmax", int, 64, GLOBAL, "max query terms (reference ABS_MAX_QUERY_TERMS=9000, Query.h:43; ours is the padded device width)"),
    _p("dns_servers", "dns", str, "", GLOBAL, "DNS resolver ips (Conf dns parms)"),
    _p("master_password", "mpwd", str, "", GLOBAL, "admin master password; empty = open (Conf::m_masterPwds, PageLogin)", broadcast=False),
    _p("ssl_cert", "sslcert", str, "", GLOBAL, "TLS certificate chain path (gb.pem role, TcpServer.cpp SSL) — empty serves plaintext", broadcast=False),
    _p("ssl_key", "sslkey", str, "", GLOBAL, "TLS private key path (empty = key inside ssl_cert)", broadcast=False),
    _p("serve_device", "sdev", bool, True, GLOBAL, "serve /search from the HBM-resident index with micro-batching (SURVEY §7.8 throughput mode)"),
    _p("serve_mesh", "smesh", bool, False, GLOBAL, "sharded instances serve /search through the mesh-resident path: one shard_map program per wave, Msg3a merge + site dedup in-jit (SURVEY §7 stage 4/5)"),
    _p("tenant_hot", "thot", int, 0, GLOBAL, "resident-tenant count bound for the tenancy plane's LRU hot set (serve.tenancy; addColl/delColl CollectionRec scale); 0 = unbounded"),
    _p("device_budget", "devbudget", int, 0, GLOBAL, "soft byte cap on the membudget 'device' label — HBM-resident tenant bases; breach parks cold tenants (membudget.cap_evict); 0 = uncapped"),
    _p("merge_quiet_hours", "mergehours", str, "", GLOBAL, "DailyMerge window (DailyMerge.h:11)"),
    _p("alert_cmd", "alertcmd", str, "", GLOBAL, "command run on host death/recovery with OSSE_ALERT_* env (PingServer.h:77 email/SMS role); empty = log only", broadcast=False),
    _p("trace_sample", "tsample", int, 64, GLOBAL, "head-sample 1 in N query traces (utils.trace, Dapper-style); 1 = every query, 0 = tracing off"),
    _p("slow_query_ms", "slowms", float, 1000.0, GLOBAL, "queries slower than this keep their trace regardless of sampling and land in slowlog.jsonl"),
    _p("shard_cache_ttl", "shcttl", float, 30.0, GLOBAL, "seconds a shard node caches /rpc/search replies (termlist-cache role, RdbCache); generation-invalidated on writes, 0 disables"),
    # --- per-collection (coll.conf / CollectionRec) ---
    _p("docs_wanted", "n", int, 10, COLL, "results per page (SearchInput 'n')"),
    _p("site_cluster", "sc", bool, True, COLL, "max-2-per-site clustering (Msg51/Clusterdb)"),
    _p("dedup_results", "dr", bool, True, COLL, "content-hash dedup of results (Msg40)"),
    _p("spider_max_pages", "maxpages", int, 0, COLL, "crawl page quota (CollectionRec::m_maxToCrawl)"),
    _p("spider_delay_ms", "sdelay", int, 1000, COLL, "same-IP politeness wait (Spider.cpp wait tree)"),
    _p("max_spiders", "maxspiders", int, 8, COLL, "concurrent fetches (Spider.h MAX_SPIDERS)"),
    _p("spider_proxies", "sproxies", str, "", COLL, "comma-separated crawl proxy host:port pool (SpiderProxy.h:27); empty = direct"),
    _p("lang_weight", "langw", float, 20.0, COLL, "same-language score boost (Posdb.cpp SAMELANGMULT)"),
    _p("title_max_len", "tml", int, 80, COLL, "title truncation (Title.cpp)"),
    _p("summary_excerpts", "ns", int, 3, COLL, "summary excerpt count (Summary.h)"),
    _p("pqr_enabled", "pqr", bool, True, COLL, "post-query rerank pass (PostQueryRerank.cpp)"),
    _p("result_cache_ttl", "rcttl", float, 10.0, COLL, "seconds to cache rendered result pages (Msg17/Msg40Cache); 0 disables"),
    _p("result_cache_swr", "rcswr", float, 0.0, COLL, "stale-while-revalidate window after result_cache_ttl expires: serve the stale page and refresh in the background (same generation only); 0 disables"),
    _p("pqr_lang_demote", "pqrlang", float, 0.8, COLL, "foreign-language demotion factor (m_pqr_demFactForeignLanguage)"),
    _p("pqr_site_demote", "pqrsite", float, 0.85, COLL, "per-extra-result same-domain demotion (PQR diversity role)"),
    _p("pqr_depth_demote", "pqrdepth", float, 0.97, COLL, "url path-depth demotion (prefer canonical pages)"),
    _p("autoban_qps", "abqps", int, 0, COLL, "per-IP query rate limit, 0 = off (AutoBan.cpp)"),
    _p("summary_max_len", "sml", int, 180, COLL, "summary length (Summary.h)"),
]

_BY_SCOPE: dict[str, dict[str, Parm]] = {GLOBAL: {}, COLL: {}}
_BY_CGI: dict[str, Parm] = {}
for parm in PARMS:
    _BY_SCOPE[parm.scope][parm.name] = parm
    _BY_CGI[parm.cgi] = parm


class _ParmObject:
    """Dict-backed object whose attributes are defined by the parm table."""

    _scope: str = GLOBAL

    def __init__(self, **overrides: Any):
        self._values: dict[str, Any] = {
            p.name: p.default for p in _BY_SCOPE[self._scope].values()
        }
        self._listeners: list[Callable[[str, Any], None]] = []
        for k, v in overrides.items():
            self.set(k, v)

    def __getattr__(self, name: str) -> Any:
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value: Any) -> None:
        # route parm names through set() so plain assignment can't shadow
        # the registry (conf.num_shards = 8 must behave like set())
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self.set(name, value)

    def set(self, name: str, value: Any, *, _from_sync: bool = False) -> None:
        parm = _BY_SCOPE[self._scope].get(name)
        if parm is None:
            raise KeyError(f"unknown parm {name!r} in scope {self._scope}")
        value = parm.type(value)
        self._values[name] = value
        if not _from_sync:
            for fn in self._listeners:
                fn(name, value)

    def on_update(self, fn: Callable[[str, Any], None]) -> None:
        """Register a live-update listener (the 0x3f broadcast hook)."""
        self._listeners.append(fn)

    def set_from_cgi(self, cgi: str, value: Any) -> None:
        """URL-api update: ``&maxmem=...`` (reference Pages/Parms URL api)."""
        parm = _BY_CGI.get(cgi)
        if parm is None or parm.scope != self._scope:
            raise KeyError(f"unknown cgi parm {cgi!r}")
        if parm.type is bool and isinstance(value, str):
            value = value not in ("0", "false", "False", "")
        self.set(parm.name, value)

    # --- config file round trip (gb.conf / coll.conf equivalent) ---
    def to_dict(self) -> dict[str, Any]:
        return dict(self._values)

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self._values, indent=2, sort_keys=True))

    def load(self, path: str | Path) -> None:
        for k, v in json.loads(Path(path).read_text()).items():
            if k in _BY_SCOPE[self._scope]:
                self.set(k, v, _from_sync=True)


class Conf(_ParmObject):
    """Global config (reference ``Conf.h:49``, file ``gb.conf``)."""

    _scope = GLOBAL


class CollectionConf(_ParmObject):
    """Per-collection config (reference ``CollectionRec``, file ``coll.conf``)."""

    _scope = COLL

    def __init__(self, name: str = "main", **overrides: Any):
        super().__init__(**overrides)
        self.__dict__["name"] = name


def parm_table() -> list[Parm]:
    """The full table — used by the admin UI to render parameter pages."""
    return list(PARMS)


def parm(name: str) -> Parm:
    """One parm's table entry by name (any scope)."""
    for scope in _BY_SCOPE.values():
        if name in scope:
            return scope[name]
    raise KeyError(f"unknown parm {name!r}")
