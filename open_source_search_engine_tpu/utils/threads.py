"""Named daemon-thread helpers — the one sanctioned way to spawn.

Reference: the gb binary had no anonymous threads — every worker was a
named loop registered with the Loop/BigFile thread queues, so a hung
process could always be diagnosed from a thread dump. Our reproduction
had drifted into a dozen ad-hoc ``threading.Thread(...)`` call sites,
some named, some not (an unnamed thread in a py-spy dump is a dead
end). Every spawn now flows through here; the ``thread-spawn`` osselint
rule keeps it that way.

All helper threads are daemons: background workers (SWR refreshes,
heartbeats, samplers) must never block interpreter exit — orderly
shutdown is the job of each owner's ``stop()``, not of ``join`` at
teardown.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from . import schedcheck as _schedcheck


def make_thread(name: str, target: Callable[..., Any], *args: Any,
                **kwargs: Any) -> threading.Thread:
    """A named daemon thread, NOT started (callers that must publish
    the Thread object before it runs — batch workers whose loop checks
    ``self._thread``). Inside an active ``schedcheck.explore`` the
    thread is a cooperatively scheduled one."""
    if _schedcheck._active is not None:
        sched = _schedcheck.maybe_thread(name, target, args, kwargs)
        if sched is not None:
            return sched
    return threading.Thread(target=target, args=args, kwargs=kwargs,
                            daemon=True, name=name)


def spawn(name: str, target: Callable[..., Any], *args: Any,
          **kwargs: Any) -> threading.Thread:
    """Create AND start a named daemon thread; returns it for joining."""
    t = make_thread(name, target, *args, **kwargs)
    t.start()
    return t
