"""Core runtime utilities (reference layer L0: Loop/Mem/Log/SafeBuf/types).

The reference's L0 is a single-threaded event loop plus hand-rolled memory
and file layers (``Loop.cpp``, ``Mem.cpp``, ``BigFile.cpp``). On the TPU
build the host runtime is ordinary Python/asyncio + numpy, so this package
only carries the pieces with real semantic content: the typed parameter
registry (``Parms.cpp`` equivalent), the 64-bit term hash, logging, and URL
handling.
"""
