"""Host-plane memory-budget governor — the Mem.cpp allocation gate.

Reference: the single ``gb`` binary enforces ``Conf::m_maxMem`` through
``g_mem`` (``Mem.cpp:255``): every large allocation registers with a
label, over-budget requests are REFUSED, and the caller degrades
(defer the merge, dump the tree, shed the batch) instead of letting
the kernel OOM-kill the process. This is that plane for the host side
of the TPU port: one process-wide :class:`MemBudget` (``g_membudget``)
keyed off the existing ``max_mem`` parm.

Two accounting styles, both counted against the one limit:

* **reservations** (``reserve``/``release``) — transient working sets
  with a clear lifetime: a merge's input+output arrays, a pack pass's
  padded device staging arrays, a build batch's concatenated key
  images. ``reserving()`` is the context-manager form.
* **gauges** (``set_gauge``) — long-lived structures that grow and
  shrink in place, keyed per owner: each Rdb reports its memtable
  bytes under the ``memtable`` label and the governor sums them.

On an over-budget ``reserve`` the governor first runs registered
**pressure handlers** (flush-the-memtable hooks — weakly referenced so
a dead Collection never pins memory or leaks handlers), re-checks, and
only then refuses. Every refusal bumps ``membudget.reject.<label>`` in
``g_stats`` (statsdb surfaces it) and the caller is expected to shrink
or defer — never to crash.

The device plane's twin is ``query/devcheck.py`` (checkify harness);
``/admin/mem`` serves :meth:`MemBudget.snapshot` live.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable

from .log import get_logger
from .stats import g_stats

log = get_logger("membudget")

#: default budget — the ``max_mem`` parm default (4 GB/instance,
#: Conf::m_maxMem); serve wiring overwrites it from the live conf
DEFAULT_LIMIT = 4 << 30

#: the per-subsystem labels the core planes report under (free-form
#: strings are accepted; these are the wired ones). "device" is the
#: tenant plane's HBM-resident index bytes (serve/tenancy.py).
LABELS = ("memtable", "merge", "pack", "docproc", "cache", "device")


class MemBudget:
    """Process-wide labeled memory budget with graceful refusal."""

    def __init__(self, limit: int = DEFAULT_LIMIT):
        self._lock = threading.Lock()
        self.limit = int(limit)
        #: label -> reserved bytes (transient working sets)
        self._reserved: dict[str, int] = {}
        #: label -> {owner key -> bytes} (long-lived gauges)
        self._gauges: dict[str, dict[object, int]] = {}
        #: label -> soft cap in bytes (set_label_cap); breaching a cap
        #: runs the pressure pass scoped to that label rather than
        #: refusing — the "device" cap bounds the resident tenant set
        #: independently of the global limit
        self._caps: dict[str, int] = {}
        #: label -> refusal count (mirrors the g_stats counters)
        self.rejections: dict[str, int] = {}
        self.high_water = 0
        #: (priority, seq, key, weak fn) — run ascending by priority
        #: until the budget fits, so cheap shedders (cold tenants) go
        #: before expensive ones (the cache plane)
        self._pressure: list[tuple] = []
        self._pressure_seq = 0
        #: labels with a cap-relief pass in flight (a handler that
        #: zeroes gauges re-enters set_gauge; the guard stops the
        #: recursion, not the relief)
        self._relieving: set[str] = set()

    # --- limit -----------------------------------------------------------

    def set_limit(self, limit: int) -> None:
        """Re-point the budget (the max_mem parm live-update hook)."""
        with self._lock:
            self.limit = max(int(limit), 1)

    def set_label_cap(self, label: str, nbytes: int) -> None:
        """Soft cap for ONE label, independent of the global limit
        (0/negative clears). Breaching it triggers a label-scoped
        pressure pass (``membudget.cap_evict``) instead of a refusal —
        the device label's cap is how the tenant plane sizes its hot
        set."""
        with self._lock:
            if int(nbytes) <= 0:
                self._caps.pop(label, None)
                return
            self._caps[label] = int(nbytes)
        g_stats.gauge(f"membudget.cap.{label}", int(nbytes))

    def label_cap(self, label: str) -> int:
        """The label's soft cap, 0 = uncapped."""
        with self._lock:
            return self._caps.get(label, 0)

    # --- accounting ------------------------------------------------------

    def _used_locked(self) -> int:
        return (sum(self._reserved.values())
                + sum(sum(g.values()) for g in self._gauges.values()))

    def used(self, label: str | None = None) -> int:
        with self._lock:
            if label is None:
                return self._used_locked()
            return (self._reserved.get(label, 0)
                    + sum(self._gauges.get(label, {}).values()))

    def free(self) -> int:
        with self._lock:
            return max(self.limit - self._used_locked(), 0)

    def would_fit(self, nbytes: int) -> bool:
        with self._lock:
            return self._used_locked() + int(nbytes) <= self.limit

    def set_gauge(self, label: str, key: object, nbytes: int) -> None:
        """Absolute usage of one owner under a label (0 removes it).
        ``key`` is any hashable owner identity (an Rdb's dir path).
        Pushing a capped label over its soft cap runs the label-scoped
        pressure pass (counted ``membudget.cap_evict``)."""
        with self._lock:
            g = self._gauges.setdefault(label, {})
            if nbytes <= 0:
                g.pop(key, None)
            else:
                g[key] = int(nbytes)
            self.high_water = max(self.high_water, self._used_locked())
            cap = self._caps.get(label, 0)
            over = (cap > 0 and label not in self._relieving
                    and self._label_used_locked(label) > cap)
            if over:
                self._relieving.add(label)
        if over:
            try:
                g_stats.count("membudget.cap_evict")
                g_stats.count(f"membudget.cap_evict.{label}")
                with self._lock:
                    excess = self._label_used_locked(label) - cap
                self._relieve(max(excess, 1), label=label)
            finally:
                with self._lock:
                    self._relieving.discard(label)

    def _label_used_locked(self, label: str) -> int:
        return (self._reserved.get(label, 0)
                + sum(self._gauges.get(label, {}).values()))

    def _label_fits_locked(self, label: str) -> bool:
        cap = self._caps.get(label, 0)
        return cap <= 0 or self._label_used_locked(label) <= cap

    def reserve(self, label: str, nbytes: int) -> bool:
        """Claim ``nbytes`` under ``label``; False = over budget (after
        pressure relief) and the caller must degrade. Zero/negative
        requests always succeed (and claim nothing)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        from .chaos import g_chaos
        if g_chaos.enabled and \
                g_chaos.decide("membudget.reserve", key=label):
            # forced pressure: the shed-before-refuse path must run
            # even when the budget would have fit
            self._relieve(nbytes)
        def _fits_locked() -> bool:
            if self._used_locked() + nbytes > self.limit:
                return False
            cap = self._caps.get(label, 0)
            return cap <= 0 or \
                self._label_used_locked(label) + nbytes <= cap

        with self._lock:
            fits = _fits_locked()
            globally = self._used_locked() + nbytes <= self.limit
        if not fits:
            # a label-cap-only breach relieves scoped to the label;
            # a global breach runs the full ladder
            self._relieve(nbytes, label=None if not globally else label)
            with self._lock:
                fits = _fits_locked()
        if not fits:
            with self._lock:
                self.rejections[label] = \
                    self.rejections.get(label, 0) + 1
            g_stats.count("membudget.reject")
            g_stats.count(f"membudget.reject.{label}")
            log.warning(
                "over budget: %s wants %d MB, %d MB free of %d MB — "
                "refusing (caller degrades)", label, nbytes >> 20,
                self.free() >> 20, self.limit >> 20)
            return False
        with self._lock:
            self._reserved[label] = \
                self._reserved.get(label, 0) + nbytes
            self.high_water = max(self.high_water, self._used_locked())
        return True

    def release(self, label: str, nbytes: int) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            cur = self._reserved.get(label, 0)
            self._reserved[label] = max(cur - nbytes, 0)

    class _Reservation:
        def __init__(self, budget: "MemBudget", label: str, n: int):
            self.budget, self.label, self.n = budget, label, n
            self.granted = False

        def __enter__(self):
            self.granted = self.budget.reserve(self.label, self.n)
            return self.granted

        def __exit__(self, *exc):
            if self.granted:
                self.budget.release(self.label, self.n)
            return False

    def reserving(self, label: str, nbytes: int) -> "_Reservation":
        """``with g_membudget.reserving("merge", est) as ok:`` —
        releases on exit when granted; ``ok`` is the grant."""
        return MemBudget._Reservation(self, label, int(nbytes))

    # --- pressure relief -------------------------------------------------

    def add_pressure_handler(
            self, fn: Callable[[int], int], priority: int = 100,
            key: str | None = None) -> None:
        """Register a memory-freeing hook run before a refusal:
        ``fn(need_bytes) -> freed_bytes_hint``. Bound methods are held
        through ``weakref.WeakMethod`` so registering never pins the
        owner (a test's ShardedCollection must be collectable).

        Handlers run in ascending ``priority`` order and the pass stops
        as soon as the budget fits — the tenant plane registers at a
        LOW priority so device pressure sheds cold tenants before the
        cache plane flushes anything. ``key`` makes registration
        idempotent (re-adding the same key replaces the old entry —
        singletons re-attach safely after a ``reset()``)."""
        with self._lock:
            try:
                ref: object = weakref.WeakMethod(fn)  # bound method
            except TypeError:
                ref = weakref.ref(fn) if hasattr(fn, "__name__") \
                    else (lambda: fn)
            if key is not None:
                self._pressure = [e for e in self._pressure
                                  if e[2] != key]
            self._pressure_seq += 1
            self._pressure.append(
                (int(priority), self._pressure_seq, key, ref))

    def _relieve(self, need: int, label: str | None = None) -> None:
        """The shed pass: handlers ascending by priority, stopping the
        moment the budget (or, for a cap breach, the label) fits —
        cheap shedders spare expensive ones. At least one handler
        always runs (chaos-forced pressure exercises the pass even
        when the reservation would fit)."""
        with self._lock:
            entries = sorted(self._pressure, key=lambda e: (e[0], e[1]))
        dead = []
        ran = 0
        for entry in entries:
            fn = entry[3]()
            if fn is None:
                dead.append(entry)  # owner collected: drop the handler
                continue
            try:
                fn(need)
            except Exception as e:  # noqa: BLE001 — relief best-effort
                log.warning("pressure handler failed: %s", e)
            ran += 1
            with self._lock:
                fits = self._label_fits_locked(label) if label \
                    else self._used_locked() + need <= self.limit
            if fits:
                break
        if dead:
            with self._lock:
                self._pressure = [e for e in self._pressure
                                  if e not in dead]

    # --- introspection (/admin/mem) -------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            labels: dict[str, dict] = {}
            for lb in sorted(set(self._reserved)
                             | set(self._gauges)
                             | set(self.rejections) | set(LABELS)):
                labels[lb] = {
                    "reserved": self._reserved.get(lb, 0),
                    "gauged": sum(
                        self._gauges.get(lb, {}).values()),
                    "rejections": self.rejections.get(lb, 0),
                    "cap": self._caps.get(lb, 0),
                }
            used = self._used_locked()
            return {
                "limit": self.limit,
                "used": used,
                "free": max(self.limit - used, 0),
                "high_water": self.high_water,
                "rejections": sum(self.rejections.values()),
                "labels": labels,
            }

    def reset(self) -> None:
        """Drop all accounting (test isolation; the limit stays)."""
        with self._lock:
            self._reserved.clear()
            self._gauges.clear()
            self._caps.clear()
            self.rejections.clear()
            self.high_water = 0
            self._pressure = []
            self._relieving.clear()


#: process-wide singleton (reference ``g_mem``)
g_membudget = MemBudget()
