"""Host-plane memory-budget governor — the Mem.cpp allocation gate.

Reference: the single ``gb`` binary enforces ``Conf::m_maxMem`` through
``g_mem`` (``Mem.cpp:255``): every large allocation registers with a
label, over-budget requests are REFUSED, and the caller degrades
(defer the merge, dump the tree, shed the batch) instead of letting
the kernel OOM-kill the process. This is that plane for the host side
of the TPU port: one process-wide :class:`MemBudget` (``g_membudget``)
keyed off the existing ``max_mem`` parm.

Two accounting styles, both counted against the one limit:

* **reservations** (``reserve``/``release``) — transient working sets
  with a clear lifetime: a merge's input+output arrays, a pack pass's
  padded device staging arrays, a build batch's concatenated key
  images. ``reserving()`` is the context-manager form.
* **gauges** (``set_gauge``) — long-lived structures that grow and
  shrink in place, keyed per owner: each Rdb reports its memtable
  bytes under the ``memtable`` label and the governor sums them.

On an over-budget ``reserve`` the governor first runs registered
**pressure handlers** (flush-the-memtable hooks — weakly referenced so
a dead Collection never pins memory or leaks handlers), re-checks, and
only then refuses. Every refusal bumps ``membudget.reject.<label>`` in
``g_stats`` (statsdb surfaces it) and the caller is expected to shrink
or defer — never to crash.

The device plane's twin is ``query/devcheck.py`` (checkify harness);
``/admin/mem`` serves :meth:`MemBudget.snapshot` live.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable

from .log import get_logger
from .stats import g_stats

log = get_logger("membudget")

#: default budget — the ``max_mem`` parm default (4 GB/instance,
#: Conf::m_maxMem); serve wiring overwrites it from the live conf
DEFAULT_LIMIT = 4 << 30

#: the per-subsystem labels the core planes report under (free-form
#: strings are accepted; these are the wired ones)
LABELS = ("memtable", "merge", "pack", "docproc", "cache")


class MemBudget:
    """Process-wide labeled memory budget with graceful refusal."""

    def __init__(self, limit: int = DEFAULT_LIMIT):
        self._lock = threading.Lock()
        self.limit = int(limit)
        #: label -> reserved bytes (transient working sets)
        self._reserved: dict[str, int] = {}
        #: label -> {owner key -> bytes} (long-lived gauges)
        self._gauges: dict[str, dict[object, int]] = {}
        #: label -> refusal count (mirrors the g_stats counters)
        self.rejections: dict[str, int] = {}
        self.high_water = 0
        #: weakly-held callables ``fn(need_bytes) -> freed_bytes_hint``
        self._pressure: list[object] = []

    # --- limit -----------------------------------------------------------

    def set_limit(self, limit: int) -> None:
        """Re-point the budget (the max_mem parm live-update hook)."""
        with self._lock:
            self.limit = max(int(limit), 1)

    # --- accounting ------------------------------------------------------

    def _used_locked(self) -> int:
        return (sum(self._reserved.values())
                + sum(sum(g.values()) for g in self._gauges.values()))

    def used(self, label: str | None = None) -> int:
        with self._lock:
            if label is None:
                return self._used_locked()
            return (self._reserved.get(label, 0)
                    + sum(self._gauges.get(label, {}).values()))

    def free(self) -> int:
        with self._lock:
            return max(self.limit - self._used_locked(), 0)

    def would_fit(self, nbytes: int) -> bool:
        with self._lock:
            return self._used_locked() + int(nbytes) <= self.limit

    def set_gauge(self, label: str, key: object, nbytes: int) -> None:
        """Absolute usage of one owner under a label (0 removes it).
        ``key`` is any hashable owner identity (an Rdb's dir path)."""
        with self._lock:
            g = self._gauges.setdefault(label, {})
            if nbytes <= 0:
                g.pop(key, None)
            else:
                g[key] = int(nbytes)
            self.high_water = max(self.high_water, self._used_locked())

    def reserve(self, label: str, nbytes: int) -> bool:
        """Claim ``nbytes`` under ``label``; False = over budget (after
        pressure relief) and the caller must degrade. Zero/negative
        requests always succeed (and claim nothing)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        from .chaos import g_chaos
        if g_chaos.enabled and \
                g_chaos.decide("membudget.reserve", key=label):
            # forced pressure: the shed-before-refuse path must run
            # even when the budget would have fit
            self._relieve(nbytes)
        with self._lock:
            fits = self._used_locked() + nbytes <= self.limit
        if not fits:
            self._relieve(nbytes)
            with self._lock:
                fits = self._used_locked() + nbytes <= self.limit
        if not fits:
            with self._lock:
                self.rejections[label] = \
                    self.rejections.get(label, 0) + 1
            g_stats.count("membudget.reject")
            g_stats.count(f"membudget.reject.{label}")
            log.warning(
                "over budget: %s wants %d MB, %d MB free of %d MB — "
                "refusing (caller degrades)", label, nbytes >> 20,
                self.free() >> 20, self.limit >> 20)
            return False
        with self._lock:
            self._reserved[label] = \
                self._reserved.get(label, 0) + nbytes
            self.high_water = max(self.high_water, self._used_locked())
        return True

    def release(self, label: str, nbytes: int) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            cur = self._reserved.get(label, 0)
            self._reserved[label] = max(cur - nbytes, 0)

    class _Reservation:
        def __init__(self, budget: "MemBudget", label: str, n: int):
            self.budget, self.label, self.n = budget, label, n
            self.granted = False

        def __enter__(self):
            self.granted = self.budget.reserve(self.label, self.n)
            return self.granted

        def __exit__(self, *exc):
            if self.granted:
                self.budget.release(self.label, self.n)
            return False

    def reserving(self, label: str, nbytes: int) -> "_Reservation":
        """``with g_membudget.reserving("merge", est) as ok:`` —
        releases on exit when granted; ``ok`` is the grant."""
        return MemBudget._Reservation(self, label, int(nbytes))

    # --- pressure relief -------------------------------------------------

    def add_pressure_handler(
            self, fn: Callable[[int], int]) -> None:
        """Register a memory-freeing hook run before a refusal:
        ``fn(need_bytes) -> freed_bytes_hint``. Bound methods are held
        through ``weakref.WeakMethod`` so registering never pins the
        owner (a test's ShardedCollection must be collectable)."""
        with self._lock:
            try:
                ref: object = weakref.WeakMethod(fn)  # bound method
            except TypeError:
                ref = weakref.ref(fn) if hasattr(fn, "__name__") \
                    else (lambda: fn)
            self._pressure.append(ref)

    def _relieve(self, need: int) -> None:
        with self._lock:
            refs = list(self._pressure)
        live = []
        for ref in refs:
            fn = ref()
            if fn is None:
                continue  # owner collected: drop the handler
            live.append(ref)
            try:
                fn(need)
            except Exception as e:  # noqa: BLE001 — relief best-effort
                log.warning("pressure handler failed: %s", e)
        with self._lock:
            self._pressure = live

    # --- introspection (/admin/mem) -------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            labels: dict[str, dict] = {}
            for lb in sorted(set(self._reserved)
                             | set(self._gauges)
                             | set(self.rejections) | set(LABELS)):
                labels[lb] = {
                    "reserved": self._reserved.get(lb, 0),
                    "gauged": sum(
                        self._gauges.get(lb, {}).values()),
                    "rejections": self.rejections.get(lb, 0),
                }
            used = self._used_locked()
            return {
                "limit": self.limit,
                "used": used,
                "free": max(self.limit - used, 0),
                "high_water": self.high_water,
                "rejections": sum(self.rejections.values()),
                "labels": labels,
            }

    def reset(self) -> None:
        """Drop all accounting (test isolation; the limit stays)."""
        with self._lock:
            self._reserved.clear()
            self._gauges.clear()
            self.rejections.clear()
            self.high_water = 0
            self._pressure = []


#: process-wide singleton (reference ``g_mem``)
g_membudget = MemBudget()
