"""jitwatch — runtime compile / retrace / host-transfer attribution.

The static half of the jit plane (the ``jit-*`` osselint family) bans
the trace-discipline hazards it can see in the AST; this module is the
runtime half: it watches what JAX actually *does* and attributes every
compile, retrace and host transfer to a ``(function, shape-signature,
call-site)`` key, so a steady-state latency cliff (the Gigablast
analog: a Msg39 spike when a query shape misses every warm plan) names
the line that caused it instead of showing up as anonymous tail
latency.

Capture channels (all restored exactly on :func:`disable`):

* ``jax._src.pjit``'s ``TRACING CACHE MISS at <site> because: ...``
  explanations (gated on the ``jax_explain_cache_misses`` config,
  flipped on while enabled) — these carry the jit call site and the
  miss category, distinguishing a cold first trace from a genuine
  retrace.
* ``jax._src.interpreters.pxla``'s ``Compiling <fn> with global shapes
  and types [...]`` records — emitted at DEBUG even when
  ``jax_log_compiles`` is off, so a DEBUG-level handler sees every
  backend compile without changing global logging behavior.
* ``jax._src.dispatch``'s ``Finished tracing + transforming`` records
  — per-trace durations.
* Wrappers around ``jax.device_put`` / ``jax.device_get`` — the
  explicit transfer guard. JAX's own ``transfer_guard("log")`` writes
  from C++ straight to stderr where Python cannot observe it, so the
  blessed transfer entry points are wrapped instead, plus a
  best-effort ``__array__`` patch that catches explicit
  ``device_x.__array__()`` materialization.

Counters feed ``g_stats`` (``jit.compiles``, ``jit.retrace.<site>``,
``jit.transfer.<site>``) and each event drops a zero-width span into
the tracing plane so a sampled trace shows *where inside the request*
the compile landed. ``OSSE_JITWATCH=1`` turns the watcher on via
:func:`maybe_enable` (wired into the device layer import and the
server); with the variable unset this module is inert — importing it
touches neither jax config nor any logger.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
import traceback
from dataclasses import dataclass, field
from pathlib import Path

from . import trace
from .stats import g_stats

#: loggers whose records carry the compile/retrace story
_JAX_LOGGERS = ("jax._src.pjit", "jax._src.interpreters.pxla",
                "jax._src.dispatch")

#: repo-relative module suffixes that OWN device↔host traffic — a
#: transfer attributed elsewhere is a hot-path violation (mirrors
#: osselint's _JIT_TRANSFER_BOUNDARY)
BOUNDARY_SITES = ("query/devindex.py", "query/scorer.py",
                  "parallel/sharded.py", "build/devbuild.py")

_PKG_ROOT = Path(__file__).resolve().parent.parent
_SELF_FILE = str(Path(__file__).resolve())

_MISS_RE = re.compile(
    r"TRACING CACHE MISS at ([^\s]+):(\d+) \(([^)]*)\) because:")
_COMPILE_RE = re.compile(
    r"Compiling ([^\s]+) with global shapes and types \[(.*?)\]\.",
    re.DOTALL)
_TRACED_RE = re.compile(
    r"Finished tracing \+ transforming (\S+) for pjit in "
    r"([0-9.eE+-]+) sec")


@dataclass
class Event:
    """One attributed compile/retrace/transfer bucket."""
    kind: str            # compile | first_trace | retrace | transfer
    fn: str              # jitted function (or transfer entry point)
    shapes: str          # shape signature ("" when unknown)
    site: str            # file.py:line, repo-relative when possible
    count: int = 0
    bytes: int = 0       # transfers only
    last: str = ""       # last explanation / direction

    def as_dict(self) -> dict:
        return {"kind": self.kind, "fn": self.fn,
                "shapes": self.shapes, "site": self.site,
                "count": self.count, "bytes": self.bytes,
                "boundary": is_boundary_site(self.site),
                "last": self.last}


def is_boundary_site(site: str) -> bool:
    """Does ``site`` live in a module blessed to touch the host?"""
    path = site.rsplit(":", 1)[0]
    return path.endswith(BOUNDARY_SITES)


def _norm_site(filename: str, lineno: int) -> str:
    try:
        rel = Path(filename).resolve().relative_to(_PKG_ROOT)
        return f"{rel.as_posix()}:{lineno}"
    except ValueError:
        return f"{Path(filename).name}:{lineno}"


def _caller_site() -> str:
    """First stack frame outside jitwatch, jax and the stdlib — the
    repo line that triggered the event."""
    for fr in reversed(traceback.extract_stack()):
        fn = fr.filename
        if fn == _SELF_FILE or "site-packages" in fn \
                or "/lib/python" in fn or fn.startswith("<"):
            continue
        return _norm_site(fn, fr.lineno or 0)
    return "unknown:0"


def _nbytes(x) -> int:
    try:
        import jax
        return int(sum(getattr(leaf, "nbytes", 0) or 0
                       for leaf in jax.tree_util.tree_leaves(x)))
    except Exception:
        g_stats.count("jit.nbytes_errors")
        return 0


class _Handler(logging.Handler):
    def __init__(self, watch: "JitWatch"):
        super().__init__(level=logging.DEBUG)
        self._watch = watch

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._watch._on_record(record)
        except Exception:
            # a broken parse must never take down the jit under watch
            g_stats.count("jit.watch_errors")


class JitWatch:
    """Singleton attribution table; enable()/disable() are idempotent
    and restore every hook they install."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        self.events: dict[tuple, Event] = {}
        self.totals = {"compiles": 0, "first_traces": 0,
                       "retraces": 0, "transfers": 0,
                       "transfers_offboundary": 0}
        self._handler = _Handler(self)
        self._saved_loggers: dict[str, tuple[int, bool]] = {}
        self._saved_explain: bool | None = None
        self._orig_put = None
        self._orig_get = None
        self._orig_array = None
        self._array_cls = None
        self._tl = threading.local()

    # -- lifecycle ----------------------------------------------------

    def enable(self) -> None:
        with self._lock:
            if self.enabled:
                return
            import jax
            self._saved_explain = bool(
                jax.config.jax_explain_cache_misses)
            jax.config.update("jax_explain_cache_misses", True)
            for name in _JAX_LOGGERS:
                lg = logging.getLogger(name)
                self._saved_loggers[name] = (lg.level, lg.propagate)
                lg.setLevel(logging.DEBUG)
                lg.addHandler(self._handler)
                # keep the DEBUG firehose out of the app log while we
                # watch; restored on disable
                lg.propagate = False
            self._orig_put, self._orig_get = (jax.device_put,
                                              jax.device_get)
            orig_put, orig_get = self._orig_put, self._orig_get

            def device_put(*args, **kwargs):
                self._note_transfer("device_put", "h2d", args)
                self._tl.explicit = True
                try:
                    return orig_put(*args, **kwargs)
                finally:
                    self._tl.explicit = False

            def device_get(*args, **kwargs):
                self._note_transfer("device_get", "d2h", args)
                self._tl.explicit = True
                try:
                    return orig_get(*args, **kwargs)
                finally:
                    self._tl.explicit = False

            jax.device_put, jax.device_get = device_put, device_get
            self._patch_array()
            self.enabled = True
            g_stats.gauge("jit.watch_enabled", 1)

    def _patch_array(self) -> None:
        """Best-effort implicit-transfer tripwire for explicit
        ``dev_x.__array__()`` calls. ``np.array``/``np.asarray`` reach
        the data through C-level slots a class-attribute patch cannot
        see — which is exactly why the jit-implicit-transfer static
        rule exists for those spellings."""
        try:
            from jax._src.array import ArrayImpl
            orig = ArrayImpl.__array__

            def patched(arr, *a, **k):
                if not getattr(self._tl, "explicit", False):
                    self._note_transfer("__array__", "d2h-implicit",
                                        arr)
                return orig(arr, *a, **k)

            ArrayImpl.__array__ = patched
            self._array_cls, self._orig_array = ArrayImpl, orig
        except Exception:
            g_stats.count("jit.array_patch_failed")

    def disable(self) -> None:
        with self._lock:
            if not self.enabled:
                return
            import jax
            jax.config.update("jax_explain_cache_misses",
                              self._saved_explain)
            for name, (level, prop) in self._saved_loggers.items():
                lg = logging.getLogger(name)
                lg.removeHandler(self._handler)
                lg.setLevel(level)
                lg.propagate = prop
            self._saved_loggers.clear()
            jax.device_put, jax.device_get = (self._orig_put,
                                              self._orig_get)
            if self._array_cls is not None:
                self._array_cls.__array__ = self._orig_array
                self._array_cls = self._orig_array = None
            self.enabled = False
            g_stats.gauge("jit.watch_enabled", 0)

    def reset(self) -> None:
        """Drop the attribution table (counters in g_stats persist —
        the bench snapshots deltas instead)."""
        with self._lock:
            self.events.clear()
            for k in self.totals:
                self.totals[k] = 0

    # -- event plumbing ----------------------------------------------

    def _bump(self, kind: str, fn: str, shapes: str, site: str,
              nbytes: int = 0, last: str = "") -> Event:
        key = (kind, fn, shapes, site)
        with self._lock:
            ev = self.events.get(key)
            if ev is None:
                ev = self.events[key] = Event(kind, fn, shapes, site)
            ev.count += 1
            ev.bytes += nbytes
            if last:
                ev.last = last[:400]
        return ev

    def _on_record(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        m = _COMPILE_RE.search(msg)
        if m:
            now = time.perf_counter()
            site = _caller_site()
            self._bump("compile", m.group(1), m.group(2)[:200], site)
            with self._lock:
                self.totals["compiles"] += 1
            g_stats.count("jit.compiles")
            trace.record("jit.compile", now, now, fn=m.group(1),
                         site=site)
            return
        m = _MISS_RE.search(msg)
        if m:
            now = time.perf_counter()
            site = _norm_site(m.group(1), int(m.group(2)))
            fn = m.group(3)
            # keep the category line ("never seen input type
            # signature…"), drop the MISS header
            why = msg.split("because:", 1)[-1].strip()
            if "never seen function" in msg:
                self._bump("first_trace", fn, "", site, last=why)
                with self._lock:
                    self.totals["first_traces"] += 1
                g_stats.count("jit.first_traces")
            else:
                self._bump("retrace", fn, "", site, last=why)
                with self._lock:
                    self.totals["retraces"] += 1
                g_stats.count("jit.retraces")
                g_stats.count(f"jit.retrace.{site}")
                trace.record("jit.retrace", now, now, fn=fn,
                             site=site)
            return
        m = _TRACED_RE.search(msg)
        if m:
            g_stats.record_ms("jit.trace_ms",
                              1000.0 * float(m.group(2)))

    def _note_transfer(self, fn: str, direction: str, args) -> None:
        now = time.perf_counter()
        site = _caller_site()
        nbytes = _nbytes(args)
        self._bump("transfer", fn, "", site, nbytes=nbytes,
                   last=direction)
        offb = not is_boundary_site(site)
        with self._lock:
            self.totals["transfers"] += 1
            if offb:
                self.totals["transfers_offboundary"] += 1
        g_stats.count("jit.transfers")
        g_stats.count(f"jit.transfer.{site}")
        trace.record("jit.transfer", now, now, fn=fn, site=site,
                     direction=direction, bytes=nbytes)

    # -- reporting ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            events = sorted(self.events.values(),
                            key=lambda e: -e.count)
            return {"enabled": self.enabled,
                    "totals": dict(self.totals),
                    "events": [e.as_dict() for e in events]}


g_jitwatch = JitWatch()


def enable() -> None:
    g_jitwatch.enable()


def disable() -> None:
    g_jitwatch.disable()


def enabled() -> bool:
    return g_jitwatch.enabled


def reset() -> None:
    g_jitwatch.reset()


def snapshot() -> dict:
    return g_jitwatch.snapshot()


def maybe_enable() -> None:
    """Enable iff OSSE_JITWATCH=1 — the import-time wiring used by the
    device layer and the server; a true no-op otherwise."""
    if os.environ.get("OSSE_JITWATCH", "") == "1":
        enable()
