"""DNS resolver — wire-protocol client with per-record TTLs.

Reference: ``Dns.cpp`` (3.1k LoC, ``Dns.h:131``): the spider runs its
OWN resolver — iterative root→TLD→authority walk, an RdbCache of
records with real TTLs, in-flight dedup, and strict timeout budgets —
because ``getaddrinfo`` gives a crawler no TTL control, no timeout
budget, and one blocking slot per lookup.

This module speaks the DNS wire format over UDP (stdlib sockets only):

* **query** A records against configured servers (``dns_servers``
  parm) with a per-try timeout and a total per-lookup budget;
* **parse** answers including compressed names, CNAME chains (followed
  up to a bounded depth) and referrals;
* **iterative mode**: when a server answers with a referral
  (authority NS + glue A records, no answer), the walk follows it —
  the root→TLD→authority descent — up to a bounded depth;
* **cache** every A record under ITS OWN TTL (clamped to sane bounds),
  negative answers under a short TTL;
* **in-flight dedup** so a burst of lookups for one host costs one
  query (ipresolve's dedup covers the first-ip path; this covers
  direct users).

``ipresolve.first_ip`` prefers this resolver when servers are
configured and falls back to the OS resolver otherwise, so air-gapped
test runs keep working.
"""

from __future__ import annotations

import os
import secrets
import socket
import struct
import threading
import time

from .log import get_logger

log = get_logger("dns")

#: per-try socket timeout and the whole-lookup budget (Dns.cpp bounds
#: each trip and the overall walk)
TRY_TIMEOUT_S = 1.5
TOTAL_BUDGET_S = 5.0
#: TTL clamps: never cache longer than a day, never shorter than 10 s
TTL_MIN_S, TTL_MAX_S = 10.0, 86400.0
NEGATIVE_TTL_S = 60.0
MAX_CNAME_DEPTH = 8
MAX_REFERRAL_DEPTH = 8

QTYPE_A = 1
QTYPE_NS = 2
QTYPE_CNAME = 5


def build_query(name: str, qid: int, qtype: int = QTYPE_A,
                recurse: bool = True) -> bytes:
    """One DNS question packet (RFC 1035 §4)."""
    flags = 0x0100 if recurse else 0x0000  # RD bit
    out = struct.pack(">HHHHHH", qid, flags, 1, 0, 0, 0)
    for label in name.strip(".").split("."):
        lb = label.encode("idna") if not label.isascii() \
            else label.encode()
        out += bytes([len(lb)]) + lb
    out += b"\x00" + struct.pack(">HH", qtype, 1)
    return out


def _read_name(pkt: bytes, off: int) -> tuple[str, int]:
    """Decode a (possibly compressed) name; returns (name, next_off)."""
    labels: list[str] = []
    jumps = 0
    next_off = None
    while True:
        if off >= len(pkt):
            raise ValueError("truncated name")
        ln = pkt[off]
        if ln & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(pkt):
                raise ValueError("truncated pointer")
            ptr = ((ln & 0x3F) << 8) | pkt[off + 1]
            if next_off is None:
                next_off = off + 2
            off = ptr
            jumps += 1
            if jumps > 32:
                raise ValueError("pointer loop")
            continue
        if ln == 0:
            off += 1
            break
        labels.append(pkt[off + 1: off + 1 + ln].decode(
            "ascii", "replace"))
        off += 1 + ln
    return ".".join(labels).lower(), (next_off if next_off is not None
                                      else off)


def parse_response(pkt: bytes) -> dict:
    """→ {id, rcode, answers: [(name, type, ttl, data)], authority:
    [...], additional: [...]} — data is an IP string for A, a name for
    NS/CNAME, raw bytes otherwise."""
    if len(pkt) < 12:
        raise ValueError("short packet")
    qid, flags, qd, an, ns, ar = struct.unpack(">HHHHHH", pkt[:12])
    off = 12
    for _ in range(qd):  # skip questions
        _, off = _read_name(pkt, off)
        off += 4
    out = {"id": qid, "rcode": flags & 0xF, "answers": [],
           "authority": [], "additional": []}
    for section, count in (("answers", an), ("authority", ns),
                           ("additional", ar)):
        for _ in range(count):
            name, off = _read_name(pkt, off)
            if off + 10 > len(pkt):
                raise ValueError("truncated rr")
            rtype, rclass, ttl, rdlen = struct.unpack(
                ">HHIH", pkt[off: off + 10])
            off += 10
            rdata = pkt[off: off + rdlen]
            if rtype == QTYPE_A and rdlen == 4:
                data = socket.inet_ntoa(rdata)
            elif rtype in (QTYPE_NS, QTYPE_CNAME):
                data, _ = _read_name(pkt, off)
            else:
                data = rdata
            off += rdlen
            out[section].append((name, rtype, int(ttl), data))
    return out


class DnsResolver:
    """A-record resolver over the configured servers.

    ``iterative=True`` starts at the given servers as roots and
    follows referrals (the reference's root walk); the default mode
    sets RD and lets a recursive upstream do the walk, which is what
    a crawl box with a local caching resolver wants."""

    def __init__(self, servers: list[str] | None = None,
                 iterative: bool = False, port: int = 53):
        env = os.environ.get("OSSE_DNS_SERVERS", "")
        self.servers = list(servers or
                            [s for s in env.split(",") if s])
        self.iterative = iterative
        self.port = port
        #: host→ip answers on the cache plane (the Msg13 DNS-cache
        #: slice of RdbCache); per-entry TTL from the A record,
        #: negative answers cached briefly as None — hence lookup()'s
        #: (hit, value) form rather than get()
        from ..cache import g_cacheplane
        self._cache = g_cacheplane.register(
            "dns", ttl_s=TTL_MAX_S, max_entries=200_000,
            desc="A-record answers incl. negatives (Msg13 DNS cache)")
        self._inflight: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._rr = 0  # server round-robin cursor

    # -- cache ----------------------------------------------------------

    def _cache_get(self, host: str) -> tuple[bool, str | None]:
        return self._cache.lookup(host)

    def _cache_put(self, host: str, ip: str | None, ttl: float) -> None:
        ttl = min(max(ttl, TTL_MIN_S), TTL_MAX_S) if ip is not None \
            else NEGATIVE_TTL_S
        self._cache.put(host, ip, ttl_s=ttl)

    # -- wire -----------------------------------------------------------

    def _ask(self, server: str, name: str, deadline: float,
             recurse: bool) -> dict | None:
        qid = secrets.randbelow(1 << 16)
        pkt = build_query(name, qid, recurse=recurse)
        timeout = min(TRY_TIMEOUT_S, max(deadline - time.monotonic(),
                                         0.05))
        try:
            with socket.socket(socket.AF_INET,
                               socket.SOCK_DGRAM) as s:
                s.settimeout(timeout)
                host, _, prt = server.partition(":")
                s.sendto(pkt, (host, int(prt) if prt else self.port))
                while True:
                    data, _ = s.recvfrom(4096)
                    resp = parse_response(data)
                    if resp["id"] == qid:  # ignore spoofed/stale ids
                        return resp
        except Exception:  # noqa: BLE001 — timeout, net error, parse
            return None

    # -- resolution -----------------------------------------------------

    def resolve(self, host: str,
                budget_s: float | None = None) -> str | None:
        """First A record for host, or None (negative answers cache
        briefly). Bounded by ``budget_s`` (default TOTAL_BUDGET_S)
        wall time — shared across CNAME hops and glueless-referral
        sub-lookups."""
        budget = budget_s if budget_s is not None else TOTAL_BUDGET_S
        host = host.strip(".").lower()
        hit, ip = self._cache_get(host)
        if hit:
            return ip
        with self._lock:
            ev = self._inflight.get(host)
            if ev is None:
                ev = self._inflight[host] = threading.Event()
                owner = True
            else:
                owner = False
        if not owner:
            ev.wait(budget + 1.0)
            hit, ip = self._cache_get(host)
            return ip
        try:
            ip, ttl = self._resolve_uncached(
                host, time.monotonic() + budget)
            self._cache_put(host, ip, ttl)
            return ip
        finally:
            with self._lock:
                self._inflight.pop(host, None)
            ev.set()

    def _resolve_uncached(self, host: str,
                          deadline: float) -> tuple[str | None, float]:
        name = host
        servers = list(self.servers)
        if not servers:
            return None, 0.0
        for _ in range(MAX_CNAME_DEPTH):
            resp = self._walk(name, servers, deadline)
            if resp is None:
                return None, 0.0
            a = [(n, t, ttl, d) for n, t, ttl, d in resp["answers"]
                 if t == QTYPE_A and n == name]
            if a:
                return a[0][3], float(a[0][2])
            cn = [(ttl, d) for n, t, ttl, d in resp["answers"]
                  if t == QTYPE_CNAME and n == name]
            if cn:
                name = cn[0][1]
                # A records for the target may ride the same response
                a2 = [(ttl, d) for n, t, ttl, d in resp["answers"]
                      if t == QTYPE_A and n == name]
                if a2:
                    return a2[0][1], float(a2[0][0])
                continue
            return None, 0.0
        return None, 0.0

    def _walk(self, name: str, servers: list[str],
              deadline: float) -> dict | None:
        """One query in recursive mode; the referral-following
        root→TLD→authority descent in iterative mode."""
        if not self.iterative:
            for i in range(len(servers)):
                if time.monotonic() >= deadline:
                    return None
                server = servers[(self._rr + i) % len(servers)]
                resp = self._ask(server, name, deadline, recurse=True)
                if resp is not None and resp["rcode"] in (0, 3):
                    self._rr = (self._rr + i + 1) % len(servers)
                    return resp
            return None
        cur = list(servers)
        for _ in range(MAX_REFERRAL_DEPTH):
            resp = None
            for server in cur:
                if time.monotonic() >= deadline:
                    return None
                resp = self._ask(server, name, deadline, recurse=False)
                if resp is not None and resp["rcode"] in (0, 3):
                    break
            if resp is None:
                return None
            if resp["answers"] or resp["rcode"] == 3:
                return resp
            # referral: NS in authority + glue A in additional
            ns_names = [d for _, t, _, d in resp["authority"]
                        if t == QTYPE_NS]
            glue = [d for n, t, _, d in resp["additional"]
                    if t == QTYPE_A and n in ns_names]
            if not glue:
                # glueless referral: resolve one NS name under the
                # SAME deadline (a fresh budget per nesting level
                # would let adversarial zones stall the spider N×5s)
                nxt = None
                for nsn in ns_names[:2]:
                    nxt = self._resolve_uncached(nsn, deadline)[0] \
                        if time.monotonic() < deadline else None
                    if nxt:
                        break
                if not nxt:
                    return None
                glue = [nxt]
            cur = glue
        return None
