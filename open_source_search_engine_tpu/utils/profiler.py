"""Sampling profiler — where is the process spending its time?

Reference: ``Profiler.cpp/h`` — a SIGPROF-style sampler
(``startRealTimeProfiler`` ``Profiler.cpp:1586`` arms ``setitimer``;
``getStackFrame`` ``Profiler.cpp:1446`` walks the stack into a buffer
rendered by the profiler admin page) plus the quickpoll-miss tracker
naming functions that hog the event loop.

Here: a sampler THREAD walks every Python thread's current frame stack
via ``sys._current_frames()`` at a fixed rate and aggregates
(function, file:line) self/cumulative hit counts — the same product as
the reference's IP-buffer histogram, without signals (signal-based
sampling can't interrupt C extensions portably; a thread sees exactly
the frames the GIL publishes). Rendered by ``/admin/profiler``.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter

from . import threads
from .log import get_logger

log = get_logger("profiler")


class SamplingProfiler:
    """Start/stop stack sampler with per-function hit aggregation."""

    def __init__(self, interval_s: float = 0.01):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples = 0
        #: sampler-loop exceptions survived (visible in report())
        self.sample_errors = 0
        #: (func, file, line of def) → self-time hits (top of stack)
        self.self_hits: Counter = Counter()
        #: same key → cumulative hits (anywhere on stack)
        self.cum_hits: Counter = Counter()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _sample_once(self) -> None:
        me = threading.get_ident()
        for tid, frame in list(sys._current_frames().items()):
            if tid == me:
                continue
            self.samples += 1
            seen = set()
            top = True
            while frame is not None:
                code = frame.f_code
                key = (code.co_name, code.co_filename, code.co_firstlineno)
                if top:
                    self.self_hits[key] += 1
                    top = False
                if key not in seen:  # recursion: one cum hit per sample
                    self.cum_hits[key] += 1
                    seen.add(key)
                frame = frame.f_back

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self._sample_once()
                except Exception as exc:  # noqa: BLE001 — keep sampling
                    self.sample_errors += 1
                    log.debug("profiler sample failed: %s", exc)

        self._thread = threads.spawn("profiler", loop)
        log.info("sampling profiler started (%.0f Hz)",
                 1.0 / self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(1.0)
            self._thread = None

    def reset(self) -> None:
        self.samples = 0
        self.sample_errors = 0
        self.self_hits.clear()
        self.cum_hits.clear()

    def report(self, top: int = 30) -> dict:
        """The profiler page payload: top functions by self and by
        cumulative samples (fractions of total)."""
        total = max(self.samples, 1)

        def rows(counter):
            return [{
                "func": k[0],
                "where": f"{k[1]}:{k[2]}",
                "hits": n,
                "frac": round(n / total, 4),
            } for k, n in counter.most_common(top)]
        return {"samples": self.samples, "running": self.running,
                "interval_ms": self.interval_s * 1000,
                "top_self": rows(self.self_hits),
                "top_cumulative": rows(self.cum_hits)}


#: process-wide instance (the reference's g_profiler)
g_profiler = SamplingProfiler()
