"""Niceness gate — the UdpProtocol.h niceness bit for the HTTP planes.

Background (niceness-1) requests wait — bounded — while interactive
(niceness-0) requests are in flight; interactive work never waits.
Shared by the public search server and the cluster node RPC server so
spider writes and heal pulls yield to queries on BOTH planes.
"""

from __future__ import annotations

import threading
import time


class NicenessGate:
    def __init__(self, max_wait_s: float = 2.0):
        self.max_wait_s = max_wait_s
        self._cv = threading.Condition()
        self._n0 = 0

    @property
    def interactive_inflight(self) -> int:
        return self._n0

    def enter(self, niceness: int) -> None:
        """Call before handling a request. Interactive requests are
        counted; background ones block (up to ``max_wait_s`` — bounded
        so background work cannot starve forever) while any
        interactive request is in flight."""
        if niceness <= 0:
            with self._cv:
                self._n0 += 1
            return
        deadline = time.monotonic() + self.max_wait_s
        with self._cv:
            while self._n0 > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)

    def exit(self, niceness: int) -> None:
        if niceness <= 0:
            with self._cv:
                self._n0 -= 1
                if self._n0 <= 0:
                    self._cv.notify_all()
