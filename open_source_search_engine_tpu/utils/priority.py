"""Request priority tiers — the vocabulary the admission plane speaks.

Reference: the engine survived open-internet load by treating traffic
classes differently — AutoBan rate-limited abusive sources, the
niceness bit (``UdpProtocol.h``) made spider work yield to queries, and
``maxQueryTime`` bounded what one query could cost. This module is the
shared, layering-safe half of that story: tier names, the
``X-OSSE-Priority`` header that carries a request's tier through
scatter legs, and the contextvar binding the transport reads when it
stamps outbound RPCs. The gate that *enforces* tiers lives in
``serve/admission.py``; ``parallel/`` and ``query/`` only ever need
this module, so the dependency arrow keeps pointing downward.

Tiers, highest priority first:

* ``interactive`` — a human waiting on a SERP; never queues behind the
  other tiers.
* ``suggest`` — typeahead/completion traffic: latency-sensitive but
  individually cheap and abandonable.
* ``crawlbot`` — bulk/background clients (spiders, batch exports); the
  first tier shed under overload, mapped to niceness 1 on the node
  planes so it also yields inside each host.
"""

from __future__ import annotations

import contextlib
import contextvars

#: highest priority first — wake/shed order is exactly this tuple
TIERS: tuple[str, ...] = ("interactive", "suggest", "crawlbot")

#: scatter legs carry the front door's verdict on this header (like
#: X-OSSE-Deadline carries the budget and X-OSSE-Trace the span)
PRIORITY_HEADER = "X-OSSE-Priority"

#: the tenant (collection owner) a request bills against — the
#: admission plane's weighted-fair ledger key, carried across wire
#: legs exactly like the tier so a scatter leg sheds against the same
#: quota its coordinator would
TENANT_HEADER = "X-OSSE-Tenant"

#: tier -> the niceness bit the node planes honor (crawlbot work yields
#: to interactive inside each host, not just at the front door)
_TIER_NICENESS = {"interactive": 0, "suggest": 0, "crawlbot": 1}

_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "osse-priority-tier", default=None)

_tenant_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "osse-priority-tenant", default=None)


class QueueFull(RuntimeError):
    """A bounded admission/dispatch queue refused an enqueue — the
    overload signal the serve edge turns into shed-stale-or-503
    (distinct from a timeout: no work was started at all)."""


def current_tier() -> str | None:
    """The tier bound to this context, or None outside a request."""
    return _ctx.get()


@contextlib.contextmanager
def bind_tier(tier: str | None):
    """Bind ``tier`` for the duration: every outbound RPC inside stamps
    it on :data:`PRIORITY_HEADER` so shard nodes honor the front door's
    classification."""
    tok = _ctx.set(tier)
    try:
        yield
    finally:
        _ctx.reset(tok)


def tier_from_header(value: str | None) -> str | None:
    """Parse an ``X-OSSE-Priority`` header; unknown/absent -> None
    (the receiver falls back to its own classification)."""
    v = (value or "").strip().lower()
    return v if v in TIERS else None


def current_tenant() -> str | None:
    """The tenant bound to this context, or None outside a request."""
    return _tenant_ctx.get()


@contextlib.contextmanager
def bind_tenant(tenant: str | None):
    """Bind the billing tenant for the duration; outbound RPCs stamp
    it on :data:`TENANT_HEADER` (the quota analog of tier)."""
    tok = _tenant_ctx.set(tenant)
    try:
        yield
    finally:
        _tenant_ctx.reset(tok)


def tenant_from_header(value: str | None) -> str | None:
    """Parse an ``X-OSSE-Tenant`` header; absent/oversized -> None.
    Tenant names are free-form collection names, so only length is
    policed (a hostile header must not mint unbounded counter keys)."""
    v = (value or "").strip()
    return v[:64] if v else None


def tier_niceness(tier: str | None) -> int:
    """The niceness bit a tier rides on the node planes."""
    return _TIER_NICENESS.get(tier or "", 0)


def classify(query: dict, niceness: int = 0,
             header_tier: str | None = None) -> str:
    """Front-door classification. Precedence: an explicit ``tier=``
    request param, then the propagated header (a scatter leg keeps its
    coordinator's verdict), then the niceness bit (background callers
    already self-identify), else interactive — misclassifying *up* is
    safer than starving a human."""
    explicit = tier_from_header(str(query.get("tier", "")))
    if explicit is not None:
        return explicit
    if header_tier in TIERS:
        return header_tier
    if niceness > 0:
        return "crawlbot"
    return "interactive"
