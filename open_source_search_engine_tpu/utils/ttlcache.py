"""General TTL record cache — the RdbCache role.

The reference's ``RdbCache`` is the one cache class behind DNS,
robots.txt, termlists, title recs and the Msg17 result cache. The
specialized caches here grew ad hoc (termlist LRU, robots TTL, DNS
TTL); this is the GENERAL form for new consumers: keyed TTL entries,
bounded size with stalest-half eviction, thread-safe, with optional
version tagging so a whole generation can be invalidated in O(1)
(the Rdb-version trick the termlist cache uses).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Hashable


class TtlCache:
    def __init__(self, ttl_s: float = 3600.0, max_entries: int = 4096):
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._d: dict[Hashable, tuple[float, int, Any]] = {}
        self._lock = threading.Lock()
        self._version = 0
        self.hits = 0
        self.misses = 0

    def bump_version(self) -> None:
        """Invalidate every current entry in O(1) (new generation)."""
        with self._lock:
            self._version += 1

    def get(self, key: Hashable):
        now = time.monotonic()
        with self._lock:
            hit = self._d.get(key)
            if hit is None or hit[0] < now or hit[1] != self._version:
                self.misses += 1
                return None
            self.hits += 1
            return hit[2]

    def put(self, key: Hashable, value: Any,
            ttl_s: float | None = None) -> None:
        now = time.monotonic()
        with self._lock:
            if len(self._d) >= self.max_entries:
                # dead-generation and already-expired entries are free
                # wins — drop them before sacrificing live ones
                dead = [k for k, (exp, ver, _) in self._d.items()
                        if exp < now or ver != self._version]
                for k in dead:
                    del self._d[k]
                if len(self._d) >= self.max_entries:
                    for k in sorted(self._d,
                                    key=lambda k: self._d[k][0])[
                            : self.max_entries // 2]:
                        del self._d[k]
            self._d[key] = (now + (ttl_s if ttl_s is not None
                                   else self.ttl_s),
                            self._version, value)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            # "live" counts only what a get() could still return —
            # tombstones from bump_version() must not inflate gauges
            live = sum(1 for exp, ver, _ in self._d.values()
                       if exp >= now and ver == self._version)
            return {"entries": len(self._d), "live": live,
                    "hits": self.hits, "misses": self.misses,
                    "version": self._version}
