"""Chaos plane — deterministic, seedable fault injection.

The reference engine's defining property was survival: years of
crawl/index/serve across flaky hosts, with twin failover, Rdb CRC
quarantine, and OOM-deferred merges absorbing the failures. We own the
same planes (hedged transport, cache shedding, scrub, the resident
loop) — this module is how we *prove* they compose, by injecting the
ancestral faults on demand:

==================  =====================================================
injection point     faults (Gigablast ancestor)
==================  =====================================================
transport.request   drop / delay / refuse / blackhole a scatter leg
                    (dead host in the Msg39 scatter)
cluster.node        kill / wedge / slowwalk a shard node mid-query
                    (the wedged-twin EWMA case)
rdb.read            flip bytes in a posting run on disk so CRC verify /
                    scrub must trip (corrupt RdbMap)
membudget.reserve   force a pressure pass so caches shed before work is
                    refused (the OOM merge defer)
resident.loop       stall a wave / drop a collect
fleet               REAL process faults on a spawned node: kill
                    (SIGKILL — recovery is journal replay, not a
                    politely-stopped server) / wedge (SIGSTOP — the
                    held-reply case, the hedge must eat it)
==================  =====================================================

Arming: ``OSSE_CHAOS=<seed>`` in the environment (``maybe_enable`` at
import of the device layer and the servers), or ``g_chaos.enable(seed)``
programmatically. Off is a **true no-op** exactly like jitwatch: the
only cost on a hot path is one attribute check (``g_chaos.enabled``) —
every seam guards its call with that flag.

Determinism: a decision is a pure function of ``(seed, point name,
per-point call index)`` via sha256 — no shared RNG stream, so the same
seed and the same per-point call sequence replay the same fault
schedule regardless of how threads interleave *across* points. Every
fired fault counts under ``chaos.<point>.<kind>`` in g_stats.
"""

from __future__ import annotations

import hashlib
import os
import time

from .lockcheck import make_lock
from .log import get_logger
from .stats import g_stats

log = get_logger("chaos")


class ChaosError(RuntimeError):
    """An injected fault — distinguishable from a real one in tests and
    telemetry, and handled by the same recovery paths."""


#: point name → fault kinds it can fire (the registry; rates start at 0
#: until enable() arms them)
DEFAULT_POINTS: dict[str, tuple[str, ...]] = {
    "transport.request": ("drop", "delay", "refuse", "blackhole"),
    "cluster.node": ("slowwalk", "wedge", "kill"),
    "rdb.read": ("flipbyte",),
    "membudget.reserve": ("pressure",),
    "resident.loop": ("stall", "drop_collect"),
    "fleet": ("kill", "wedge"),
}


class _Point:
    __slots__ = ("name", "kinds", "rate", "match", "delay_s", "calls",
                 "fired")

    def __init__(self, name: str, kinds: tuple[str, ...]):
        self.name = name
        self.kinds = kinds
        self.rate = 0.0
        #: substring filter on the decide() key ("" matches everything)
        self.match = ""
        self.delay_s = 0.05
        self.calls = 0
        self.fired: dict[str, int] = {}


class ChaosPlane:
    """Singleton (:data:`g_chaos`). Inert until armed."""

    def __init__(self):
        self.enabled = False
        self.seed: int | None = None
        self._lock = make_lock("chaos.plane")
        self._points: dict[str, _Point] = {}
        self._fresh_points()

    def _fresh_points(self) -> None:
        self._points = {n: _Point(n, k) for n, k in
                        DEFAULT_POINTS.items()}

    # --- arming -----------------------------------------------------------

    def enable(self, seed: int, rate: float = 0.1) -> None:
        """Arm every point at ``rate``; idempotent re-arms reset the
        per-point call counters so the schedule replays from the top."""
        with self._lock:
            self.seed = int(seed)
            self._fresh_points()
            for p in self._points.values():
                p.rate = float(rate)
            self.enabled = True
        g_stats.gauge("chaos.enabled", 1)
        log.info("chaos plane armed (seed=%d rate=%.3f)", seed, rate)

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self.seed = None
            self._fresh_points()
        g_stats.gauge("chaos.enabled", 0)

    def configure(self, point: str, rate: float | None = None,
                  kinds: tuple[str, ...] | None = None,
                  match: str | None = None,
                  delay_s: float | None = None) -> None:
        """Narrow one point: its fault rate, the kinds it may fire, a
        substring the decide() key must contain (e.g. one twin's
        ``host:port``), and the sleep used by delay-ish kinds. Tests
        and the soak use this to aim faults."""
        with self._lock:
            p = self._points[point]
            if rate is not None:
                p.rate = float(rate)
            if kinds is not None:
                p.kinds = tuple(kinds)
            if match is not None:
                p.match = match
            if delay_s is not None:
                p.delay_s = float(delay_s)

    def fired(self, point: str | None = None) -> dict:
        """Per-kind fire counts (one point, or all points nested)."""
        with self._lock:
            if point is not None:
                return dict(self._points[point].fired)
            return {n: dict(p.fired) for n, p in self._points.items()}

    # --- the decision function --------------------------------------------

    def decide(self, point: str, key: str = "") -> str | None:
        """None (no fault) or a fault kind. Pure in ``(seed, point,
        call#)``: the hash — not shared RNG state — makes the schedule
        replayable under threading."""
        p = self._points.get(point)
        if p is None or p.rate <= 0.0:
            return None
        with self._lock:
            n = p.calls
            p.calls += 1
        if p.match and p.match not in key:
            return None
        h = hashlib.sha256(
            f"{self.seed}:{point}:{n}".encode()).digest()
        if int.from_bytes(h[:8], "big") / 2.0 ** 64 >= p.rate:
            return None
        kind = p.kinds[int.from_bytes(h[8:12], "big") % len(p.kinds)]
        with self._lock:
            p.fired[kind] = p.fired.get(kind, 0) + 1
        g_stats.count(f"chaos.{point}.{kind}")
        return kind

    # --- seam helpers (each called only behind an `enabled` check) --------

    def leg_fault(self, addr: str, path: str, timeout: float) -> None:
        """transport.request: raise (drop/refuse/blackhole) or sleep
        (delay) as if the wire did it. Refusal raises a real
        ConnectionRefusedError so the transport's fast-fail path is the
        one exercised."""
        kind = self.decide("transport.request", key=f"{addr}{path}")
        if kind is None:
            return
        p = self._points["transport.request"]
        if kind == "delay":
            time.sleep(p.delay_s)
            return
        if kind == "refuse":
            raise ConnectionRefusedError(
                f"chaos: refused {addr}{path}")
        if kind == "blackhole":
            # the worst dead-host mode: silence, then failure — held to
            # a bounded slice of the leg timeout so tests stay fast
            time.sleep(min(float(timeout), p.delay_s * 10.0))
        raise ChaosError(f"chaos: {kind} {addr}{path}")

    def node_fault(self, node) -> None:
        """cluster.node: slow-walk (small sleep), wedge (long sleep),
        or kill (stop the server from a side thread; the in-flight
        reply is severed and the client's hedge eats it)."""
        kind = self.decide("cluster.node",
                           key=str(getattr(node, "port", "")))
        if kind is None:
            return
        p = self._points["cluster.node"]
        if kind == "slowwalk":
            time.sleep(p.delay_s)
            return
        if kind == "wedge":
            time.sleep(p.delay_s * 20.0)
            return
        from . import threads
        threads.spawn("chaos-kill", node.stop)
        # hold the in-flight reply past the hedge leash: a kill is not
        # a clean error — the socket goes silent, and the client's
        # hedge (not an instant error-failover) is what must eat it
        time.sleep(p.delay_s * 10.0)
        raise ChaosError("chaos: node killed mid-query")

    def rdb_fault(self, rdb) -> None:
        """rdb.read: corrupt one on-disk run so the CRC planes (load
        verify / scrub) must trip before those bytes are trusted
        again."""
        if self.decide("rdb.read",
                       key=getattr(rdb, "name", "")) == "flipbyte":
            self.corrupt_one_run(rdb)

    def corrupt_one_run(self, rdb) -> str | None:
        """Flip one byte of one loaded run on disk (deterministic pick
        from the seed). Returns the path touched, or None when the rdb
        has no on-disk runs. The scrub/verify plane — not this — is
        responsible for noticing."""
        runs = [r for r in getattr(rdb, "runs", [])
                if getattr(r, "path", None) is not None]
        if not runs:
            return None
        h = hashlib.sha256(
            f"{self.seed}:flip:{len(runs)}".encode()).digest()
        run = runs[int.from_bytes(h[:4], "big") % len(runs)]
        fname = "data.npy" if run.data is not None else "keys.npy"
        target = run.path / fname
        size = os.path.getsize(target)
        if size < 256:
            return None
        # stay past the .npy header; flip mid-payload
        off = 192 + int.from_bytes(h[4:8], "big") % (size - 256)
        with open(target, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
        g_stats.count("chaos.rdb.corrupted")
        log.info("chaos: flipped byte %d of %s", off, target)
        return str(target)

    def fleet_fault(self, pid: int, key: str = "") -> str | None:
        """fleet: a REAL signal to a spawned node process — ``kill``
        is SIGKILL (no atexit, no save; the node's next life must
        recover every acked write from its journal) and ``wedge`` is
        SIGSTOP (sockets stay open, replies never come — the
        transport's hedge timer, not an error failover, has to eat the
        in-flight requests). Returns the kind fired, or None."""
        import signal

        kind = self.decide("fleet", key=key or str(pid))
        if kind is None:
            return None
        sig = signal.SIGKILL if kind == "kill" else signal.SIGSTOP
        try:
            os.kill(int(pid), sig)
            log.info("chaos: fleet %s pid=%d", kind, pid)
        except ProcessLookupError:
            log.warning("chaos: fleet %s pid=%d already gone", kind,
                        pid)
        return kind

    def fleet_resume(self, pid: int) -> None:
        """SIGCONT a wedged node (the operator un-sticking a host)."""
        import signal

        try:
            os.kill(int(pid), signal.SIGCONT)
        except ProcessLookupError:
            pass

    def resident_fault(self, where: str) -> None:
        """resident.loop: stall an issue/collect, or drop a collect
        (raises; the loop fails that wave's tickets and the layer above
        — hedge, retry — recovers)."""
        kind = self.decide("resident.loop", key=where)
        if kind is None:
            return
        if kind == "stall":
            time.sleep(self._points["resident.loop"].delay_s)
            return
        if where == "collect":
            raise ChaosError("chaos: collect dropped")


#: process-wide plane (jitwatch-style: module import costs nothing,
#: arming is explicit)
g_chaos = ChaosPlane()


def maybe_enable() -> bool:
    """Arm from ``OSSE_CHAOS=<seed>`` if set (call at server startup —
    never on a hot path). Returns True when armed.

    ``OSSE_CHAOS_RATE`` (float) overrides the default ambient fault
    rate — the fleet supervisor hands children ``OSSE_CHAOS`` with
    rate 0 so their seams are armed and replayable but only faults the
    parent *aims* (via configure()/the fleet seams) ever fire."""
    v = os.environ.get("OSSE_CHAOS", "")
    if not v:
        return False
    try:
        seed = int(v)
    except ValueError:
        log.warning("OSSE_CHAOS=%r is not an integer seed; ignored", v)
        return False
    try:
        rate = float(os.environ.get("OSSE_CHAOS_RATE", "0.1"))
    except ValueError:
        rate = 0.1
    g_chaos.enable(seed, rate=rate)
    return True
