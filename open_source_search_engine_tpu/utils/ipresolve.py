"""First-IP resolution — the crawl plane's DNS seam.

Reference: the spider keys everything by **firstIP** (the first A record
of a url's host): spiderdb records (``Spider.h:99-108``), shard
ownership (``Hostdb.cpp:~2526``) and per-IP politeness all hang off it.
The reference runs its own recursive resolver (``Dns.cpp``, 3.1k LoC —
root→TLD walk, RdbCache-backed, in-flight dedup); here the OS resolver
does the walk and this module supplies the pieces the crawler needs
around it: a TTL cache, in-flight dedup (concurrent lookups of one host
collapse into one), an injectable resolver for tests/air-gapped runs,
and a deterministic fallback pseudo-IP when resolution fails — so
sharding and politeness stay stable even offline (every scheduler maps
an unresolvable host to the same pseudo-IP).
"""

from __future__ import annotations

import socket
import threading
import time

from . import ghash, threads
from .log import get_logger

log = get_logger("ipresolve")

#: resolution cache TTL (the reference caches DNS in an RdbCache with
#: its own TTL; 1h matches its default dns cache behavior)
TTL_S = 3600.0

_cache: dict[str, tuple[str, float]] = {}
_inflight: dict[str, threading.Event] = {}
_lock = threading.Lock()

#: test/offline hook: set to a callable host → ip-string
resolver_override = None

_wire = None


def _wire_resolver():
    """The wire-protocol DnsResolver when DNS servers are configured
    (OSSE_DNS_SERVERS env / dns_servers parm); None = OS resolver."""
    global _wire
    if _wire is None:
        import os

        from .dnsresolver import DnsResolver
        servers = [s for s in
                   os.environ.get("OSSE_DNS_SERVERS", "").split(",")
                   if s]
        _wire = DnsResolver(servers) if servers else False
    return _wire or None


def _pseudo_ip(host: str) -> str:
    """Deterministic fallback for unresolvable hosts: a reserved-range
    pseudo-IP derived from the host hash. Sharding and politeness stay
    consistent cluster-wide (every node derives the same value); the
    0.x.x.x prefix can never collide with a real routable first-IP."""
    h = ghash.hash64(host)
    return f"0.{(h >> 16) & 0xFF}.{(h >> 8) & 0xFF}.{h & 0xFF}"


def first_ip(host: str, timeout: float = 5.0) -> str:
    """The host's first A record, TTL-cached, lookup-deduped."""
    now = time.monotonic()
    with _lock:
        hit = _cache.get(host)
        if hit is not None and hit[1] > now:
            return hit[0]
        ev = _inflight.get(host)
        if ev is None:
            ev = _inflight[host] = threading.Event()
            owner = True
        else:
            owner = False
    if not owner:
        # wait past the owner's own lookup bound: the owner ALWAYS
        # caches something (real IP or pseudo) and sets the event, so
        # the waiter nearly always reads the same value the owner
        # cached — a split (waiter pseudo vs owner real) only happens
        # if this wait itself expires, and downstream consumers carry
        # the doled first_ip rather than re-resolving
        ev.wait(timeout + 1.0)
        with _lock:
            hit = _cache.get(host)
        return hit[0] if hit is not None else _pseudo_ip(host)
    try:
        if resolver_override is not None:
            ip = resolver_override(host)
        elif (wire := _wire_resolver()) is not None:
            # configured DNS servers → the wire resolver owns the
            # lookup (per-record TTLs, timeout budget, Dns.cpp role)
            ip = wire.resolve(host, budget_s=timeout) \
                or _pseudo_ip(host)
        else:
            # getaddrinfo has no timeout parameter and can hang for
            # minutes on a broken resolver path — bound it with a
            # daemon thread (the reference's Dns.cpp owns its own UDP
            # timeouts; riding the OS resolver costs us this dance)
            box: list[str] = []

            def _lookup() -> None:
                try:
                    box.append(socket.getaddrinfo(
                        host, None, family=socket.AF_INET,
                        type=socket.SOCK_STREAM)[0][4][0])
                except Exception as exc:  # noqa: BLE001 — NXDOMAIN etc.
                    log.debug("getaddrinfo(%s) failed: %s", host, exc)
            t = threads.spawn(f"dns-{host[:24]}", _lookup)
            t.join(timeout)
            ip = box[0] if box else _pseudo_ip(host)
    except Exception:  # noqa: BLE001 — unresolvable host
        ip = _pseudo_ip(host)
    finally:
        with _lock:
            if len(_cache) > 65536:
                _cache.clear()
            _cache[host] = (ip, now + TTL_S)
            _inflight.pop(host, None)
        ev.set()
    return ip


def clear_cache() -> None:
    global _wire
    with _lock:
        _cache.clear()
        _inflight.clear()
        _wire = None  # re-read OSSE_DNS_SERVERS on next lookup
