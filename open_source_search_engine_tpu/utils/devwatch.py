"""devwatch — the device telemetry plane: HBM ledger, wave flight
recorder, roofline attribution.

jitwatch (the runtime half of the jit plane) watches what JAX *does* —
compiles, retraces, host transfers. This module watches what the
device *holds* and what the waves *cost*, the layer tracing (host
spans) and fleet metrics (host counters) both stop short of:

* **HBM ledger** — every long-lived ``device_put`` in the device
  layers (devindex columns, devbuild staging, mesh shard staging)
  registers its buffer under a ``(collection, plane, column)`` label.
  The ledger is the number the tenant plane's byte-bounded residency
  reasons about (the membudget "device" label's source of truth when
  enabled), reconciles against ``device.memory_stats()`` where the
  backend exposes it (TPU yes, CPU returns None), and exports
  ``hbm.<plane>.bytes`` gauges so ``/metrics`` can scrape per-plane
  residency fleet-wide.
* **Wave flight recorder** — a bounded ring of per-wave records from
  the resident loop (single-chip DeviceIndex waves and MeshServeIndex
  shard_map waves ride the same hooks): issue→dispatch→collect timing
  split, per-round device time and fetched bytes, escalation reissues,
  and the modeled ``wave_bytes_per_query`` next to what the round
  actually moved. Each wave also drops a device-tagged span into the
  trace plane, so a sampled trace shows the wave *inside* the request.
* **Roofline attribution** — at first dispatch of each (kernel, shape
  bucket), pull ``.cost_analysis()`` (flops / bytes accessed) from the
  compiled executable, compute arithmetic intensity, and issue a
  bandwidth-bound / compute-bound verdict against the backend's peak
  numbers. This is the instrument the fused-Pallas footprint items
  use to prove a wave-bytes delta instead of asserting one.

``OSSE_DEVWATCH=1`` turns the plane on via :func:`maybe_enable`
(wired into the device-layer imports and the server, next to
jitwatch); with the variable unset every hook is a guarded early
return — importing this module touches nothing and the hot path pays
one attribute load per call site.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import trace
from .stats import g_stats

#: flight-recorder ring bound — old waves fall off, the plane never
#: grows with uptime
RING = int(os.environ.get("OSSE_DEVWATCH_RING", "256"))

#: published peak (dense-matmul FLOP/s, HBM bytes/s) per TPU
#: generation — matched by substring against ``device_kind``. The
#: roofline ridge (flops/bw) splits bandwidth-bound from
#: compute-bound; exact peaks matter less than which side of the
#: ridge a kernel lands on.
_TPU_PEAKS = (
    ("v5 lite", 197e12, 819e9, "tpu-v5e"),
    ("v5e", 197e12, 819e9, "tpu-v5e"),
    ("v5p", 459e12, 2765e9, "tpu-v5p"),
    ("v6", 918e12, 1640e9, "tpu-v6e"),
    ("v4", 275e12, 1228e9, "tpu-v4"),
    ("v3", 123e12, 900e9, "tpu-v3"),
    ("v2", 45e12, 700e9, "tpu-v2"),
)

#: order-of-magnitude host numbers for the CPU fallback — labeled
#: assumed so nobody reads a CI-box verdict as a chip verdict
_CPU_PEAKS = (2e11, 4e10, "cpu (assumed)")


def _nbytes(a) -> int:
    """Bytes of one registered buffer — accepts a device array, a
    numpy array, or a plain int."""
    if isinstance(a, int):
        return a
    try:
        return int(a.nbytes)
    except Exception:
        try:
            n = 1
            for s in a.shape:
                n *= int(s)
            return n * a.dtype.itemsize
        except Exception:
            g_stats.count("devwatch.nbytes_errors")
            return 0


class DevWatch:
    """Singleton telemetry plane; enable()/disable() are idempotent
    flag flips — unlike jitwatch there is nothing to patch, every
    capture point is an explicit hook in the device layers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        #: (collection, plane) -> {column: bytes}
        self.ledger: dict[tuple[str, str], dict[str, int]] = {}
        self._planes: set[str] = set()
        #: bounded flight-recorder ring
        self.waves: deque = deque(maxlen=RING)
        #: (kernel, bucket-tuple) -> roofline entry
        self.costs: dict[tuple[str, tuple], dict] = {}
        self.totals = {"waves": 0, "wave_errors": 0, "rounds": 0}
        self.wave_seq = 0
        self._peaks: dict | None = None
        self._tl = threading.local()

    # -- lifecycle ----------------------------------------------------

    def enable(self) -> None:
        with self._lock:
            if self.enabled:
                return
            self.enabled = True
        g_stats.gauge("devwatch.enabled", 1)

    def disable(self) -> None:
        with self._lock:
            if not self.enabled:
                return
            self.enabled = False
        g_stats.gauge("devwatch.enabled", 0)

    def reset(self) -> None:
        """Drop ledger, ring and cost table (g_stats counters persist —
        benches snapshot deltas instead)."""
        with self._lock:
            self.ledger.clear()
            self.waves.clear()
            self.costs.clear()
            for k in self.totals:
                self.totals[k] = 0
            self.wave_seq = 0
        self._export_gauges()

    # -- HBM ledger ---------------------------------------------------

    def note_columns(self, coll: str, plane: str, columns: dict) -> None:
        """Register (replace) the whole (collection, plane) slice —
        the device-index refresh path: one call after every rebuild
        covers base, delta and regrow identically."""
        if not self.enabled:
            return
        sizes = {str(k): _nbytes(v) for k, v in columns.items()}
        with self._lock:
            self.ledger[(coll, plane)] = sizes
            self._planes.add(plane)
        self._export_gauges()

    def note_buffer(self, coll: str, plane: str, column: str,
                    nbytes) -> None:
        """Register (replace) ONE buffer — transient staging (mesh
        wave operands, build scratch) that comes and goes per wave."""
        if not self.enabled:
            return
        with self._lock:
            self.ledger.setdefault((coll, plane), {})[column] = \
                _nbytes(nbytes)
            self._planes.add(plane)
        self._export_gauges()

    def drop_buffer(self, coll: str, plane: str, column: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            cols = self.ledger.get((coll, plane))
            if cols is not None:
                cols.pop(column, None)
        self._export_gauges()

    def drop(self, coll: str, plane: str | None = None) -> None:
        """Release a collection's entries (one plane, or all on park /
        delColl)."""
        if not self.enabled:
            return
        with self._lock:
            for key in [k for k in self.ledger
                        if k[0] == coll
                        and (plane is None or k[1] == plane)]:
                del self.ledger[key]
        self._export_gauges()

    def collection_bytes(self, coll: str) -> int:
        with self._lock:
            return sum(sum(cols.values())
                       for (c, _p), cols in self.ledger.items()
                       if c == coll)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(sum(cols.values())
                       for cols in self.ledger.values())

    def _export_gauges(self) -> None:
        with self._lock:
            per_plane = {p: 0 for p in self._planes}
            for (_c, p), cols in self.ledger.items():
                per_plane[p] = per_plane.get(p, 0) + sum(cols.values())
        for p, n in per_plane.items():
            g_stats.gauge(f"hbm.{p}.bytes", n)
        g_stats.gauge("hbm.total.bytes", sum(per_plane.values()))

    def reconcile(self) -> dict:
        """Ledger vs what the runtime says the chip holds.
        ``memory_stats()`` is backend-dependent: TPU reports
        bytes_in_use / peak / limit, CPU returns None — degrade to
        nulls, never raise. Fragmentation is the slice of live device
        bytes the ledger cannot name (allocator slack + unregistered
        temporaries); headroom is limit − in_use."""
        ledger_total = self.total_bytes()
        devices = []
        try:
            import jax
            for d in jax.devices():
                try:
                    ms = d.memory_stats()
                except Exception:
                    ms = None
                ent = {"device": str(d),
                       "kind": getattr(d, "device_kind", "unknown")}
                if ms:
                    in_use = int(ms.get("bytes_in_use", 0))
                    peak = int(ms.get("peak_bytes_in_use", 0))
                    limit = int(ms.get("bytes_limit", 0) or 0)
                    ent.update({
                        "bytes_in_use": in_use,
                        "peak_bytes_in_use": peak,
                        "bytes_limit": limit or None,
                        "headroom": (limit - in_use) if limit else None,
                        "ledger_delta": in_use - ledger_total,
                        "fragmentation": (
                            max(0.0, (in_use - ledger_total) / in_use)
                            if in_use else 0.0)})
                else:
                    ent.update({"bytes_in_use": None,
                                "peak_bytes_in_use": None,
                                "bytes_limit": None, "headroom": None,
                                "ledger_delta": None,
                                "fragmentation": None})
                devices.append(ent)
        except Exception:
            g_stats.count("devwatch.reconcile_errors")
        return {"ledger_bytes": ledger_total, "devices": devices}

    # -- wave flight recorder ----------------------------------------

    def wave_begin(self, source: str, **tags) -> dict | None:
        """Open a wave record on the loop thread, before issue.
        Returns None when disabled — every later stage no-ops on
        None, so call sites never branch."""
        if not self.enabled:
            return None
        with self._lock:
            self.wave_seq += 1
            seq = self.wave_seq
        return {"seq": seq, "source": source, "tags": dict(tags),
                "t0": time.perf_counter(), "t_issue": None,
                "t_collect": None, "rounds": []}

    def wave_issued(self, obs: dict | None, **tags) -> None:
        if obs is None:
            return
        obs["t_issue"] = time.perf_counter()
        obs["tags"].update(tags)

    def wave_collect(self, obs: dict | None) -> None:
        """Collect starts: rounds deposited by the index's
        collect_batch (via :meth:`note_round`, same thread) attach to
        this wave until :meth:`wave_end`."""
        if obs is None:
            return
        obs["t_collect"] = time.perf_counter()
        self._tl.active = obs

    def note_round(self, **detail) -> None:
        """One collect round (fetch + parse + escalation reissue) as
        seen from inside collect_batch — device time, bytes fetched,
        modeled bytes, escalations. Attaches to the thread's active
        wave; a collect outside the loop (warm, direct search) is
        counted, not recorded."""
        if not self.enabled:
            return
        obs = getattr(self._tl, "active", None)
        if obs is None:
            g_stats.count("devwatch.rounds_unattached")
            return
        obs["rounds"].append(detail)
        with self._lock:
            self.totals["rounds"] += 1

    def wave_end(self, obs: dict | None, error: str | None = None,
                 **tags) -> None:
        if obs is None:
            return
        if getattr(self._tl, "active", None) is obs:
            self._tl.active = None
        t_end = time.perf_counter()
        obs["tags"].update(tags)
        t0 = obs["t0"]
        ti = obs["t_issue"] if obs["t_issue"] is not None else t0
        tc = obs["t_collect"] if obs["t_collect"] is not None else ti
        rec = {"seq": obs["seq"], "source": obs["source"],
               "issue_s": ti - t0, "wait_s": max(0.0, tc - ti),
               "collect_s": max(0.0, t_end - tc),
               "total_s": t_end - t0,
               "rounds": obs["rounds"], "error": error}
        rec.update(obs["tags"])
        with self._lock:
            self.waves.append(rec)
            self.totals["waves"] += 1
            if error:
                self.totals["wave_errors"] += 1
        g_stats.count("devwatch.waves")
        g_stats.record_ms("devwatch.wave_ms", 1000.0 * (t_end - t0))
        trace.record("devwatch.wave", t0, t_end, device=1,
                     source=obs["source"], seq=obs["seq"],
                     rounds=len(obs["rounds"]), error=error or "")

    # -- roofline attribution ----------------------------------------

    def _peaks_for(self) -> dict:
        if self._peaks is not None:
            return self._peaks
        flops, bw, label = _CPU_PEAKS
        assumed = True
        try:
            import jax
            kind = str(jax.devices()[0].device_kind).lower()
            for sub, f, b, lab in _TPU_PEAKS:
                if sub in kind:
                    flops, bw, label, assumed = f, b, lab, False
                    break
        except Exception:
            g_stats.count("devwatch.peaks_errors")
        self._peaks = {"flops": flops, "bw": bw, "label": label,
                       "assumed": assumed, "ridge": flops / bw}
        return self._peaks

    def note_cost(self, kernel: str, bucket, thunk,
                  modeled_bytes=None) -> None:
        """Roofline one (kernel, shape-bucket): the FIRST dispatch
        pays one ``lower().compile().cost_analysis()`` via ``thunk``
        (the compile itself is warm — the real dispatch right after
        compiles the same shapes anyway); every later dispatch is a
        dict hit + counter bump, which is what keeps the devwatch-on
        overhead under the BENCH_DEVOBS 2% gate."""
        if not self.enabled:
            return
        key = (kernel, tuple(int(x) for x in bucket))
        ent = self.costs.get(key)
        if ent is not None:
            ent["dispatches"] += 1
            return
        peaks = self._peaks_for()
        flops = nbytes = 0.0
        try:
            ca = thunk().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0) or 0.0)
            nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
        except Exception:
            g_stats.count("devwatch.cost_errors")
        intensity = (flops / nbytes) if nbytes else 0.0
        verdict = ("unknown" if not nbytes else
                   "bandwidth-bound" if intensity < peaks["ridge"]
                   else "compute-bound")
        entry = {"kernel": kernel, "bucket": list(key[1]),
                 "flops": flops, "bytes": nbytes,
                 "intensity": intensity, "ridge": peaks["ridge"],
                 "verdict": verdict,
                 "modeled_bytes": (int(modeled_bytes)
                                   if modeled_bytes else None),
                 "dispatches": 1, "peak": peaks["label"],
                 "assumed": peaks["assumed"]}
        with self._lock:
            self.costs.setdefault(key, entry)
        g_stats.count("devwatch.cost_entries")

    # -- reporting ----------------------------------------------------

    def ledger_snapshot(self) -> dict:
        """collection → plane → column → bytes."""
        out: dict = {}
        with self._lock:
            for (c, p), cols in self.ledger.items():
                out.setdefault(c, {})[p] = dict(cols)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            waves = list(self.waves)
            costs = sorted(self.costs.values(),
                           key=lambda e: (e["kernel"], e["bucket"]))
            totals = dict(self.totals)
            per_plane: dict[str, int] = {}
            per_coll: dict[str, int] = {}
            for (c, p), cols in self.ledger.items():
                n = sum(cols.values())
                per_plane[p] = per_plane.get(p, 0) + n
                per_coll[c] = per_coll.get(c, 0) + n
        return {"enabled": self.enabled,
                "totals": totals,
                "ledger": self.ledger_snapshot(),
                "planes": per_plane,
                "collections": per_coll,
                "total_bytes": sum(per_plane.values()),
                "reconcile": self.reconcile(),
                "waves": waves,
                "rooflines": costs,
                "peaks": self._peaks_for()}


g_devwatch = DevWatch()


def enable() -> None:
    g_devwatch.enable()


def disable() -> None:
    g_devwatch.disable()


def enabled() -> bool:
    return g_devwatch.enabled


def reset() -> None:
    g_devwatch.reset()


def snapshot() -> dict:
    return g_devwatch.snapshot()


def reconcile() -> dict:
    return g_devwatch.reconcile()


def note_columns(coll: str, plane: str, columns: dict) -> None:
    g_devwatch.note_columns(coll, plane, columns)


def note_buffer(coll: str, plane: str, column: str, nbytes) -> None:
    g_devwatch.note_buffer(coll, plane, column, nbytes)


def drop_buffer(coll: str, plane: str, column: str) -> None:
    g_devwatch.drop_buffer(coll, plane, column)


def drop(coll: str, plane: str | None = None) -> None:
    g_devwatch.drop(coll, plane)


def collection_bytes(coll: str) -> int:
    return g_devwatch.collection_bytes(coll)


def wave_begin(source: str, **tags) -> dict | None:
    return g_devwatch.wave_begin(source, **tags)


def wave_issued(obs, **tags) -> None:
    g_devwatch.wave_issued(obs, **tags)


def wave_collect(obs) -> None:
    g_devwatch.wave_collect(obs)


def note_round(**detail) -> None:
    g_devwatch.note_round(**detail)


def wave_end(obs, error: str | None = None, **tags) -> None:
    g_devwatch.wave_end(obs, error=error, **tags)


def note_cost(kernel: str, bucket, thunk, modeled_bytes=None) -> None:
    g_devwatch.note_cost(kernel, bucket, thunk,
                         modeled_bytes=modeled_bytes)


def maybe_enable() -> None:
    """Enable iff OSSE_DEVWATCH=1 — import-time wiring in the device
    layers and the server; a true no-op otherwise."""
    if os.environ.get("OSSE_DEVWATCH", "") == "1":
        enable()
