"""64-bit term hashing (reference: ``hash.h`` / ``hash.cpp`` ``hash64``).

The reference hashes lower-cased words with a table-driven 64-bit mix and
derives the 48-bit posdb termId from it (``XmlDoc.cpp:hashAll``; termId is
the low 48 bits, ``Posdb.h`` termId field). We use our own stateless
FNV-1a-64 variant with an avalanche finalizer — the exact hash function is
an internal detail (only stability within one index matters), but the
*shape* (word → 64-bit → 48-bit termId, prefix-salted field hashes) mirrors
the reference.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF

TERMID_BITS = 48
TERMID_MASK = (1 << TERMID_BITS) - 1


#: byte-at-a-time FNV above this length costs ~1.5 µs/KB in Python;
#: long payloads (page content, joined section text) take the C-speed
#: blake2b path instead. The threshold sits at 1 KiB so every KEY
#: derived from a URL (docids, titledb/spiderdb/linkdb keys — URLs are
#: well under 1 KiB after normalization caps) keeps its historical
#: value; only content/section hashes of large payloads changed, which
#: affects cross-version dedup votes, not record reachability.
_LONG_DATA = 1024


_native_hash = None


def _get_native_hash():
    """libdoccore's osse_hash64 (bit-identical FNV+avalanche) — ~10×
    the Python byte loop on URL-length keys; resolved lazily to avoid
    an import cycle with the native package."""
    global _native_hash
    if _native_hash is None:
        try:
            from .. import native
            _native_hash = native.hash64_native \
                if native.get_doccore() is not None else False
        except Exception:  # noqa: BLE001 — Python loop fallback
            _native_hash = False
    return _native_hash


def hash64(data: bytes | str, seed: int = 0) -> int:
    """64-bit content hash: FNV-1a + murmur finalizer for short keys
    (words, urls), blake2b for long payloads."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    if len(data) > _LONG_DATA:
        import hashlib
        h = hashlib.blake2b(data, digest_size=8,
                            key=seed.to_bytes(8, "little") if seed
                            else b"").digest()
        return int.from_bytes(h, "little")
    nh = _get_native_hash()
    if nh:
        return nh(data, seed)
    h = (_FNV_OFFSET ^ seed) & _MASK64
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    # finalizer for better avalanche on short keys
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _MASK64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _MASK64
    h ^= h >> 33
    return h


@lru_cache(maxsize=1 << 20)
def term_id(word: str, prefix: str | None = None) -> int:
    """48-bit termId for a word, optionally field-prefixed.

    Mirrors the reference's prefixed-field hashing (``hashString`` with a
    prefix hash for e.g. ``site:``/``inurl:`` terms, ``XmlDoc.cpp:hashAll``):
    the prefix hash is mixed into the word hash so ``site:foo.com`` and the
    plain body word occupy distinct termId spaces. Cached: term vocabulary
    is Zipf-distributed, so indexing rehashes the same words constantly.
    """
    h = hash64(word.lower())
    if prefix:
        h = hash64(prefix, seed=h)
    return h & TERMID_MASK


@lru_cache(maxsize=1 << 20)
def bigram_id(w1: str, w2: str) -> int:
    """termId of the bigram "w1 w2" (reference: ``Phrases.cpp`` two-word
    phrase hashing — a combined hash of the two word hashes)."""
    return hash64(w2.lower(), seed=hash64(w1.lower())) & TERMID_MASK


def doc_id(url: str) -> int:
    """38-bit docId from a normalized URL.

    The reference derives a 38-bit "probable docid" from the URL hash
    (``Titledb.h`` ``getProbableDocId``: hash96 of URL masked by
    ``DOCID_MASK`` = 38 bits). Same shape here; collision resolution is the
    caller's job, as in the reference.
    """
    return hash64(url) & ((1 << 38) - 1)


def hash64_array(arr: np.ndarray, seed: int = 0) -> np.ndarray:
    """Vectorized 64-bit avalanche over a uint64 array (for key→shard maps)."""
    with np.errstate(over="ignore"):  # modular 2^64 wraparound is the point
        h = arr.astype(np.uint64) ^ np.uint64(seed)
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xC4CEB9FE1A85EC53)
        h ^= h >> np.uint64(33)
    return h
