"""Language identification (reference: ``Language.cpp``/
``LanguageIdentifier.cpp`` ~8k LoC of charset+dictionary scoring).

Two data-free signal families, layered like the reference's scorer:

1. **Script detection** (Unicode block histogram over the raw
   characters) — decisive for non-Latin languages: Cyrillic → ru,
   Greek → el, Hebrew → he, Arabic → ar, Thai → th, Devanagari → hi,
   Hangul → ko, kana → ja, and Han-without-kana → zh. The reference
   leans on charset hints the same way; code-point ranges are the
   charset-independent form.
2. **Stopword profiles** for Latin-script languages (en/fr/es/de/it/
   pt/nl/sv/pl/tr) — distinct-stopword scoring normalized by profile
   size so long profiles don't dominate.

Contract unchanged: text/tokens → langId packed into posdb keys and
used by the same-language query boost (``Posdb.cpp`` SAMELANGMULT).
"""

from __future__ import annotations

# langIds — reference Lang.h enumerates ~60; we carry the common set and
# the same "0 = unknown" convention the scorer relies on.
LANG_UNKNOWN = 0
LANG_ENGLISH = 1
LANG_FRENCH = 2
LANG_SPANISH = 3
LANG_GERMAN = 4
LANG_ITALIAN = 5
LANG_PORTUGUESE = 6
LANG_DUTCH = 7
LANG_RUSSIAN = 8
LANG_JAPANESE = 9
LANG_CHINESE = 10
LANG_KOREAN = 11
LANG_ARABIC = 12
LANG_HEBREW = 13
LANG_GREEK = 14
LANG_THAI = 15
LANG_HINDI = 16
LANG_SWEDISH = 17
LANG_POLISH = 18
LANG_TURKISH = 19

LANG_NAMES = {
    LANG_UNKNOWN: "xx", LANG_ENGLISH: "en", LANG_FRENCH: "fr",
    LANG_SPANISH: "es", LANG_GERMAN: "de", LANG_ITALIAN: "it",
    LANG_PORTUGUESE: "pt", LANG_DUTCH: "nl", LANG_RUSSIAN: "ru",
    LANG_JAPANESE: "ja", LANG_CHINESE: "zh", LANG_KOREAN: "ko",
    LANG_ARABIC: "ar", LANG_HEBREW: "he", LANG_GREEK: "el",
    LANG_THAI: "th", LANG_HINDI: "hi", LANG_SWEDISH: "sv",
    LANG_POLISH: "pl", LANG_TURKISH: "tr",
}
LANG_IDS = {v: k for k, v in LANG_NAMES.items()}

_PROFILES: dict[int, frozenset[str]] = {
    LANG_ENGLISH: frozenset(
        "the a an of and to in is was for that with are his this they have "
        "from not had her she you were which their been has will would "
        "there on it at by but be or as we".split()),
    LANG_FRENCH: frozenset(
        "le la les de des du et en un une est pour que qui dans sur pas au "
        "avec son ses par plus ne se ce cette mais ou donc être avoir fait "
        "comme tout nous vous leur aux".split()),
    LANG_SPANISH: frozenset(
        "el la los las de del y en un una es por que con para su como más "
        "pero sus le ya o este sí porque esta entre cuando muy sin sobre "
        "también hasta donde quien desde nos".split()),
    LANG_GERMAN: frozenset(
        "der die das und in den von zu mit sich des auf für ist im dem nicht "
        "ein eine als auch es an werden aus er hat dass sie nach bei einer "
        "um am sind noch wie über einen so zum war haben nur oder aber vor "
        "zur bis mehr durch können".split()),
    LANG_ITALIAN: frozenset(
        "il la le di del e in un una è per che con non si da dei al come "
        "più ma gli alla sono questo anche della nel quando essere molto "
        "stato questa loro tutti".split()),
    LANG_PORTUGUESE: frozenset(
        "o a os as de do da e em um uma é por que com para seu como mais "
        "mas foi ao não se na dos das pelo uma os quando muito nos já está "
        "também só pela até".split()),
    LANG_DUTCH: frozenset(
        "de het een en van in is dat op te zijn met voor niet aan er ook als "
        "bij maar om uit door over ze hij naar heeft worden wordt kunnen "
        "geen deze zo nog wel".split()),
    LANG_RUSSIAN: frozenset(
        "и в не на я что он с как это по но они мы все она так его за был "
        "от то же бы у вы из ее мне еще нет о из-за когда даже ну если уже "
        "или ни быть".split()),
    LANG_SWEDISH: frozenset(
        "och i att det som en på är av för med till den har de inte om ett "
        "han men var jag sig från vi så kan man när år".split()),
    LANG_POLISH: frozenset(
        "i w nie na się że z do to jest jak po co tak ale o od za przez "
        "przy już tylko był może przed być bardzo także czy ich".split()),
    LANG_TURKISH: frozenset(
        "bir ve bu da ne için ile olarak çok daha sonra kadar gibi ama en "
        "diye olan her iki ya değil ise veya".split()),
}

#: Unicode script ranges → language (the charset-hint role of
#: Language.cpp, charset-independent). Checked on the raw characters.
_SCRIPTS: list[tuple[int, int, int]] = [
    (0x3040, 0x30FF, LANG_JAPANESE),    # hiragana + katakana
    (0xAC00, 0xD7AF, LANG_KOREAN),      # hangul syllables
    (0x1100, 0x11FF, LANG_KOREAN),      # hangul jamo
    (0x4E00, 0x9FFF, LANG_CHINESE),     # CJK unified (zh unless kana)
    (0x0400, 0x04FF, LANG_RUSSIAN),     # cyrillic
    (0x0590, 0x05FF, LANG_HEBREW),
    (0x0600, 0x06FF, LANG_ARABIC),
    (0x0370, 0x03FF, LANG_GREEK),
    (0x0E00, 0x0E7F, LANG_THAI),
    (0x0900, 0x097F, LANG_HINDI),       # devanagari
]


def detect_script(text: str, sample: int = 4000) -> int:
    """Dominant non-Latin script over a character sample → langId
    (LANG_UNKNOWN when the text is overwhelmingly Latin/other)."""
    t = text[:sample]
    if t.isascii():  # C-speed common case: nothing above 0x7F
        return LANG_UNKNOWN
    import numpy as np
    cps = np.frombuffer(t.encode("utf-32-le"), dtype=np.uint32)
    cps = cps[cps >= 0x0370]
    counts: dict[int, int] = {}
    for lo, hi, lang in _SCRIPTS:
        c = int(((cps >= lo) & (cps <= hi)).sum())
        if c:
            counts[lang] = counts.get(lang, 0) + c
    if not counts:
        return LANG_UNKNOWN
    best = max(counts, key=counts.get)
    # Han characters are shared: kana presence means Japanese even when
    # Han dominates the histogram
    if best == LANG_CHINESE and counts.get(LANG_JAPANESE, 0) >= 2:
        best = LANG_JAPANESE
    # require the winning script to be a real presence, not stray chars
    return best if counts[best] >= 5 else LANG_UNKNOWN


def detect_language(words: list[str], min_hits: int = 2,
                    text: str | None = None) -> int:
    """Layered id: script first (decisive for non-Latin), then the best
    normalized stopword-profile hit; LANG_UNKNOWN when nothing clears
    the bar (the reference also overlays TLD hints — callers can)."""
    if text is None and words:
        text = " ".join(words[:400])
    if text:
        script = detect_script(text)
        if script != LANG_UNKNOWN:
            return script
    if not words:
        return LANG_UNKNOWN
    sample = set(words[:2000])
    best, best_score, best_hits = LANG_UNKNOWN, 0.0, 0
    for lang, profile in _PROFILES.items():
        # distinct stopwords hit, normalized by profile size so big
        # profiles don't win by surface area
        hits = len(sample & profile)
        score = hits / (len(profile) ** 0.5)
        if score > best_score:
            best, best_score, best_hits = lang, score, hits
    return best if best_hits >= min_hits else LANG_UNKNOWN
