"""Language identification (reference: ``Language.cpp``/``LanguageIdentifier.cpp``
~8k LoC of charset+dictionary scoring; ours is a compact stopword-profile
scorer — same contract: text → langId used in posdb keys and same-language
query boost (``Posdb.cpp`` SAMELANGMULT))."""

from __future__ import annotations

# langIds — reference Lang.h enumerates ~60; we carry the common set and
# the same "0 = unknown" convention the scorer relies on.
LANG_UNKNOWN = 0
LANG_ENGLISH = 1
LANG_FRENCH = 2
LANG_SPANISH = 3
LANG_GERMAN = 4
LANG_ITALIAN = 5
LANG_PORTUGUESE = 6
LANG_DUTCH = 7
LANG_RUSSIAN = 8

LANG_NAMES = {
    LANG_UNKNOWN: "xx", LANG_ENGLISH: "en", LANG_FRENCH: "fr",
    LANG_SPANISH: "es", LANG_GERMAN: "de", LANG_ITALIAN: "it",
    LANG_PORTUGUESE: "pt", LANG_DUTCH: "nl", LANG_RUSSIAN: "ru",
}
LANG_IDS = {v: k for k, v in LANG_NAMES.items()}

_PROFILES: dict[int, frozenset[str]] = {
    LANG_ENGLISH: frozenset(
        "the a an of and to in is was for that with are his this they have "
        "from not had her she you were which their been has will would "
        "there on it at by but be or as we".split()),
    LANG_FRENCH: frozenset(
        "le la les de des du et en un une est pour que qui dans sur pas au "
        "avec son ses par plus ne se ce cette mais ou donc".split()),
    LANG_SPANISH: frozenset(
        "el la los las de del y en un una es por que con para su como más "
        "pero sus le ya o este sí porque esta entre cuando".split()),
    LANG_GERMAN: frozenset(
        "der die das und in den von zu mit sich des auf für ist im dem nicht "
        "ein eine als auch es an werden aus er hat dass sie nach".split()),
    LANG_ITALIAN: frozenset(
        "il la le di del e in un una è per che con non si da dei al come "
        "più ma gli alla sono questo anche della nel".split()),
    LANG_PORTUGUESE: frozenset(
        "o a os as de do da e em um uma é por que com para seu como mais "
        "mas foi ao não se na dos das pelo".split()),
    LANG_DUTCH: frozenset(
        "de het een en van in is dat op te zijn met voor niet aan er ook als "
        "bij maar om uit door over ze hij".split()),
    LANG_RUSSIAN: frozenset(
        "и в не на я что он с как это по но они мы все она так его за был "
        "от то же бы у вы из".split()),
}


def detect_language(words: list[str], min_hits: int = 2) -> int:
    """Best stopword-profile match over the token stream; LANG_UNKNOWN when
    nothing clears the bar (the reference also falls back to charset and
    TLD hints — callers can overlay those)."""
    if not words:
        return LANG_UNKNOWN
    sample = set(words[:2000])
    best, best_hits = LANG_UNKNOWN, 0
    for lang, profile in _PROFILES.items():
        # distinct stopwords hit, so one frequent word can't dominate
        hits = len(sample & profile)
        if hits > best_hits:
            best, best_hits = lang, hits
    return best if best_hits >= min_hits else LANG_UNKNOWN
