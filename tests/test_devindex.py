"""Device-resident index tests — bit-parity with the host-packed path.

The resident kernel reuses score_cube, so any ranking difference means
the gather/rank/scatter front end diverged from the packer's. Every
query family must produce identical (docid, score) sets both ways.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import (
    get_device_index, search_device, search_device_batch)

DOCS = {
    "http://a.example.com/fruit": """
      <html><head><title>Fruit basics</title></head><body>
      <h1>Apples and bananas</h1>
      <p>The apple is sweet. A banana is tropical. Apple pie wins.</p>
      </body></html>""",
    "http://b.example.com/apple": """
      <html><head><title>Apple orchard</title></head><body>
      <p>Our orchard grows apple trees. Apple harvest is in fall.
      No banana here.</p></body></html>""",
    "http://c.example.org/banana": """
      <html><head><title>Banana farm</title></head><body>
      <p>Banana plantations export banana bunches worldwide.</p>
      </body></html>""",
    "http://d.example.org/other": """
      <html><head><title>Vegetables</title></head><body>
      <p>Carrots and beets. Root cellar storage tips.</p></body></html>""",
}


@pytest.fixture(scope="module")
def coll(tmp_path_factory):
    c = Collection("dev", tmp_path_factory.mktemp("dev"))
    c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
    for u, h in DOCS.items():
        docproc.index_document(c, u, h)
    return c


QUERIES = ["apple", "banana", "apple banana", "fruit -banana",
           '"apple pie"', "site:b.example.com apple", "zeppelin"]


def assert_parity(host, dev, q):
    """Scores must agree exactly; tied docids may differ (both paths
    return SOME k of the tied docs — tie order is not part of the
    contract, matching TopTree's arbitrary insertion order)."""
    assert dev.total_matches == host.total_matches, q
    assert [round(r.score, 3) for r in dev.results] == \
           [round(r.score, 3) for r in host.results], q
    host_by_score = {}
    for r in host.results:
        host_by_score.setdefault(round(r.score, 3), set()).add(r.docid)
    uniq = {s_ for s_, ds in host_by_score.items() if len(ds) == 1}
    for r in dev.results:
        if round(r.score, 3) in uniq:
            assert {r.docid} == host_by_score[round(r.score, 3)], q
    assert len({r.docid for r in dev.results}) == len(dev.results), q


class TestResidentParity:
    def test_matches_host_packed_path(self, coll):
        for q in QUERIES:
            host = engine.search(coll, q, topk=10, site_cluster=False)
            dev = search_device(coll, q, topk=10, site_cluster=False)
            assert dev.total_matches == host.total_matches, q
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted(map(key, dev.results)) == \
                   sorted(map(key, host.results)), q

    def test_batch_matches_single(self, coll):
        batch = search_device_batch(coll, QUERIES, topk=10,
                                    site_cluster=False)
        for q, b in zip(QUERIES, batch):
            s = search_device(coll, q, topk=10, site_cluster=False)
            assert [r.docid for r in b.results] == \
                   [r.docid for r in s.results], q
            np.testing.assert_allclose(
                [r.score for r in b.results],
                [r.score for r in s.results], rtol=1e-6)

    def test_refresh_tracks_writes(self, coll):
        di = get_device_index(coll)
        v0 = di._built_version
        assert not search_device(coll, "quokka").results
        docproc.index_document(
            coll, "http://e.example.org/q",
            "<html><title>Q</title><body>a quokka appears</body></html>")
        res = search_device(coll, "quokka")
        assert get_device_index(coll)._built_version > v0
        assert len(res.results) == 1
        docproc.remove_document(coll, "http://e.example.org/q")
        assert not search_device(coll, "quokka").results

    def test_empty_collection(self, tmp_path):
        c = Collection("empty", tmp_path)
        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        assert search_device(c, "anything").total_matches == 0

    def test_pure_negative_query_matches_host(self, coll):
        """`-apple` must match NOTHING on both paths (the reference's
        early-out when no positive required term exists) — the resident
        path used to match every doc lacking the term."""
        host = engine.search(coll, "-apple", topk=10)
        dev = search_device(coll, "-apple", topk=10)
        assert host.total_matches == 0 and not host.results
        assert dev.total_matches == 0 and not dev.results

    def test_over_quota_occurrences_keep_sibling_sublists(self, tmp_path):
        """A doc with more than quota (P//n_sublists) occurrences of a
        word must not clobber its bigram sublist's slots: over-quota
        scatter lanes are routed to the drop row (duplicate-index
        scatter order is implementation-defined on TPU)."""
        c = Collection("quota", tmp_path)
        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        spam = " ".join(["pepper"] * 24) + " pepper mill grinder."
        docproc.index_document(
            c, "http://q.example.com/mill",
            f"<html><head><title>Mill</title></head><body><p>{spam}</p>"
            "</body></html>")
        docproc.index_document(
            c, "http://q.example.com/other",
            "<html><head><title>Other</title></head><body>"
            "<p>salt mill only here.</p></body></html>")
        for q in ["pepper mill", "pepper", '"pepper mill"']:
            host = engine.search(c, q, topk=10, site_cluster=False)
            dev = search_device(c, q, topk=10, site_cluster=False)
            assert dev.total_matches == host.total_matches, q
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted(map(key, dev.results)) == \
                   sorted(map(key, host.results)), q


class TestScale:
    """The round-2 scale contract: runs longer than any fixed cap score
    fully (docid-tile streaming), identical to the host-packed path."""

    def test_large_termlist_no_truncation(self, tmp_path):
        import numpy as np

        from open_source_search_engine_tpu.index import posdb
        from open_source_search_engine_tpu.utils import ghash

        c = Collection("big", tmp_path)

        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        n = 40_000  # > the old 32768-per-run resident cap
        docids = np.arange(1, n + 1, dtype=np.uint64)
        common = ghash.term_id("common")
        rare = ghash.term_id("rare")
        keys = [posdb.pack(termid=common, docid=docids, wordpos=5,
                           densityrank=10, siterank=docids % 15,
                           hashgroup=0, langid=1)]
        keys.append(posdb.pack(termid=rare, docid=docids[::200], wordpos=9,
                               densityrank=10, siterank=docids[::200] % 15,
                               hashgroup=0, langid=1))
        c.posdb.add(np.concatenate(keys))
        c.num_docs = n

        host = engine.search(c, "common rare", topk=10,
                             with_snippets=False, site_cluster=False)
        dev = search_device(c, "common rare", topk=10,
                            with_snippets=False, site_cluster=False)
        assert host.total_matches == len(docids[::200])
        assert dev.total_matches == host.total_matches
        # identical postings per doc → massive score ties: the two
        # paths may legitimately return different tie members, so pin
        # the score sequence (the tie-aware parity contract)
        assert [round(r.score, 3) for r in dev.results] == \
               [round(r.score, 3) for r in host.results]

        # single common term: every doc matches, none truncated away.
        # Scores tie massively (identical postings), so the two paths
        # may pick different — equally best — docids: compare scores,
        # not the arbitrary tie order.
        host1 = engine.search(c, "common", topk=10, with_snippets=False,
                              site_cluster=False)
        dev1 = search_device(c, "common", topk=10, with_snippets=False,
                             site_cluster=False)
        assert host1.total_matches == n
        assert dev1.total_matches == n
        assert [round(r.score, 3) for r in dev1.results] == \
               [round(r.score, 3) for r in host1.results]
        assert len({r.docid for r in dev1.results}) == 10
        assert all(r.docid in set(docids) for r in dev1.results)


class TestIncrementalDelta:
    """Adds/deletes against a served index cost O(memtable), not
    O(corpus): the base rebuilds only when the Rdb run set moves."""

    def test_adds_and_deletes_without_full_rebuild(self, tmp_path):
        c = Collection("inc", tmp_path)
        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        for i in range(30):
            docproc.index_document(
                c, f"http://inc.test/d{i}",
                f"<html><head><title>Doc {i}</title></head><body>"
                f"<p>stable corpus text number{i} here.</p></body></html>")
        c.posdb.dump()  # base postings now live in a run
        di = get_device_index(c)
        base_rebuilds = di.full_rebuilds

        # adds land in the delta: visible immediately, no base rebuild
        for i in range(3):
            docproc.index_document(
                c, f"http://inc.test/new{i}",
                "<html><head><title>Fresh</title></head><body>"
                f"<p>freshterm arrives number{i} stable.</p></body></html>")
            res = search_device(c, "freshterm")
            assert res.total_matches == i + 1
        assert di.full_rebuilds == base_rebuilds
        assert di.delta_rebuilds > 0

        # delete a BASE doc: dead-masked out, still no base rebuild
        assert docproc.remove_document(c, "http://inc.test/d5")
        res = search_device(c, "number5")
        assert all("d5" not in r.url for r in res.results)
        assert search_device(c, "stable").total_matches == 32
        assert di.full_rebuilds == base_rebuilds

        # re-index a base doc with new content: old postings dead,
        # new postings served from the delta
        docproc.index_document(
            c, "http://inc.test/d7",
            "<html><head><title>Doc 7 v2</title></head><body>"
            "<p>rewrittenterm stable now.</p></body></html>")
        assert search_device(c, "rewrittenterm").total_matches == 1
        assert search_device(c, "number7").total_matches == 0
        assert di.full_rebuilds == base_rebuilds

        # parity with the host path across the mixed base/delta state
        for q in ["stable", "freshterm", "rewrittenterm", "number12"]:
            host = engine.search(c, q, topk=10, site_cluster=False)
            dev = search_device(c, q, topk=10, site_cluster=False)
            assert_parity(host, dev, q)

        # a dump moves the run set: a BACKGROUND rebuild folds it into
        # a fresh index while the old one keeps serving, then swaps
        c.posdb.dump()
        search_device(c, "stable")  # never blocks on the rebuild
        import time as _t
        for _ in range(100):
            if get_device_index(c) is not di:
                break
            _t.sleep(0.1)
        di2 = get_device_index(c)
        assert di2 is not di and di2.full_rebuilds == 1
        assert search_device(c, "stable").total_matches > 0

    def test_identical_recrawl_no_double_serving(self, tmp_path):
        """Re-indexing a doc with UNCHANGED content (routine recrawl):
        the tombstone/positive pairs annihilate inside the memtable, so
        no tombstone survives — the base copy must still be superseded
        or the doc serves from both base and delta with doubled df."""
        c = Collection("recrawl", tmp_path)
        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        html = ("<html><head><title>Evergreen</title></head><body>"
                "<p>evergreen content never changes.</p></body></html>")
        docproc.index_document(c, "http://re.test/page", html)
        docproc.index_document(
            c, "http://re.test/other",
            "<html><head><title>Other</title></head><body>"
            "<p>different content here.</p></body></html>")
        c.posdb.dump()
        get_device_index(c)
        # identical re-index: base copy superseded, delta serves
        docproc.index_document(c, "http://re.test/page", html)
        host = engine.search(c, "evergreen content", topk=10,
                             site_cluster=False)
        dev = search_device(c, "evergreen content", topk=10,
                            site_cluster=False)
        assert host.total_matches == 1
        assert dev.total_matches == 1
        assert round(dev.results[0].score, 3) == \
               round(host.results[0].score, 3)


class TestFullCubePath:
    """F2 routing: corpus-wide drivers score on the full-cube kernel —
    results must match the host-packed path exactly (same min_scores)."""

    def test_f2_parity_with_host(self, tmp_path, monkeypatch):
        import open_source_search_engine_tpu.query.devindex as dv

        # shrink thresholds so a 200-doc corpus exercises dense rows,
        # materialized cube rows, AND the F2 route
        monkeypatch.setattr(dv, "DENSE_MIN_DF", 0)
        monkeypatch.setattr(dv, "CUBE_MIN_DF", 16)
        c = Collection("f2", tmp_path)
        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        for i in range(200):
            extra = "orange grove" if i % 3 == 0 else "plain field"
            docproc.index_document(
                c, f"http://f2.test/s{i % 7}/d{i}",
                f"<html><head><title>Doc {i} common</title></head><body>"
                f"<p>common words everywhere {extra} number{i}.</p>"
                "</body></html>")
        c.posdb.dump()
        # delta postings on top of the base (tests the scatter rows)
        docproc.index_document(
            c, "http://f2.test/fresh",
            "<html><head><title>Fresh common</title></head><body>"
            "<p>common orange arrival.</p></body></html>")
        di = get_device_index(c)

        queries = ["common", "common words", "common orange",
                   '"common words"', "common -orange", "words everywhere"]
        for q in queries:
            host = engine.search(c, q, topk=10, site_cluster=False,
                                 with_snippets=False)
            dev = search_device(c, q, topk=10, site_cluster=False,
                                with_snippets=False)
            assert_parity(host, dev, q)
        # the common-word queries really did take the F2 route
        p = di.plan(
            __import__("open_source_search_engine_tpu.query.compiler",
                       fromlist=["compile_query"]).compile_query("common"))
        assert p.driver_df > dv.CUBE_MIN_DF
        assert len(di.cube_slot_of) > 0  # cube rows materialized


    def test_fd_direct_route_parity(self, tmp_path, monkeypatch):
        """The direct-cube (FD) kernel: all-cube-term queries skip cube
        assembly; results must match the host path exactly, and the
        route must actually be taken (direct_ok) until delta postings
        disqualify it."""
        import open_source_search_engine_tpu.query.devindex as dv
        from open_source_search_engine_tpu.query.compiler import \
            compile_query

        monkeypatch.setattr(dv, "DENSE_MIN_DF", 0)
        monkeypatch.setattr(dv, "CUBE_MIN_DF", 16)
        c = Collection("fd", tmp_path)
        c.conf.pqr_enabled = False
        for i in range(200):
            extra = "orange grove" if i % 3 == 0 else "plain field"
            docproc.index_document(
                c, f"http://fd.test/s{i % 7}/d{i}",
                f"<html><head><title>Doc {i} common</title></head><body>"
                f"<p>common words everywhere {extra} number{i}.</p>"
                "</body></html>")
        c.posdb.dump()
        di = get_device_index(c)
        queries = ["common", "common words", "words everywhere common"]
        for q in queries:
            p = di.plan(compile_query(q))
            assert p.direct_ok, q  # base-only cube terms -> FD route
            host = engine.search(c, q, topk=10, site_cluster=False,
                                 with_snippets=False)
            dev = search_device(c, q, topk=10, site_cluster=False,
                                with_snippets=False)
            assert_parity(host, dev, q)
        # delta postings ride the FD scatter tail (still direct);
        # parity must hold through it
        docproc.index_document(
            c, "http://fd.test/fresh",
            "<html><head><title>Fresh common</title></head><body>"
            "<p>common arrival.</p></body></html>")
        di.refresh()
        p = di.plan(compile_query("common"))
        assert p.direct_ok and len(p.p_start)  # delta -> scatter rows
        host = engine.search(c, "common", topk=10, site_cluster=False,
                             with_snippets=False)
        dev = search_device(c, "common", topk=10, site_cluster=False,
                            with_snippets=False)
        assert_parity(host, dev, "common")


class TestClusterdbRead:
    """Query-time clusterdb use (Clusterdb.h:42, Msg51.h:96): the
    sitehash column clusters results BEFORE any titledb access."""

    def test_sitehash_clustering_matches_titlerec_clustering(self, coll):
        di = get_device_index(coll)
        # sitehashes exist for every doc and group by site
        a = di.sitehash_of(
            __import__("open_source_search_engine_tpu.utils.ghash",
                       fromlist=["doc_id"]).doc_id(
                "http://a.example.com/fruit"))
        assert a != 0
        host = engine.search(coll, "apple", topk=10, site_cluster=True)
        dev = search_device(coll, "apple", topk=10, site_cluster=True)
        assert {r.url for r in dev.results} == {r.url for r in host.results}
        assert dev.clustered == host.clustered

    def test_hidden_results_skip_titledb(self, tmp_path):
        c = Collection("clu", tmp_path)
        c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
        for i in range(6):
            docproc.index_document(
                c, f"http://one.site.test/p{i}",
                f"<html><head><title>Page {i} shared</title></head>"
                f"<body><p>shared words everywhere {i}.</p></body></html>")
        fetched = []
        orig = docproc.get_document

        def spy(coll_, url=None, docid=None):
            fetched.append(docid)
            return orig(coll_, url=url, docid=docid)

        import open_source_search_engine_tpu.query.engine as eng
        di = get_device_index(c)
        raw = di.search_batch(["shared"], topk=64)
        from open_source_search_engine_tpu.query.compiler import (
            compile_query)
        docids, scores, nm = raw[0]
        results, clustered = eng.build_results(
            lambda d: spy(c, docid=d), docids, scores,
            compile_query("shared"), topk=10, with_snippets=False,
            site_cluster=True, site_of=di.sitehash_of)
        assert nm == 6 and clustered == 4
        assert len(results) == 2
        # only the 2 served results touched titledb — the 4 hidden by
        # clustering were decided from the clusterdb sitehash column
        assert len(fetched) == 2


class TestBackgroundRebase:
    def test_dump_does_not_block_serving(self, tmp_path, monkeypatch):
        """A run-set move (dump) must not block queries: the old
        resident view keeps serving (VERDICT r3 item 6; reference
        RdbDump.h:21 — dumps never block the loop) while the rebuild
        runs in the background, then the new base swaps in."""
        import threading
        import time as _time

        import open_source_search_engine_tpu.query.devindex as dv
        from open_source_search_engine_tpu.query.engine import \
            get_device_index

        c = Collection("bg", tmp_path)
        c.conf.pqr_enabled = False
        for i in range(30):
            docproc.index_document(
                c, f"http://bg.test/d{i}",
                f"<html><body><p>resident words number{i}</p></body>"
                "</html>")
        di0 = get_device_index(c)
        r0 = search_device(c, "resident", topk=5, with_snippets=False)
        assert r0.total_matches == 30

        # make the rebuild observably slow
        gate = threading.Event()
        orig = dv.DeviceIndex._build_base

        def slow_build(self, *a, **kw):
            gate.wait(10.0)
            return orig(self, *a, **kw)

        monkeypatch.setattr(dv.DeviceIndex, "_build_base", slow_build)
        docproc.index_document(
            c, "http://bg.test/fresh",
            "<html><body><p>resident fresh arrival</p></body></html>")
        c.posdb.dump()  # run set moves -> background rebuild

        t0 = _time.perf_counter()
        r1 = search_device(c, "resident", topk=5, with_snippets=False)
        blocked = _time.perf_counter() - t0
        assert blocked < 5.0          # did NOT wait for the rebuild
        assert r1.total_matches == 30  # frozen pre-dump view serves
        assert get_device_index(c) is di0

        gate.set()  # let the rebuild finish, then poll for the swap
        for _ in range(100):
            if get_device_index(c) is not di0:
                break
            _time.sleep(0.1)
        di1 = get_device_index(c)
        assert di1 is not di0
        r2 = search_device(c, "resident", topk=5, with_snippets=False)
        assert r2.total_matches == 31  # the dumped write is visible
