"""Device-resident index tests — bit-parity with the host-packed path.

The resident kernel reuses score_cube, so any ranking difference means
the gather/rank/scatter front end diverged from the packer's. Every
query family must produce identical (docid, score) sets both ways.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import (
    get_device_index, search_device, search_device_batch)

DOCS = {
    "http://a.example.com/fruit": """
      <html><head><title>Fruit basics</title></head><body>
      <h1>Apples and bananas</h1>
      <p>The apple is sweet. A banana is tropical. Apple pie wins.</p>
      </body></html>""",
    "http://b.example.com/apple": """
      <html><head><title>Apple orchard</title></head><body>
      <p>Our orchard grows apple trees. Apple harvest is in fall.
      No banana here.</p></body></html>""",
    "http://c.example.org/banana": """
      <html><head><title>Banana farm</title></head><body>
      <p>Banana plantations export banana bunches worldwide.</p>
      </body></html>""",
    "http://d.example.org/other": """
      <html><head><title>Vegetables</title></head><body>
      <p>Carrots and beets. Root cellar storage tips.</p></body></html>""",
}


@pytest.fixture(scope="module")
def coll(tmp_path_factory):
    c = Collection("dev", tmp_path_factory.mktemp("dev"))
    for u, h in DOCS.items():
        docproc.index_document(c, u, h)
    return c


QUERIES = ["apple", "banana", "apple banana", "fruit -banana",
           '"apple pie"', "site:b.example.com apple", "zeppelin"]


class TestResidentParity:
    def test_matches_host_packed_path(self, coll):
        for q in QUERIES:
            host = engine.search(coll, q, topk=10, site_cluster=False)
            dev = search_device(coll, q, topk=10, site_cluster=False)
            assert dev.total_matches == host.total_matches, q
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted(map(key, dev.results)) == \
                   sorted(map(key, host.results)), q

    def test_batch_matches_single(self, coll):
        batch = search_device_batch(coll, QUERIES, topk=10,
                                    site_cluster=False)
        for q, b in zip(QUERIES, batch):
            s = search_device(coll, q, topk=10, site_cluster=False)
            assert [r.docid for r in b.results] == \
                   [r.docid for r in s.results], q
            np.testing.assert_allclose(
                [r.score for r in b.results],
                [r.score for r in s.results], rtol=1e-6)

    def test_refresh_tracks_writes(self, coll):
        di = get_device_index(coll)
        v0 = di._built_version
        assert not search_device(coll, "quokka").results
        docproc.index_document(
            coll, "http://e.example.org/q",
            "<html><title>Q</title><body>a quokka appears</body></html>")
        res = search_device(coll, "quokka")
        assert get_device_index(coll)._built_version > v0
        assert len(res.results) == 1
        docproc.remove_document(coll, "http://e.example.org/q")
        assert not search_device(coll, "quokka").results

    def test_empty_collection(self, tmp_path):
        c = Collection("empty", tmp_path)
        assert search_device(c, "anything").total_matches == 0
