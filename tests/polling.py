"""Condition-polling helpers for tests that wait on another thread.

A fixed ``time.sleep(X)`` encodes a guess about scheduler timing: too
short flakes under load, too long taxes every run. Poll the actual
condition instead — the open-loop load harness (bench.py BENCH_LOAD)
exposed exactly these guesses by running the suite on saturated boxes.
"""

from __future__ import annotations

import time


def wait_until(cond, timeout: float = 5.0, interval: float = 0.005,
               desc: str = "condition"):
    """Poll ``cond()`` until truthy; return its value. Raises
    ``AssertionError`` (with ``desc``) on timeout so a hung wait reads
    as a test failure, not an error."""
    end = time.monotonic() + timeout
    while True:
        v = cond()
        if v:
            return v
        if time.monotonic() >= end:
            raise AssertionError(
                f"wait_until: {desc} not reached in {timeout}s")
        time.sleep(interval)
