"""Blaster: query replay + two-endpoint diff (Blaster.h:31,
main.cpp:1861,1898 blasterdiff)."""

import json
import sys

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.serve.server import SearchHTTPServer

sys.path.insert(0, "tools")


def _mk_server(tmp_path, name, docs):
    srv = SearchHTTPServer(tmp_path / name, port=0)
    coll = srv.colldb.get("main")
    for url, html in docs:
        docproc.index_document(coll, url, html)
    srv.start()
    return srv


def test_replay_and_diff(tmp_path, capsys):
    import blaster
    docs = [(f"http://b.test/p{i}",
             f"<html><body><p>blast words number{i}</p></body></html>")
            for i in range(6)]
    a = _mk_server(tmp_path, "a", docs)
    b = _mk_server(tmp_path, "b", docs[:5])  # one doc missing on B
    qf = tmp_path / "queries.txt"
    qf.write_text("# comment\nblast words\nnumber3\nnumber5\n")
    try:
        ep_a = f"http://127.0.0.1:{a._httpd.server_port}"
        ep_b = f"http://127.0.0.1:{b._httpd.server_port}"
        rc = blaster.main([str(qf), ep_a, "--threads", "2"])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0 and out["ok"] == 3 and out["errors"] == 0
        assert out["qps"] > 0 and out["p50_ms"] is not None
        # diff mode: B lacks number5 -> at least one query diffs
        rc = blaster.main([str(qf), ep_a, "--diff", ep_b,
                           "--threads", "2"])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 1 and out["diffs"] >= 1
    finally:
        a.stop()
        b.stop()
