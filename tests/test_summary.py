"""Summary/highlight/site-clustering tests (Msg20 + Msg51 equivalents)."""

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.summary import highlight, make_summary

LONG_TEXT = (
    "The city library opened in 1901. It holds many rare manuscripts. "
    "Among its collections, the astronomy archive is famous worldwide. "
    "Visitors can view telescope drawings from the 17th century. "
    "The archive reading room requires an appointment. "
    "A separate wing houses modern science journals. "
    "Children's books occupy the ground floor near the entrance. "
    "The library garden hosts readings every summer evening."
)


class TestSummary:
    def test_window_contains_query_terms(self):
        s = make_summary(LONG_TEXT, ["telescope", "drawings"])
        assert "telescope" in s.lower()
        assert "drawings" in s.lower()

    def test_prefers_window_with_more_distinct_terms(self):
        # 'archive' appears twice; the window with archive AND appointment
        # must win over the one with archive alone
        s = make_summary(LONG_TEXT, ["archive", "appointment"],
                         max_fragments=1)
        assert "appointment" in s.lower()

    def test_no_match_falls_back_to_head(self):
        s = make_summary(LONG_TEXT, ["zeppelin"])
        assert s.startswith("The city library")

    def test_empty_text(self):
        assert make_summary("", ["x"]) == ""

    def test_highlight_wraps_matches(self):
        h = highlight("The Cat and the cat.", ["cat"])
        assert h == "The <b>Cat</b> and the <b>cat</b>."

    def test_highlight_no_query(self):
        assert highlight("text", []) == "text"


class TestSiteClustering:
    @pytest.fixture(scope="class")
    def coll(self, tmp_path_factory):
        c = Collection("cluster", tmp_path_factory.mktemp("cluster"))
        # 5 docs from one site, 1 from another — all matching 'widget'
        for i in range(5):
            docproc.index_document(
                c, f"http://bigsite.example.com/p{i}",
                f"<html><title>Widget page {i}</title><body>"
                f"<p>widget catalog entry number {i} here</p></body></html>")
        docproc.index_document(
            c, "http://small.example.org/only",
            "<html><title>Widget source</title><body>"
            "<p>widget specialists</p></body></html>")
        return c

    def test_max_two_per_site(self, coll):
        res = engine.search(coll, "widget", topk=10)
        sites = [r.site for r in res.results]
        assert sites.count("bigsite.example.com") == 2
        assert sites.count("small.example.org") == 1
        assert res.clustered == 3  # 3 bigsite results hidden
        assert res.total_matches == 6  # pre-clustering count

    def test_clustering_can_be_disabled(self, coll):
        res = engine.search(coll, "widget", topk=10, site_cluster=False)
        assert len(res.results) == 6
        assert res.clustered == 0
