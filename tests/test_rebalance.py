"""Rebalance + Repair tests (VERDICT round-2 item 7).

Reference contracts: Rebalance.h:13 (grow the shard count, identical
query results before/after) and Repair.h:20 (rebuild derived Rdbs from
titledb after a wipe, identical search results)."""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.control.rebalance import rebalance, repair
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.parallel import (
    ShardedCollection, make_mesh, sharded_search)
from tests.golden.corpus import golden_docs

QUERIES = ["alpha", "alpha bravo", '"lima kilo"', "report -alpha",
           "alpha AND NOT bravo", "site:site0.golden.test alpha",
           "charlie delta report"]


def _snap(res):
    return (res.total_matches,
            sorted((round(r.score, 3) for r in res.results), reverse=True))


def test_rebalance_grow_preserves_results(tmp_path):
    src = ShardedCollection("g", tmp_path / "old", n_shards=2)
    for url, html in golden_docs().items():
        src.index_document(url, html)
    before = {q: _snap(sharded_search(src, q, mesh=make_mesh(2), topk=10,
                                      site_cluster=False))
              for q in QUERIES}

    dst = rebalance("g", src, tmp_path / "new",
                    old_n_shards=2, new_n_shards=4)
    assert dst.num_docs == src.num_docs
    mesh4 = make_mesh(4)
    for q in QUERIES:
        after = _snap(sharded_search(dst, q, mesh=mesh4, topk=10,
                                     site_cluster=False))
        assert after == before[q], q

    # a NEW document routes consistently on the new topology
    dst.index_document(
        "http://site9.golden.test/late",
        "<html><head><title>Late alpha</title></head><body>"
        "<p>alpha latecomer joins.</p></body></html>")
    res = sharded_search(dst, "latecomer", mesh=mesh4, topk=5)
    assert res.total_matches == 1


def test_repair_rebuilds_from_titledb(tmp_path):
    c = Collection("r", tmp_path)
    for url, html in list(golden_docs().items())[:12]:
        docproc.index_document(c, url, html)
    from open_source_search_engine_tpu.query import engine
    before = {q: _snap(engine.search(c, q, topk=10, site_cluster=False))
              for q in QUERIES}

    # catastrophic posdb + linkdb + clusterdb loss
    c.posdb.wipe()
    c.clusterdb.wipe()
    c.linkdb.rdb.wipe()
    assert engine.search(c, "alpha", topk=10).total_matches == 0

    n = repair(c)
    assert n == 12
    for q in QUERIES:
        assert _snap(engine.search(c, q, topk=10,
                                   site_cluster=False)) == before[q], q


def test_rebalance_preserves_speller(tmp_path):
    src = ShardedCollection("sp", tmp_path / "o", n_shards=2)
    for url, html in list(golden_docs().items())[:10]:
        src.index_document(url, html)
    dst = rebalance("sp", src, tmp_path / "n", 2, 4)
    from open_source_search_engine_tpu.parallel.sharded import (
        suggest_sharded)
    from open_source_search_engine_tpu.query.compiler import compile_query
    # a misspelling of a corpus word still corrects on the new grid
    plan = compile_query("reprot")
    assert suggest_sharded(dst, plan) is not None
