"""Schedcheck scenario suites for the serving protocols.

Each scenario is a zero-arg callable that builds its own world —
threads via ``utils.threads``, primitives via the ``lockcheck``
factories, so everything cooperates with the active exploration — and
asserts the protocol's interleaving invariant. ``explore(fn)`` runs it
under N seeded schedules; any assertion, deadlock, or leaked thread
fails the schedule and shrinks to a minimal preemption trace.

Shared between ``tests/test_schedcheck.py`` (fast suite,
``OSSE_SCHED_BUDGET=64`` in check.sh) and ``bench.py``'s
``BENCH_SCHED=1`` deep run (1024 schedules per scenario).

The ``_Buggy*`` subclasses at the bottom re-introduce, TEST-LOCALLY,
the two historical interleaving bugs (PR 4's cache generation
re-read-at-put, PR 13's lone-hog displacement share) — the detector's
credibility gate: ``explore`` must find both within a bounded budget.
"""

from __future__ import annotations

import tempfile
import types
from datetime import datetime

import numpy as np

from open_source_search_engine_tpu.utils import threads
from open_source_search_engine_tpu.utils import deadline as deadline_mod
from open_source_search_engine_tpu.utils.schedcheck import sched_point, settle


# --------------------------------------------------------------------------
# 1. resident loop: drain-then-refresh vs in-flight waves
# --------------------------------------------------------------------------


class _FakeDI:
    """Duck-typed DeviceIndex: issue/collect with sched points so the
    explorer can preempt mid-wave."""

    def __init__(self, version: int):
        self._built_version = version

    def issue_batch(self, plans, topk: int = 64, lang: int = 0):
        sched_point("di.issue")
        return [("wave", self._built_version, len(plans))]

    def collect_batch(self, pending):
        sched_point("di.collect")
        return [(None, None, 0)] * pending[0][2]

    def resident_bytes(self) -> int:
        return 1024


def scenario_resident_refresh() -> None:
    """A write landing mid-flight must neither starve refresh (the
    post-write ticket resolves in bounded virtual time) nor leak a
    stale generation onto a ticket submitted after the write."""
    from open_source_search_engine_tpu.query import resident

    gen = {"v": 0}
    loop = resident.ResidentLoop(lambda: _FakeDI(gen["v"]),
                                 gen_fn=lambda: gen["v"], name="sched")
    try:
        t0 = loop.submit([("plan", 0)])

        def writer() -> None:
            sched_point("rdb.write")
            gen["v"] += 1
            sched_point("rdb.write.done")

        w = threads.spawn("writer", writer)
        t0.wait(timeout=30.0)        # liveness: the wave resolves
        w.join()
        want = gen["v"]              # stable: the only writer is done
        t1 = loop.submit([("plan", 1)])
        t1.wait(timeout=30.0)        # liveness: refresh window opened
        # drain-then-refresh: a ticket submitted AFTER the write
        # completed is issued against the refreshed base, never the
        # pre-write in-flight snapshot
        assert t1.generation == want, (t1.generation, want)
        assert t0.generation is not None
    finally:
        loop.stop()


# --------------------------------------------------------------------------
# 2. tenancy: single-flight promotion, rider expiry, leader failure
# --------------------------------------------------------------------------


def scenario_tenancy_promotion() -> None:
    from open_source_search_engine_tpu.query import engine
    from open_source_search_engine_tpu.serve import tenancy as tenancy_mod

    built = {"n": 0, "fail_first": True}

    def fake_gdi(coll):
        sched_point("engine.build")
        if built["fail_first"]:
            built["fail_first"] = False
            raise RuntimeError("leader build failed")
        built["n"] += 1
        return _FakeDI(0)

    orig = engine.get_device_index
    engine.get_device_index = fake_gdi
    rm = tenancy_mod.ResidencyManager()
    coll = types.SimpleNamespace(
        name="rx", posdb=types.SimpleNamespace(version=0))
    try:
        # leader failure: the error propagates to the leader and the
        # flight is cleared — no rider can wedge on a dead flight
        try:
            rm.loop_for(coll)
            raise AssertionError("leader failure did not propagate")
        except RuntimeError as exc:
            assert "leader build failed" in str(exc)
        assert rm._flights == {}, rm._flights

        # rider expiry: an expired deadline sheds out of a wedged
        # flight instead of queueing blind behind it
        rm._flights["rx"] = tenancy_mod._Flight()
        try:
            rm.loop_for(coll, deadline=deadline_mod.Deadline.after(0.0))
            raise AssertionError("expired rider did not shed")
        except deadline_mod.DeadlineExceeded:
            pass
        rm._flights.pop("rx")

        # single-flight: concurrent cold hits elect ONE leader; every
        # rider gets the same live loop and the index builds once
        got: list = []

        def hit(i: int) -> None:
            got.append(rm.loop_for(coll))

        ws = [threads.spawn(f"hit{i}", hit, i) for i in range(3)]
        for t in ws:
            t.join()
        assert len(got) == 3 and len({id(x) for x in got}) == 1, got
        assert built["n"] == 1, built["n"]
    finally:
        rm.stop_all()
        engine.get_device_index = orig


# --------------------------------------------------------------------------
# 3. cache plane: entry-time generation stamping vs concurrent writes
# --------------------------------------------------------------------------


def _cache_value_compute(gen: dict):
    def compute():
        v = gen["v"]                 # the data this compute actually read
        sched_point("cache.compute")
        return ("val", v)
    return compute


def scenario_cache_generation(cache_cls=None) -> None:
    """A value served under pinned generation g can never be a
    pre-write (older-generation) compute — the PR 4 invariant. The
    fixed GenCache stamps entries with the generation captured at
    get_or_compute ENTRY; re-reading at put time is the historical bug
    (:class:`BuggyGenCache`)."""
    from open_source_search_engine_tpu.cache import plane as plane_mod

    cls = cache_cls or plane_mod.GenCache
    gen = {"v": 0}
    cache = cls("schedgen", ttl_s=60.0, gen_fn=lambda: gen["v"])
    compute = _cache_value_compute(gen)

    def writer() -> None:
        sched_point("gen.bump")
        gen["v"] += 1

    def reader(i: int) -> None:
        cache.get_or_compute("k", compute)
        g0 = gen["v"]                # pin a generation...
        hit, hv = cache.lookup("k", gen=g0)
        if hit:                      # ...anything served under it must
            assert hv[1] >= g0, \
                f"pre-write value {hv} served as generation {g0}"

    ws = [threads.spawn("writer", writer),
          threads.spawn("r0", reader, 0),
          threads.spawn("r1", reader, 1)]
    for t in ws:
        t.join()


# --------------------------------------------------------------------------
# 4. admission gate: quota displacement vs grant ordering
# --------------------------------------------------------------------------


def scenario_admission_quota(gate_cls=None) -> None:
    """With the queue full of one hog's waiters, an under-share quiet
    arrival displaces the hog's newest waiter (reason ``quota``) and is
    eventually granted — it never sheds ``queue_full`` — the PR 13
    invariant. Grant order stays FIFO for the survivors."""
    from open_source_search_engine_tpu.serve import admission as admission_mod

    cls = gate_cls or admission_mod.AdmissionGate
    gate = cls(max_inflight=1, max_queue=2, max_wait_s=30.0,
               degraded_fn=lambda: False, pressure_fn=lambda: False)
    sheds: dict = {"quiet": None, "hogs": []}
    ran: list = []

    def hog_waiter(i: int) -> None:
        try:
            with gate.admit("interactive", tenant="hog"):
                sched_point("hog.run")
                ran.append(f"hog{i}")
        except admission_mod.Shed as exc:
            sheds["hogs"].append(exc.reason)

    def quiet() -> None:
        try:
            with gate.admit("interactive", tenant="quiet"):
                sched_point("quiet.run")
                ran.append("quiet")
        except admission_mod.Shed as exc:
            sheds["quiet"] = exc.reason

    slot = gate.admit("interactive", tenant="hog")   # hog holds the slot
    ws = [threads.spawn("hog1", hog_waiter, 1),
          threads.spawn("hog2", hog_waiter, 2)]
    settle()                         # both hog waiters queued: queue full
    ws.append(threads.spawn("quiet", quiet))
    settle()                         # the quiet arrival hits a full queue
    slot.__exit__(None, None, None)  # free the slot; grants drain FIFO
    for t in ws:
        t.join()
    assert sheds["quiet"] is None, \
        f"quiet tenant shed {sheds['quiet']!r} with a displaceable hog queued"
    assert "quiet" in ran, (ran, sheds)
    assert sheds["hogs"] == ["quota"], sheds  # newest hog waiter displaced
    assert gate._inflight == 0
    assert sum(len(q) for q in gate._waiting.values()) == 0


# --------------------------------------------------------------------------
# 5. Rdb write lock vs DailyMerge sweep
# --------------------------------------------------------------------------


def scenario_rdb_dailymerge() -> None:
    """Concurrent adds/dumps and forced DailyMerge sweeps conserve the
    key set exactly — the seed's unlocked merge-vs-writer mutation can
    never reappear without this failing."""
    import shutil

    from open_source_search_engine_tpu.control import dailymerge
    from open_source_search_engine_tpu.index import posdb, rdblite

    d = tempfile.mkdtemp(prefix="schedrdb")
    try:
        rdb = rdblite.Rdb("sched", d, posdb.KEY_DTYPE, journal=False)
        batches = [posdb.pack(termid=np.arange(1, 9) + 100 * b,
                              docid=np.arange(1, 9) + 1000 * b,
                              wordpos=np.full(8, b))
                   for b in range(1, 4)]

        def writer() -> None:
            for i, k in enumerate(batches):
                sched_point(f"rdb.add.{i}")
                rdb.add(k)
                rdb.dump()

        def merger() -> None:
            dm = dailymerge.DailyMerge(
                [types.SimpleNamespace(rdbs=lambda: {"sched": rdb})],
                types.SimpleNamespace(merge_quiet_hours="2-5"))
            sched_point("merge.sweep")
            assert dm.tick(now=datetime(2026, 1, 1, 3, 0))
            sched_point("merge.force")
            rdb.attempt_merge(force=True)

        ts = [threads.spawn("writer", writer),
              threads.spawn("merger", merger)]
        for t in ts:
            t.join()
        rdb.attempt_merge(force=True)
        allk = np.sort(np.concatenate(batches), order=("n2", "n1", "n0"))
        got = rdb.get_list(allk[0], allk[-1])
        assert len(got) == len(allk), (len(got), len(allk))
    finally:
        shutil.rmtree(d, ignore_errors=True)


#: the registry both the fast suite (tests) and the deep run (bench)
#: iterate — name → zero-arg scenario
SCENARIOS = {
    "resident_refresh": scenario_resident_refresh,
    "tenancy_promotion": scenario_tenancy_promotion,
    "cache_generation": scenario_cache_generation,
    "admission_quota": scenario_admission_quota,
    "rdb_dailymerge": scenario_rdb_dailymerge,
}


# --------------------------------------------------------------------------
# seeded historical bugs (test-local — NEVER in the tree)
# --------------------------------------------------------------------------


def make_buggy_cache_cls():
    """PR 4's generation-stamp race, reintroduced: the entry is stamped
    with the generation RE-READ at put time instead of the one captured
    at entry, so a write landing during the compute makes a pre-write
    value pass as post-write fresh."""
    from open_source_search_engine_tpu.cache import plane as plane_mod

    class BuggyGenCache(plane_mod.GenCache):
        def get_or_compute(self, key, compute, ttl_s=None,
                           gen=plane_mod._UNSET, swr_s=0.0):
            hit, v = self.lookup(key, gen=gen)
            if hit:
                return v, "hit"
            value = compute()
            sched_point("buggy.put")
            # BUG: gen defaults to _UNSET here, so put() re-reads
            # gen_fn() NOW — post-write — instead of the entry-time gen
            self.put(key, value, ttl_s=ttl_s, gen=gen)
            return value, "miss"

    return BuggyGenCache


def make_buggy_gate_cls():
    """PR 13's lone-hog displacement bug, reintroduced: the victim's
    share is computed WITHOUT counting the not-yet-queued arrival, so a
    lone hog's share is infinite, displacement never fires, and the
    quiet tenant sheds queue_full."""
    from open_source_search_engine_tpu.serve import admission as admission_mod

    class BuggyGate(admission_mod.AdmissionGate):
        def _displace_locked(self, tenant):
            if self._t_queued.get(tenant, 0) + 1 > \
                    self._share_locked(tenant):
                return False
            from open_source_search_engine_tpu.utils.priority import TIERS
            for t in reversed(TIERS):
                q = self._waiting[t]
                for i in range(len(q) - 1, -1, -1):
                    victim = q[i]
                    vt = victim.get("tenant")
                    if vt is None or vt == tenant:
                        continue
                    # BUG: no extra=tenant — the arrival isn't counted
                    # as active, a lone hog divides by one tenant
                    if self._t_queued.get(vt, 0) > self._share_locked(vt):
                        del q[i]
                        self._t_queued[vt] = \
                            self._t_queued.get(vt, 1) - 1
                        victim["shed"] = "quota"
                        self._cv.notify_all()
                        return True
            return False

    return BuggyGate
