"""Facets, numeric range operators, and date search (the datedb role).

Reference: ``gbmin:``/``gbmax:``/``gbsortby:``/``gbfacet:`` fielded
terms (``Query.h:209``), structured-document ingestion (``qa.cpp:2910``
qajson), and ``Datedb.h:60`` (date-constrained search)."""

import json

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.compiler import compile_query
from open_source_search_engine_tpu.query.engine import (search,
                                                        search_device)


def _doc(i):
    return json.dumps({
        "title": f"Product {i} widget",
        "body": "common widget words here",
        "price": 10.0 * (i + 1),
        "rating": i % 5,
        "category": "tools" if i % 3 == 0 else "toys",
        "date": f"2024-0{(i % 8) + 1}-15",
    })


@pytest.fixture(scope="module")
def coll(tmp_path_factory):
    c = Collection("facets", tmp_path_factory.mktemp("facets"))
    c.conf.pqr_enabled = False
    for i in range(24):
        docproc.index_document(c, f"http://shop.test/p{i}", _doc(i))
    return c


def test_json_fields_extracted_and_stored(coll):
    rec = docproc.get_document(coll, url="http://shop.test/p3")
    assert rec["fields"]["price"] == 40.0
    assert rec["fields"]["category"] == "tools"
    assert rec["fields"]["date"] > 1.7e9  # parsed to epoch seconds
    # numeric fields land in fielddb
    docids, vals = coll.fielddb.column("price")
    assert len(docids) == 24 and 40.0 in vals


def test_gbmin_gbmax_filters(coll):
    plan = compile_query("widget gbmin:price:55 gbmax:price:145")
    assert plan.filters == {"price": [55.0, 145.0]}
    res = search(coll, "widget gbmin:price:55 gbmax:price:145",
                 topk=24, site_cluster=False, with_snippets=False)
    # prices 60..140 → 9 docs
    assert res.total_matches == 9
    for r in res.results:
        rec = docproc.get_document(coll, docid=r.docid)
        assert 55.0 <= rec["fields"]["price"] <= 145.0


def test_filter_parity_flat_vs_device(coll):
    q = "widget gbmin:price:55 gbmax:price:145"
    host = search(coll, q, topk=24, site_cluster=False,
                  with_snippets=False)
    dev = search_device(coll, q, topk=24, site_cluster=False,
                        with_snippets=False)
    assert dev.total_matches == host.total_matches
    assert {r.docid for r in dev.results} == \
        {r.docid for r in host.results}
    assert [round(r.score, 3) for r in dev.results] == \
        [round(r.score, 3) for r in host.results]


def test_gbsortby_numeric(coll):
    res = search(coll, "widget gbsortby:price", topk=5,
                 site_cluster=False, with_snippets=False)
    prices = [docproc.get_document(coll, docid=r.docid)["fields"]["price"]
              for r in res.results]
    assert prices == sorted(prices, reverse=True)  # descending
    res2 = search(coll, "widget gbsortbyrev:price", topk=5,
                  site_cluster=False, with_snippets=False)
    prices2 = [docproc.get_document(coll, docid=r.docid)["fields"]["price"]
               for r in res2.results]
    assert prices2 == sorted(prices2)  # ascending


def test_gbsortby_date_parity(coll):
    q = "widget gbsortby:date"
    host = search(coll, q, topk=8, site_cluster=False,
                  with_snippets=False)
    dev = search_device(coll, q, topk=8, site_cluster=False,
                        with_snippets=False)
    dates = [docproc.get_document(coll, docid=r.docid)["fields"]["date"]
             for r in host.results]
    assert dates == sorted(dates, reverse=True)  # newest first
    assert [round(r.score, 3) for r in dev.results] == \
        [round(r.score, 3) for r in host.results]


def test_gbfacet_counts(coll):
    res = search(coll, "widget gbfacet:category", topk=10,
                 site_cluster=False, with_snippets=False)
    facets = dict(res.facets["category"])
    assert facets["tools"] == 8 and facets["toys"] == 16
    dev = search_device(coll, "widget gbfacet:category", topk=10,
                        site_cluster=False, with_snippets=False)
    dfac = dict(dev.facets["category"])
    assert dfac["tools"] >= 1 and dfac["toys"] >= 1  # sampled


def test_delete_removes_field_records(tmp_path):
    c = Collection("fdel", tmp_path)
    c.conf.pqr_enabled = False
    docproc.index_document(c, "http://shop.test/x", _doc(1))
    assert len(c.fielddb.column("price")[0]) == 1
    docproc.remove_document(c, "http://shop.test/x")
    assert len(c.fielddb.column("price")[0]) == 0


def test_date_range_filter(coll):
    # docs dated 2024-03-15 .. 2024-05-15 only
    import calendar
    lo = calendar.timegm((2024, 3, 1, 0, 0, 0))
    hi = calendar.timegm((2024, 5, 30, 0, 0, 0))
    res = search(coll, f"widget gbmin:date:{lo} gbmax:date:{hi}",
                 topk=24, site_cluster=False, with_snippets=False)
    assert res.total_matches == 9  # months 3,4,5 → 3 each
    for r in res.results:
        d = docproc.get_document(coll, docid=r.docid)["fields"]["date"]
        assert lo <= d <= hi


def test_sharded_filter_parity(tmp_path):
    from open_source_search_engine_tpu.parallel import (make_mesh,
                                                        sharded_search)
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("fshard", tmp_path, n_shards=4)
    for row in sc.grid:
        for c in row:
            c.conf.pqr_enabled = False
    for i in range(24):
        sc.index_document(f"http://shop.test/p{i}", _doc(i))
    mesh = make_mesh(4)
    res = sharded_search(sc, "widget gbmin:price:55 gbmax:price:145",
                         mesh=mesh, topk=24, site_cluster=False,
                         with_snippets=False)
    assert res.total_matches == 9
