"""Cache plane tests — generation invalidation, single-flight,
stale-while-revalidate, memory-pressure shedding, and the cluster
wiring (RdbCache consolidation).

Pins the contract of :mod:`..cache.plane` plus the two hot-path
integrations: a write on shard 1 must never flush shard 0's leg
entries (per-shard generations), and the inject→query→delete→query
round trip must never serve a stale SERP — the write bumps the
generation BEFORE the RPC leaves, and the bump is observed
cluster-wide through the X-OSSE-Gen reply headers.
"""

import json
import threading
import time
import urllib.request

import pytest

from open_source_search_engine_tpu.cache import GenCache, g_cacheplane
from open_source_search_engine_tpu.parallel import cluster as cl
from open_source_search_engine_tpu.serve.server import SearchHTTPServer
from open_source_search_engine_tpu.utils import ghash
from open_source_search_engine_tpu.utils.membudget import g_membudget
from open_source_search_engine_tpu.utils.parms import CollectionConf


def _doc(i, words="cluster shared words"):
    return (f"<html><head><title>Doc {i}</title></head><body>"
            f"<p>{words} token{i}.</p></body></html>")


def _node(tmp_path, name, n_docs=3, start=True, port=0):
    node = cl.ShardNodeServer(tmp_path / name, port=port)
    for i in range(n_docs):
        node.handle("/rpc/index", {"url": f"http://t.test/{name}{i}",
                                   "content": _doc(i)})
    if start:
        node.start()
    return node


def _drain(client, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while client.pending_writes and time.monotonic() < deadline:
        time.sleep(0.02)
    assert client.pending_writes == 0


def _url_for_shard(client, shard, tag):
    """A url that docid-routes to the given shard (probe, like the
    reference's test fixtures pick per-group urls)."""
    for i in range(1000):
        u = f"http://gen.test/{tag}{i}"
        if int(client.hostmap.shard_of_docid(ghash.doc_id(u))) == shard:
            return u
    raise AssertionError("no url routed to shard %d" % shard)


# ---------------------------------------------------------------------------
# GenCache core contract
# ---------------------------------------------------------------------------

class TestGenCache:
    def test_generation_invalidation_is_o1(self):
        c = GenCache("t.gen", ttl_s=60)
        c.put("k", "old", gen=1)
        assert c.lookup("k", gen=1) == (True, "old")
        # the generation moving kills the entry with zero scanning
        assert c.lookup("k", gen=2) == (False, None)
        c.put("k", "new", gen=2)
        assert c.lookup("k", gen=2) == (True, "new")

    def test_gen_fn_supplies_default_generation(self):
        gen = [1]
        c = GenCache("t.genfn", ttl_s=60, gen_fn=lambda: gen[0])
        c.put("k", "v")
        assert c.get("k") == "v"
        gen[0] = 2
        assert c.get("k") is None

    def test_none_values_cacheable(self):
        # negative DNS answers ARE the cached value — lookup's (hit,
        # value) form must distinguish them from a miss
        c = GenCache("t.none", ttl_s=60)
        c.put("k", None)
        assert c.lookup("k") == (True, None)
        assert c.lookup("absent") == (False, None)

    def test_eviction_drops_dead_generation_first(self):
        c = GenCache("t.evict", ttl_s=60, max_entries=4)
        for i in range(3):
            c.put(("dead", i), i, gen=1)
        c.put(("live", 0), 0, gen=2)
        # at cap: the room-making sweep must shed the dead-gen entries
        # and keep the one live entry
        c.put(("live", 1), 1, gen=2)
        assert c.lookup(("live", 0), gen=2) == (True, 0)
        assert c.lookup(("live", 1), gen=2) == (True, 1)
        assert all(("dead", i) not in c._d for i in range(3))

    def test_single_flight_one_compute(self):
        c = GenCache("t.sf", ttl_s=60)
        n_threads = 8
        calls = []
        barrier = threading.Barrier(n_threads)
        statuses = []
        lock = threading.Lock()

        def compute():
            calls.append(1)
            time.sleep(0.25)  # hold the flight open while others join
            return "answer"

        def worker():
            barrier.wait()
            v, status = c.get_or_compute("hot", compute)
            with lock:
                statuses.append((v, status))

        ts = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert len(calls) == 1  # the whole stampede ran ONE compute
        assert all(v == "answer" for v, _ in statuses)
        kinds = [s for _, s in statuses]
        assert kinds.count("miss") == 1
        assert set(kinds) <= {"miss", "join", "hit"}

    def test_single_flight_leader_error_propagates(self):
        c = GenCache("t.sferr", ttl_s=60)
        entered = threading.Event()
        errors = []

        def compute():
            entered.set()
            time.sleep(0.1)
            raise RuntimeError("boom")

        def leader():
            try:
                c.get_or_compute("k", compute)
            except RuntimeError as e:
                errors.append(("leader", str(e)))

        def follower():
            entered.wait(5)
            try:
                c.get_or_compute("k", compute)
            except RuntimeError as e:
                errors.append(("follower", str(e)))

        tl = threading.Thread(target=leader)
        tf = threading.Thread(target=follower)
        tl.start()
        tf.start()
        tl.join(timeout=10)
        tf.join(timeout=10)
        # retrying in lockstep is the stampede single-flight prevents:
        # the leader's failure reaches every waiter, and at most one
        # late-arriving follower re-runs the compute
        assert ("leader", "boom") in errors
        assert len(errors) == 2

    def test_compute_racing_a_write_stores_a_dead_entry(self):
        # the generation is captured at ENTRY: a write landing during
        # the compute must leave the stored entry dead (a later miss),
        # never stamp the pre-write result with the post-write gen
        gen = [1]
        c = GenCache("t.race", ttl_s=60, gen_fn=lambda: gen[0])

        def compute():
            gen[0] = 2  # a write lands mid-compute
            return "pre-write"

        v, status = c.get_or_compute("k", compute)
        assert (v, status) == ("pre-write", "miss")
        # the entry carries the entry-time gen (1) → post-write lookups
        # (gen 2) miss instead of serving the pre-write value as fresh
        assert c.lookup("k") == (False, None)

    def test_no_join_across_a_generation_move(self):
        # a flight started under gen 1 must not hand its (pre-write)
        # result to a caller arriving after the write moved gen to 2
        gen = [1]
        c = GenCache("t.sfgen", ttl_s=60, gen_fn=lambda: gen[0])
        entered = threading.Event()
        release = threading.Event()
        out = {}

        def slow_pre_write():
            entered.set()
            release.wait(5)
            return "pre-write"

        t = threading.Thread(target=lambda: out.update(
            leader=c.get_or_compute("k", slow_pre_write)))
        t.start()
        assert entered.wait(5)
        gen[0] = 2  # the write lands while the leader computes
        v, status = c.get_or_compute("k", lambda: "post-write")
        assert (v, status) == ("post-write", "miss")  # NOT a join
        release.set()
        t.join(timeout=10)
        assert out["leader"] == ("pre-write", "miss")
        # the leader's late put is stamped gen 1 → dead at gen 2
        assert c.lookup("k") == (False, None)

    def test_swr_serves_stale_then_refreshes(self):
        c = GenCache("t.swr", ttl_s=0.05)
        versions = iter(["v1", "v2"])
        v, status = c.get_or_compute("k", lambda: next(versions))
        assert (v, status) == ("v1", "miss")
        time.sleep(0.08)  # past TTL, inside the swr window
        v, status = c.get_or_compute("k", lambda: next(versions),
                                     swr_s=10.0)
        assert (v, status) == ("v1", "stale")  # served immediately
        # the background refresh lands the fresh value under a new TTL
        for _ in range(100):
            if c.get("k") == "v2":
                break
            time.sleep(0.02)
        assert c.get("k") == "v2"
        assert c.stats()["stale_served"] == 1

    def test_swr_never_crosses_a_generation_move(self):
        c = GenCache("t.swrgen", ttl_s=0.05)
        c.put("k", "old", gen=1)
        time.sleep(0.08)
        # expired AND the generation moved: swr must NOT soften a
        # write — this is a plain miss
        v, status = c.get_or_compute("k", lambda: "new", gen=2,
                                     swr_s=10.0)
        assert (v, status) == ("new", "miss")

    def test_swr_refresh_racing_a_write_stores_a_dead_entry(self):
        # the background SWR refresh stamps with the gen the stale
        # serve happened under — a write landing mid-refresh must
        # leave a dead entry, not a pre-write value passing as fresh
        gen = [1]
        c = GenCache("t.swrrace", ttl_s=0.05, gen_fn=lambda: gen[0])
        c.put("k", "old")
        time.sleep(0.08)  # past TTL, inside the swr window

        def refresh_with_write():
            gen[0] = 2  # a write lands during the refresh
            return "pre-write"

        v, status = c.get_or_compute("k", refresh_with_write,
                                     swr_s=10.0)
        assert (v, status) == ("old", "stale")
        for _ in range(100):  # wait out the background refresh
            with c._lock:
                if "k" not in c._inflight:
                    break
            time.sleep(0.02)
        assert c.lookup("k") == (False, None)

    def test_disabled_cache_is_transparent(self):
        c = GenCache("t.off", ttl_s=60)
        c.enabled = False
        c.put("k", "v")
        assert c.lookup("k") == (False, None)
        v, status = c.get_or_compute("k", lambda: "computed")
        assert (v, status) == ("computed", "miss")
        assert c.stats()["entries"] == 0

    def test_plane_registry_uniquifies_and_flushes(self):
        c1 = g_cacheplane.register("t.reg", ttl_s=60)
        c2 = g_cacheplane.register("t.reg", ttl_s=60)
        assert c1.name == "t.reg" and c2.name == "t.reg#2"
        c1.put("a", "x" * 100)
        freed = g_cacheplane.flush("t.reg")
        assert freed > 0 and c1.stats()["entries"] == 0
        assert "t.reg" in g_cacheplane.snapshot()


# ---------------------------------------------------------------------------
# membudget integration
# ---------------------------------------------------------------------------

class TestMemoryPressure:
    def test_pressure_sheds_cache_before_refusing_real_work(self):
        """An over-budget pack reservation must empty the cache plane
        rather than be refused — a cache is droppable by definition,
        a query packer's staging arrays are not."""
        cache = g_cacheplane.register("t.pressure", ttl_s=60,
                                      max_entries=256)
        payload = "x" * (64 << 10)
        for i in range(64):
            cache.put(i, payload)
        assert g_membudget.used("cache") >= cache.stats()["bytes"] > 0
        old_limit = g_membudget.limit
        # other tests may have reset() the budget, dropping the
        # plane's weakly-held hook — re-adding is idempotent enough
        g_membudget.add_pressure_handler(g_cacheplane._on_pressure)
        try:
            g_membudget.set_limit(g_membudget.used() + (1 << 20))
            need = 2 << 20  # only fits if the cache plane sheds
            assert g_membudget.reserve("pack", need)
            assert cache.stats()["entries"] == 0
            assert g_membudget.used("cache") < (64 << 10) * 64
            g_membudget.release("pack", need)
        finally:
            g_membudget.set_limit(old_limit)


# ---------------------------------------------------------------------------
# shard-node /rpc/search cache
# ---------------------------------------------------------------------------

class TestShardNodeCache:
    def test_search_cached_and_write_invalidated(self, tmp_path):
        node = cl.ShardNodeServer(tmp_path / "n", port=0)
        for i in range(3):
            node.handle("/rpc/index",
                        {"url": f"http://t.test/n{i}",
                         "content": _doc(i, words="walrus herd")})
        h0 = node._search_cache.hits
        out1 = node.handle("/rpc/search", {"q": "walrus", "topk": 5})
        out2 = node.handle("/rpc/search", {"q": "walrus", "topk": 5})
        assert out2["total"] == out1["total"] == 3
        assert node._search_cache.hits == h0 + 1
        # a write moves posdb.version: the third search recomputes and
        # sees the new doc — no stale window
        node.handle("/rpc/index",
                    {"url": "http://t.test/new",
                     "content": _doc(9, words="walrus herd")})
        out3 = node.handle("/rpc/search", {"q": "walrus", "topk": 5})
        assert out3["total"] == 4
        assert node._search_cache.hits == h0 + 1  # that one missed
        assert out3["gen"] > out1["gen"]

    def test_batched_riders_hit_the_cache(self, tmp_path):
        node = cl.ShardNodeServer(tmp_path / "nb", port=0)
        for i in range(3):
            node.handle("/rpc/index",
                        {"url": f"http://t.test/b{i}",
                         "content": _doc(i, words="ibex ridge")})
        qs = ["ibex", "ridge"]
        node.handle("/rpc/search", {"queries": qs, "topk": 5})
        h0 = node._search_cache.hits
        out = node.handle("/rpc/search", {"queries": qs, "topk": 5})
        assert node._search_cache.hits == h0 + len(qs)
        assert [int(r["total"]) for r in out["results"]] == [3, 3]


# ---------------------------------------------------------------------------
# cluster generations
# ---------------------------------------------------------------------------

class TestClusterGenerations:
    def _cluster(self, tmp_path):
        a = _node(tmp_path, "a")
        b = _node(tmp_path, "b")
        conf = cl.HostsConf.parse(
            f"num-mirrors: 0\n127.0.0.1:{a.port}\n127.0.0.1:{b.port}")
        client = cl.ClusterClient(conf, use_heartbeat=False)
        return a, b, client

    def test_write_on_shard1_keeps_shard0_legs(self, tmp_path):
        a, b, client = self._cluster(tmp_path)
        try:
            # the first scatter's replies fold the node generations in
            # (X-OSSE-Gen); the probed query's legs — captured AFTER
            # that — are stored under the settled generations (a leg's
            # gen is snapped before its RPC, so the very first scatter
            # on a cold client stores already-dead legs by design:
            # correctness over hit rate)
            client.search("token0", topk=5)
            client.search("token1", topk=5)
            keys0 = [k for k in client._leg_cache._d
                     if k[0] == 0 and k[1] == "token1"]
            keys1 = [k for k in client._leg_cache._d
                     if k[0] == 1 and k[1] == "token1"]
            assert keys0 and keys1
            assert client._leg_cache.lookup(
                keys0[0], gen=client.shard_gen(0))[0]
            assert client._leg_cache.lookup(
                keys1[0], gen=client.shard_gen(1))[0]
            gv0 = client.gen_vector()
            # a write routed to shard 1 ...
            u = _url_for_shard(client, 1, "w")
            client.index_document(u, _doc(50))
            _drain(client)
            # ... kills shard 1's legs (local counter bumped BEFORE
            # the send, node gen folded from the write ack) ...
            assert not client._leg_cache.lookup(
                keys1[0], gen=client.shard_gen(1))[0]
            # ... while shard 0's legs stay perfectly live
            assert client._leg_cache.lookup(
                keys0[0], gen=client.shard_gen(0))[0]
            gv1 = client.gen_vector()
            assert gv1[0] == gv0[0]  # shard 0's pair untouched
            assert gv1[1] != gv0[1]  # shard 1's pair moved
            assert gv1[1][0] == gv0[1][0] + 1  # the local half
            assert gv1[1][1] > gv0[1][1]       # the observed-node half
        finally:
            client.close()
            a.stop()
            b.stop()

    def test_result_cache_keys_on_conf_values_not_identity(self, tmp_path):
        """The SERP key must use the conf's PQR factor VALUES, never
        id(conf): CPython reuses freed ids (a new conf could alias a
        dead one's entries), and equal-but-distinct confs should
        share."""
        a, b, client = self._cluster(tmp_path)
        try:
            warm = CollectionConf()
            # first scatter settles the node generations; second fills
            # a live entry under them
            client.search("token0", topk=5, conf=warm)
            client.search("token0", topk=5, conf=warm)
            h0 = client._result_cache.hits
            # a DIFFERENT conf object with equal factors shares it
            client.search("token0", topk=5, conf=CollectionConf())
            assert client._result_cache.hits == h0 + 1
            # changed PQR factors → a distinct entry, not an alias
            client.search("token0", topk=5,
                          conf=CollectionConf(pqr_enabled=False))
            assert client._result_cache.hits == h0 + 1
        finally:
            client.close()
            a.stop()
            b.stop()

    def test_inject_query_delete_query_no_stale_result(self, tmp_path):
        """The acceptance regression: a deleted doc must never ride a
        cached SERP — the generation bump is observed cluster-wide in
        this same test (local half at send time, node half via the
        reply header)."""
        a, b, client = self._cluster(tmp_path)
        try:
            u = _url_for_shard(client, 0, "zeb")
            client.index_document(
                u, _doc(7, words="zebra quagga savanna"))
            _drain(client)
            # the first scatter on a cold client folds the node
            # generations in via X-OSSE-Gen, so its own entry — stamped
            # with the ENTRY-time gen, by design — is already dead
            # (correctness over hit rate); it settles the gens for the
            # searches under test
            client.search("zebra", topk=5)
            res1 = client.search("zebra", topk=5)
            assert res1.total_matches == 1
            assert res1.results[0].url == u
            # second identical query rides the front result cache
            h0 = client._result_cache.hits
            res2 = client.search("zebra", topk=5)
            assert client._result_cache.hits == h0 + 1
            assert res2.results[0].url == u
            gv_before = client.gen_vector()
            client.remove_document(u)
            _drain(client)
            gv_after = client.gen_vector()
            assert gv_after[0] != gv_before[0]       # bump seen
            assert gv_after[0][0] == gv_before[0][0] + 1   # local half
            assert gv_after[0][1] > gv_before[0][1]  # node half (ack)
            # the very next query recomputes: no stale window at all
            res3 = client.search("zebra", topk=5)
            assert res3.total_matches == 0
            assert all(r.url != u for r in res3.results)
        finally:
            client.close()
            a.stop()
            b.stop()


# ---------------------------------------------------------------------------
# serve-plane regression (flat mode)
# ---------------------------------------------------------------------------

class TestServerDeleteRegression:
    def test_inject_query_delete_query(self, tmp_path):
        srv = SearchHTTPServer(str(tmp_path), port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            html = (b"<html><title>D</title><body>"
                    b"<p>ephemeral okapi content</p></body></html>")
            for i in (1, 2):
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/inject?url=http://d.test/{i}", data=html),
                    timeout=60)
            out = json.load(urllib.request.urlopen(
                f"{base}/search?q=okapi&format=json", timeout=60))
            assert out["totalMatches"] == 2
            h0 = srv.stats.get("result_cache_hits", 0)
            urllib.request.urlopen(f"{base}/search?q=okapi&format=json",
                                   timeout=60)
            assert srv.stats.get("result_cache_hits", 0) == h0 + 1
            # the delete bumps the index generation: the next search
            # MUST NOT serve the cached two-result page
            with urllib.request.urlopen(
                    f"{base}/delete?url=http://d.test/1",
                    timeout=60) as r:
                assert json.load(r)["deleted"] == "http://d.test/1"
            out = json.load(urllib.request.urlopen(
                f"{base}/search?q=okapi&format=json", timeout=60))
            assert out["totalMatches"] == 1
            assert all(res["url"] != "http://d.test/1"
                       for res in out["results"])
            # deleting a url that was never indexed 404s
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"{base}/delete?url=http://d.test/ghost",
                    timeout=60)
        finally:
            srv.stop()

    def test_admin_cache_page_lists_and_flushes(self, tmp_path):
        srv = SearchHTTPServer(str(tmp_path), port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            out = json.load(urllib.request.urlopen(
                f"{base}/admin/cache?format=json", timeout=60))
            assert "server.results" in out["caches"]
            assert out["enabled"] is True
            out = json.load(urllib.request.urlopen(
                f"{base}/admin/cache?flush=all&format=json",
                timeout=60))
            assert "flushed_bytes" in out
        finally:
            srv.stop()
