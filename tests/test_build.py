"""Document pipeline tests — modeled on the reference's parser-consistency
harness (``Test.cpp``, ``gb parsetest``) and the qainject scenarios
(``qa.cpp:659``): tokenizer hashgroup assignment, rank semantics,
inject → read back, delete → gone, reindex consistency."""

import numpy as np

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.build.tokenizer import tokenize_html
from open_source_search_engine_tpu.index import posdb, titledb
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.utils import ghash
from open_source_search_engine_tpu.utils.lang import LANG_ENGLISH, LANG_GERMAN, detect_language

HTML = """
<html><head><title>Tiger Habitat</title>
<meta name="description" content="All about tigers">
<script>var x = "ignoreme";</script>
<style>.c { color: red }</style>
</head><body>
<h1>The Siberian Tiger</h1>
<p>The tiger is the largest living cat species. Tigers are apex predators.</p>
<ul><li>Bengal tiger</li><li>Siberian tiger</li></ul>
<nav><a href="/about">About tigers</a></nav>
<p>Visit <a href="http://cats.example.com/lions">our lion page</a> too.</p>
</body></html>
"""


class TestTokenizer:
    def test_hashgroups_assigned(self):
        doc = tokenize_html(HTML, "http://example.com/tigers")
        by_hg = {}
        for t in doc.tokens:
            by_hg.setdefault(t.hashgroup, []).append(t.word)
        assert "habitat" in by_hg[posdb.HASHGROUP_TITLE]
        assert "siberian" in by_hg[posdb.HASHGROUP_HEADING]
        assert "largest" in by_hg[posdb.HASHGROUP_BODY]
        assert "bengal" in by_hg[posdb.HASHGROUP_INLIST]
        assert "about" in by_hg[posdb.HASHGROUP_INMENU]
        assert "description" not in str(by_hg.get(posdb.HASHGROUP_BODY, []))
        assert "tigers" in by_hg[posdb.HASHGROUP_INMETATAG]
        assert "example" in by_hg[posdb.HASHGROUP_INURL]

    def test_script_and_style_skipped(self):
        doc = tokenize_html(HTML)
        words = {t.word for t in doc.tokens}
        assert "ignoreme" not in words
        assert "color" not in words

    def test_links_with_anchor_text(self):
        doc = tokenize_html(HTML)
        hrefs = dict(doc.links)
        assert hrefs["http://cats.example.com/lions"] == "our lion page"

    def test_positions_increase(self):
        doc = tokenize_html(HTML)
        body = [t for t in doc.tokens if t.hashgroup == posdb.HASHGROUP_BODY]
        pos = [t.wordpos for t in body]
        assert pos == sorted(pos)
        assert len(set(pos)) == len(pos)

    def test_title_extracted(self):
        assert tokenize_html(HTML).title.strip() == "Tiger Habitat"


class TestRanks:
    def test_density_higher_for_shorter_sentence(self):
        """A one-word title outranks a long body sentence in density
        (reference getDensityRanks: 31 - (count-1))."""
        ml = docproc.build_meta_list("http://a.com/", HTML)
        f = posdb.unpack(ml.posdb_keys)
        title_mask = f["hashgroup"] == posdb.HASHGROUP_TITLE
        body_mask = f["hashgroup"] == posdb.HASHGROUP_BODY
        assert f["densityrank"][title_mask].max() > \
            f["densityrank"][body_mask].min()

    def test_spam_rank_docked_for_repetition(self):
        spammy = "buy " * 60 + "now this text has other words in it too " * 2
        ml = docproc.build_meta_list("http://spam.com/", spammy, is_html=False)
        f = posdb.unpack(ml.posdb_keys)
        tid = ghash.term_id("buy")
        spam_ranks = f["wordspamrank"][f["termid"] == tid]
        assert len(spam_ranks) and spam_ranks.max() < posdb.MAXWORDSPAMRANK

    def test_language_detected(self):
        assert detect_language("the cat is on the mat with the dog".split()) \
            == LANG_ENGLISH
        assert detect_language(
            "der hund und die katze sind nicht im haus".split()) == LANG_GERMAN


class TestMetaList:
    def test_bigrams_present(self):
        ml = docproc.build_meta_list("http://a.com/", HTML)
        f = posdb.unpack(ml.posdb_keys)
        assert ghash.bigram_id("apex", "predators") in f["termid"]

    def test_site_term_and_checksum_term(self):
        ml = docproc.build_meta_list("http://www.a.com/x", HTML)
        f = posdb.unpack(ml.posdb_keys)
        assert ghash.term_id("www.a.com", prefix="site") in f["termid"]
        assert f["shardbytermid"].sum() == 1  # exactly the checksum term

    def test_delete_flag_makes_tombstones(self):
        ml = docproc.build_meta_list("http://a.com/", HTML, delete=True)
        f = posdb.unpack(ml.posdb_keys)
        assert not f["delbit"].any()


class TestIndexDocument:
    def test_inject_and_read_back(self, tmp_path):
        coll = Collection("main", tmp_path)
        ml = docproc.index_document(coll, "http://example.com/tigers", HTML)
        assert coll.num_docs == 1
        # termlist for 'tiger' contains our doc
        tid = ghash.term_id("tiger")
        lst = coll.posdb.get_list(posdb.start_key(tid), posdb.end_key(tid))
        f = posdb.unpack(lst.keys)
        assert ml.docid in f["docid"]
        # titlerec round-trips
        rec = docproc.get_document(coll, "http://example.com/tigers")
        assert rec["title"] == "Tiger Habitat"
        assert rec["site"] == "example.com"

    def test_delete_document(self, tmp_path):
        coll = Collection("main", tmp_path)
        docproc.index_document(coll, "http://example.com/t", HTML)
        assert docproc.remove_document(coll, "http://example.com/t")
        assert coll.num_docs == 0
        tid = ghash.term_id("tiger")
        lst = coll.posdb.get_list(posdb.start_key(tid), posdb.end_key(tid))
        assert len(lst) == 0
        assert docproc.get_document(coll, "http://example.com/t") is None

    def test_reindex_replaces_not_duplicates(self, tmp_path):
        coll = Collection("main", tmp_path)
        docproc.index_document(coll, "http://a.com/", HTML)
        html2 = "<html><title>New</title><body>leopard</body></html>"
        docproc.index_document(coll, "http://a.com/", html2)
        assert coll.num_docs == 1
        # old terms gone, new terms present
        tid_old = ghash.term_id("tiger")
        tid_new = ghash.term_id("leopard")
        assert len(coll.posdb.get_list(posdb.start_key(tid_old),
                                       posdb.end_key(tid_old))) == 0
        assert len(coll.posdb.get_list(posdb.start_key(tid_new),
                                       posdb.end_key(tid_new))) == 1
        assert docproc.get_document(coll, "http://a.com/")["title"] == "New"

    def test_survives_dump_and_restart(self, tmp_path):
        coll = Collection("main", tmp_path)
        docproc.index_document(coll, "http://a.com/", HTML)
        coll.dump_all()
        coll.save()
        coll2 = Collection("main", tmp_path)
        assert docproc.get_document(coll2, "http://a.com/")["title"] \
            == "Tiger Habitat"
        tid = ghash.term_id("tiger")
        assert len(coll2.posdb.get_list(posdb.start_key(tid),
                                        posdb.end_key(tid))) > 0


class TestInlinkText:
    """Inlink anchor-text ranking — the reference's strongest signal
    (XmlDoc::hashIncomingLinkText, HASHGROUP_INLINKTEXT weight 16.0 with
    LINKER_WEIGHTS on the linker's siterank, Posdb.cpp:1105,1136)."""

    LINKEE = "http://target.example.com/widgets"
    LINKEE_HTML = ("<html><head><title>Products</title></head><body>"
                   "<p>our catalog page lists many products.</p>"
                   "</body></html>")
    DECOY = "http://decoy.example.com/frob"
    DECOY_HTML = ("<html><head><title>Frobnicator</title></head><body>"
                  "<p>frobnicator mentioned once in passing text body "
                  "somewhere deep.</p></body></html>")
    LINKER = "http://blog.example.org/post"
    LINKER_HTML = ("<html><head><title>Blog</title></head><body>"
                   "<p>check out this <a href="
                   "\"http://target.example.com/widgets\">frobnicator "
                   "deluxe</a> thing.</p></body></html>")

    def test_anchor_only_term_ranks_first(self, tmp_path):
        """'frobnicator' appears in the linkee ONLY via its inlink
        anchor — yet the linkee must outrank a page containing the word
        in its body (inlink weight 16 vs body 1)."""
        c = Collection("il1", tmp_path)
        docproc.index_document(c, self.LINKEE, self.LINKEE_HTML)
        docproc.index_document(c, self.DECOY, self.DECOY_HTML)
        docproc.index_document(c, self.LINKER, self.LINKER_HTML,
                               siterank=8)
        res = engine.search(c, "frobnicator", site_cluster=False)
        urls = [r.url for r in res.results]
        assert self.LINKEE in urls  # linker indexed AFTER linkee: reindex
        assert urls[0] == self.LINKEE
        # the linker page itself also matches (anchor is body text there)
        assert res.total_matches >= 2

    def test_linker_first_order_independence(self, tmp_path):
        """Linker crawled BEFORE the linkee: the harvest at linkee index
        time picks the anchor up — same ranking either way."""
        c = Collection("il2", tmp_path)
        docproc.index_document(c, self.LINKER, self.LINKER_HTML,
                               siterank=8)
        docproc.index_document(c, self.DECOY, self.DECOY_HTML)
        docproc.index_document(c, self.LINKEE, self.LINKEE_HTML)
        res = engine.search(c, "frobnicator", site_cluster=False)
        assert res.results[0].url == self.LINKEE

    def test_delete_linker_removes_anchor_signal(self, tmp_path):
        """Deleting the linker propagates: the linkee reindexes on its
        own and loses the weight-16 anchor postings (no manual refresh)."""
        c = Collection("il3", tmp_path)
        docproc.index_document(c, self.LINKEE, self.LINKEE_HTML)
        docproc.index_document(c, self.LINKER, self.LINKER_HTML)
        assert any(r.url == self.LINKEE for r in
                   engine.search(c, "frobnicator").results)
        docproc.remove_document(c, self.LINKER)
        res = engine.search(c, "frobnicator")
        assert not any(r.url == self.LINKEE for r in res.results)

    def test_recrawled_linker_dropping_link_removes_signal(self, tmp_path):
        """The linker is re-indexed WITHOUT the link: its old edge is
        tombstoned and the former linkee must stop ranking for the
        anchor-only term."""
        c = Collection("il6", tmp_path)
        docproc.index_document(c, self.LINKEE, self.LINKEE_HTML)
        docproc.index_document(c, self.LINKER, self.LINKER_HTML)
        assert engine.search(c, "frobnicator").results[0].url == self.LINKEE
        docproc.index_document(
            c, self.LINKER,
            "<html><head><title>Blog</title></head><body>"
            "<p>nothing linked here any more.</p></body></html>")
        res = engine.search(c, "frobnicator")
        assert not any(r.url == self.LINKEE for r in res.results)

    def test_resident_path_parity_with_inlinks(self, tmp_path):
        from open_source_search_engine_tpu.query.engine import search_device
        c = Collection("il4", tmp_path)
        docproc.index_document(c, self.LINKEE, self.LINKEE_HTML)
        docproc.index_document(c, self.DECOY, self.DECOY_HTML)
        docproc.index_document(c, self.LINKER, self.LINKER_HTML,
                               siterank=8)
        host = engine.search(c, "frobnicator", site_cluster=False)
        dev = search_device(c, "frobnicator", site_cluster=False)
        assert dev.total_matches == host.total_matches
        key = lambda r: (-round(r.score, 3), r.docid)
        assert sorted(map(key, dev.results)) == \
               sorted(map(key, host.results))

    def test_sharded_inlink_ranking(self, tmp_path):
        from open_source_search_engine_tpu.parallel import (
            ShardedCollection, sharded_search)
        sc = ShardedCollection("il5", tmp_path, n_shards=4)
        sc.index_document(self.LINKEE, self.LINKEE_HTML)
        sc.index_document(self.DECOY, self.DECOY_HTML)
        sc.index_document(self.LINKER, self.LINKER_HTML, siterank=8)
        res = sharded_search(sc, "frobnicator", site_cluster=False)
        assert res.results and res.results[0].url == self.LINKEE
