"""Cluster transport plane tests — the pooled/hedged/batched courier.

Pins the four tentpole behaviors of :mod:`..parallel.transport`:
keep-alive connection reuse with transparent reconnect (UdpServer's
persistent endpoints), hedged twin reads that beat a wedged primary
well under the request timeout (Multicast.cpp:520 reroute, Dean &
Barroso hedging), batched ``/rpc/search`` scatter-gather with per-query
result order, and the negotiated binary wire codec with a clean JSON
fallback for mixed-version clusters. Plus: the Msg1 ordered-redelivery
guarantee survives the pooled client.
"""

import json
import threading
import time
import urllib.request

import numpy as np

from open_source_search_engine_tpu.parallel import cluster as cl
from open_source_search_engine_tpu.parallel import transport as tr
from open_source_search_engine_tpu.utils.stats import g_stats
from tests.polling import wait_until


def _doc(i, words="cluster shared words"):
    return (f"<html><head><title>Doc {i}</title></head><body>"
            f"<p>{words} token{i}.</p></body></html>")


def _node(tmp_path, name, n_docs=3, start=True, port=0):
    node = cl.ShardNodeServer(tmp_path / name, port=port)
    for i in range(n_docs):
        node.handle("/rpc/index", {"url": f"http://t.test/{name}{i}",
                                   "content": _doc(i)})
    if start:
        node.start()
    return node


def _free_port():
    import socket
    sk = socket.socket()
    sk.bind(("127.0.0.1", 0))
    port = sk.getsockname()[1]
    sk.close()
    return port


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

class TestCodec:
    PAYLOAD = {
        "ok": True,
        "keys": np.arange(1000, dtype=np.uint64),
        "nested": {"scores": np.linspace(0.0, 1.0, 7),
                   "names": ["a", "b"], "n": 3},
        "structured": np.zeros(4, dtype=np.dtype([("k", "<u8"),
                                                  ("v", "<u4")])),
    }

    def test_binary_roundtrip(self):
        out = tr.decode_bin(tr.encode_bin(self.PAYLOAD))
        assert out["ok"] is True and out["nested"]["n"] == 3
        assert out["nested"]["names"] == ["a", "b"]
        np.testing.assert_array_equal(out["keys"], self.PAYLOAD["keys"])
        assert out["keys"].dtype == np.uint64
        np.testing.assert_array_equal(out["nested"]["scores"],
                                      self.PAYLOAD["nested"]["scores"])
        # structured dtypes survive the JSON-header descr roundtrip
        assert out["structured"].dtype == self.PAYLOAD["structured"].dtype

    def test_json_fallback_roundtrip(self):
        # the fallback wire is pure JSON (old peers json.loads it) and
        # as_array recovers the arrays from the base64 .npy strings
        wire = json.loads(json.dumps(tr.to_wire_json(self.PAYLOAD)))
        assert isinstance(wire["keys"], str)
        np.testing.assert_array_equal(tr.as_array(wire["keys"]),
                                      self.PAYLOAD["keys"])
        np.testing.assert_array_equal(
            tr.as_array(wire["nested"]["scores"]),
            self.PAYLOAD["nested"]["scores"])

    def test_body_codec_dispatch(self):
        for accept_bin in (True, False):
            data, ctype = tr.encode_body(self.PAYLOAD, accept_bin)
            out = tr.decode_body(data, ctype)
            np.testing.assert_array_equal(tr.as_array(out["keys"]),
                                          self.PAYLOAD["keys"])

    def test_binary_wire_at_least_quarter_smaller(self):
        # the acceptance floor: raw length-prefixed frames vs
        # base64-.npy-inside-JSON on a bulk pull payload
        payload = {"ok": True, "batch": {
            "keys": np.arange(200_000, dtype=np.uint64)}}
        bin_bytes, _ = tr.encode_body(payload, True)
        json_bytes, _ = tr.encode_body(payload, False)
        assert len(bin_bytes) <= 0.75 * len(json_bytes)


# ---------------------------------------------------------------------------
# connection pool
# ---------------------------------------------------------------------------

def test_connection_reuse_and_transparent_reconnect(tmp_path):
    g_stats.reset()
    node = _node(tmp_path, "a", n_docs=0)
    t = tr.Transport()
    addr = f"127.0.0.1:{node.port}"
    try:
        for _ in range(12):
            out = t.request(addr, "/rpc/ping", {}, timeout=5.0)
        # 12 sequential RPCs rode ONE accepted TCP connection
        assert out["accepts"] == 1
        snap = g_stats.snapshot()["counters"]
        assert snap["transport.conn_dial"] == 1
        assert snap["transport.conn_reuse"] == 11

        # peer restarts: the pooled socket is now dead — the next
        # request retries once on a fresh dial, the caller never sees it
        port = node.port
        node.stop()
        node2 = _node(tmp_path, "a2", n_docs=0, port=port)
        try:
            out = t.request(addr, "/rpc/ping", {}, timeout=5.0)
            assert out["ok"] and out["accepts"] == 1
            assert g_stats.snapshot()["counters"][
                "transport.conn_retry"] >= 1
        finally:
            node2.stop()
    finally:
        t.close()
        node.stop()


def test_binary_and_json_pull_all_decode_identically(tmp_path):
    """Mixed-version matrix: a binary-advertising client gets raw
    ndarray frames, a JSON-only (old) client gets the base64 wire —
    and both decode to the same RecordBatch."""
    node = _node(tmp_path, "pull", n_docs=3)
    addr = f"127.0.0.1:{node.port}"
    t_bin, t_json = tr.Transport(binary=True), tr.Transport(binary=False)
    try:
        out_b = t_bin.request(addr, "/rpc/pull-all", {}, timeout=30.0)
        out_j = t_json.request(addr, "/rpc/pull-all", {}, timeout=30.0)
        assert isinstance(out_b["rdbs"]["posdb"]["keys"], np.ndarray)
        assert isinstance(out_j["rdbs"]["posdb"]["keys"], str)
        for name in out_b["rdbs"]:
            bb = cl._decode_batch(out_b["rdbs"][name])
            bj = cl._decode_batch(out_j["rdbs"][name])
            np.testing.assert_array_equal(bb.keys, bj.keys)
            if bb.data is not None:
                np.testing.assert_array_equal(bb.data, bj.data)
    finally:
        t_bin.close()
        t_json.close()
        node.stop()


# ---------------------------------------------------------------------------
# batched scatter-gather
# ---------------------------------------------------------------------------

def test_batched_rpc_search_returns_per_query_results_in_order(tmp_path):
    node = cl.ShardNodeServer(tmp_path / "b")
    node.handle("/rpc/index", {"url": "http://t.test/apple",
                               "content": _doc(0, "apple orchard")})
    node.handle("/rpc/index", {"url": "http://t.test/pie",
                               "content": _doc(1, "pie crust")})
    node.start()
    t = tr.Transport()
    try:
        out = t.request(f"127.0.0.1:{node.port}", "/rpc/search",
                        {"queries": ["apple", "zebra", "pie"],
                         "topk": 5, "lang": 0}, timeout=30.0)
        assert out["ok"]
        totals = [r["total"] for r in out["results"]]
        assert totals == [1, 0, 1]
        # binary reply: docids come back as real ndarrays
        assert isinstance(out["results"][0]["docids"], np.ndarray)
    finally:
        t.close()
        node.stop()


def test_search_batch_coalesces_and_keeps_input_order(tmp_path):
    g_stats.reset()
    node = cl.ShardNodeServer(tmp_path / "sb")
    node.handle("/rpc/index", {"url": "http://t.test/apple",
                               "content": _doc(0, "apple orchard")})
    node.handle("/rpc/index", {"url": "http://t.test/pie",
                               "content": _doc(1, "pie crust")})
    node.start()
    conf = cl.HostsConf.parse(f"num-mirrors: 0\n127.0.0.1:{node.port}")
    client = cl.ClusterClient(conf, use_heartbeat=False)
    try:
        res = client.search_batch(["apple", "zebra", "pie"], topk=5,
                                  with_snippets=False,
                                  site_cluster=False)
        assert [r.total_matches for r in res] == [1, 0, 1]
        assert res[0].query == "apple" and res[2].query == "pie"
        # the legs coalesced into batched node dispatches
        assert g_stats.snapshot()["counters"][
            "transport.node_batched_q"] >= 3
    finally:
        client.close()
        node.stop()


# ---------------------------------------------------------------------------
# hedged twin reads
# ---------------------------------------------------------------------------

def test_hedged_read_beats_wedged_twin(tmp_path):
    """The primary twin sits on a search; the hedge fires after the
    (floored) hedge delay, the other twin answers, and the caller gets
    a full non-degraded result in a small fraction of the request
    timeout. The wedged twin stays ALIVE (slow is not dead) but loses
    its primary slot in the twin ordering."""
    docs = {f"http://t.test/h{i}": _doc(i) for i in range(3)}
    a = cl.ShardNodeServer(tmp_path / "wedged")
    b = cl.ShardNodeServer(tmp_path / "healthy")
    for url, html in docs.items():
        a.handle("/rpc/index", {"url": url, "content": html})
        b.handle("/rpc/index", {"url": url, "content": html})
    a.start()
    b.start()
    conf = cl.HostsConf.parse(
        f"num-mirrors: 1\n127.0.0.1:{a.port}\n127.0.0.1:{b.port}")
    client = cl.ClusterClient(conf, use_heartbeat=False)

    wedge = threading.Event()
    real_handle = a.handle

    def wedged_handle(path, payload):
        if path == "/rpc/search":
            wedge.wait(10.0)
        return real_handle(path, payload)

    a.handle = wedged_handle
    # seed the twin ordering so the WEDGED node is the primary pick
    client.hostmap.rtt_s[0, 0] = 0.001
    client.hostmap.rtt_s[0, 1] = 0.002
    g_stats.reset()
    try:
        t0 = time.monotonic()
        res = client.search("cluster shared", topk=5,
                            with_snippets=False, site_cluster=False)
        elapsed = time.monotonic() - t0
        assert not res.degraded
        assert res.total_matches == len(docs)
        assert elapsed < 0.25 * cl.SEARCH_TIMEOUT_S
        snap = g_stats.snapshot()["counters"]
        assert snap["transport.hedge_fired"] >= 1
        assert snap["transport.hedge_won"] >= 1
        # slow-not-dead: still alive, but demoted from primary
        assert bool(client.hostmap.alive[0, 0])
        assert client.hostmap.twin_order(0)[0] == 1

        # the whole story is visible on /admin/transport
        from open_source_search_engine_tpu.serve.server import \
            SearchHTTPServer
        srv = SearchHTTPServer(str(tmp_path / "web"), port=0,
                               cluster=client)
        srv.start()
        try:
            body = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/admin/transport",
                timeout=10.0))
            assert body["counters"]["transport.hedge_fired"] >= 1
            assert body["hostmap"]["shard0"]["twin_order"] == [1, 0]
            assert any(addr.endswith(str(b.port))
                       for addr in body["peers"])
        finally:
            srv.stop()
    finally:
        wedge.set()
        client.close()
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# ordered redelivery under the pooled client
# ---------------------------------------------------------------------------

def test_hostqueue_ordered_redelivery_with_pooled_client(tmp_path):
    """Msg1 semantics survive the transport rebuild: writes to a dead
    twin park in order, redeliver in order when it returns, and the
    NEWEST version of a rewritten URL wins on the caught-up twin."""
    a = _node(tmp_path, "live", n_docs=0)
    port_b = _free_port()
    conf = cl.HostsConf.parse(
        f"num-mirrors: 1\n127.0.0.1:{a.port}\n127.0.0.1:{port_b}")
    client = cl.ClusterClient(conf, use_heartbeat=False)
    t = tr.Transport()
    try:
        # twin b is down: v1 then v2 of the same URL park in its queue
        client.index_document("http://t.test/versioned",
                              _doc(0, "first edition"))
        client.index_document("http://t.test/versioned",
                              _doc(0, "second edition"))
        assert client.pending_writes >= 1
        b = cl.ShardNodeServer(tmp_path / "back", port=port_b)
        b.start()
        try:
            wait_until(lambda: client.pending_writes == 0,
                       timeout=30.0, interval=0.1,
                       desc="parked writes drained into reborn twin")
            # ordered drain: the twin's final state is v2, not v1
            out = t.request(f"127.0.0.1:{port_b}", "/rpc/search",
                            {"q": "second edition", "topk": 5},
                            timeout=30.0)
            assert out["total"] == 1
            out = t.request(f"127.0.0.1:{port_b}", "/rpc/search",
                            {"q": "first edition", "topk": 5},
                            timeout=30.0)
            assert out["total"] == 0
        finally:
            b.stop()
    finally:
        t.close()
        client.close()
        a.stop()
