"""Crawler tests against a local HTTP site — the reference's deterministic
"test collection" strategy (``Test.cpp``: spider a fixed url list, then
verify the resulting databases; SURVEY §4.2), with robots.txt and
politeness checks folded in (``qaspider`` pattern, ``qa.cpp:2318``).
"""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.spider import (
    Fetcher, Linkdb, SpiderLoop, SpiderScheduler, UrlFilterRule, site_rank)

# a tiny site: home → a, b; a → b, secret; b → a (cycle); secret disallowed
PAGES = {
    "/robots.txt": ("text/plain",
                    "User-agent: *\nDisallow: /secret\n"),
    "/": ("text/html",
          "<html><head><title>Home</title></head><body>"
          "<p>Welcome to the homepage of testsite.</p>"
          '<a href="/a">page a</a> <a href="/b">page b</a></body></html>'),
    "/a": ("text/html",
           "<html><head><title>Alpha</title></head><body>"
           "<p>Alpha page discusses aardvarks.</p>"
           '<a href="/b">to b</a> <a href="/secret">hidden</a>'
           "</body></html>"),
    "/b": ("text/html",
           "<html><head><title>Beta</title></head><body>"
           "<p>Beta page discusses badgers.</p>"
           '<a href="/a">back to a</a></body></html>'),
    "/secret": ("text/html",
                "<html><body><p>classified zebra data</p></body></html>"),
}


class _SiteHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        hit = PAGES.get(self.path)
        if hit is None:
            self.send_error(404)
            return
        ctype, body = hit
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture(scope="module")
def site():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _SiteHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


class TestScheduler:
    def test_dedup_and_hops(self):
        s = SpiderScheduler(max_hops=1)
        assert s.add_url("http://x.test/")
        assert not s.add_url("http://x.test/")          # seen
        assert s.add_url("http://x.test/p", hopcount=1)
        assert not s.add_url("http://x.test/q", hopcount=2)  # too deep

    def test_priority_order(self):
        s = SpiderScheduler(filters=[
            UrlFilterRule("important", priority=5),
            UrlFilterRule("*", priority=0)])
        s.add_url("http://a.test/x")
        s.add_url("http://b.test/important")
        batch = s.next_batch(2)
        assert batch[0].url.endswith("important")

    def test_filter_block(self):
        s = SpiderScheduler(filters=[
            UrlFilterRule("spam", allow=False),
            UrlFilterRule("*")])
        assert not s.add_url("http://spam.test/page")
        assert s.add_url("http://ok.test/page")

    def test_politeness_same_host_spacing(self):
        s = SpiderScheduler(filters=[UrlFilterRule("*", delay_s=60.0)])
        s.add_url("http://slow.test/1")
        s.add_url("http://slow.test/2")
        now = time.monotonic()
        b = s.next_batch(2, now=now)
        assert len(b) == 1                    # same IP: one in flight
        assert len(s.next_batch(2, now=now)) == 0
        # in-flight: even far in the future the IP stays locked until
        # the fetch completes (the doledb-lock role)
        assert len(s.next_batch(2, now=now + 61)) == 0
        s.release(b[0].url, now=now)          # fetch done -> window runs
        assert len(s.next_batch(2, now=now + 1)) == 0   # still waiting
        assert len(s.next_batch(2, now=now + 61)) == 1  # window passed

    def test_per_ip_discipline_across_hosts(self):
        """Two HOSTS resolving to one IP share a politeness window and
        are never in flight together (Spider.h firstIP semantics)."""
        ips = {"a.shared.test": "10.0.0.7", "b.shared.test": "10.0.0.7",
               "other.test": "10.0.0.9"}
        s = SpiderScheduler(filters=[UrlFilterRule("*", delay_s=30.0)],
                            resolver=lambda h: ips.get(h, "10.9.9.9"))
        s.add_url("http://a.shared.test/x")
        s.add_url("http://b.shared.test/y")
        s.add_url("http://other.test/z")
        now = time.monotonic()
        b = s.next_batch(3, now=now)
        # one url per IP per batch: the shared IP contributes ONE url
        assert len(b) == 2
        assert {r.first_ip for r in b} == {"10.0.0.7", "10.0.0.9"}
        assert len(s.next_batch(3, now=now + 999)) == 0  # in flight
        for r in b:
            s.release(r.url, now=now)
        # shared IP's second host only after the window
        assert len(s.next_batch(3, now=now + 1)) == 0
        assert len(s.next_batch(3, now=now + 31)) == 1


class TestSiteRank:
    def test_step_table(self):
        assert site_rank(0) == 0
        assert site_rank(1) == 1
        assert site_rank(7) == 6
        assert site_rank(100) == 10
        assert site_rank(10**6) == 15


class TestCrawl:
    @pytest.fixture(scope="class")
    def crawled(self, tmp_path_factory, site):
        coll = Collection("crawl", tmp_path_factory.mktemp("crawl"))
        loop = SpiderLoop(
            coll,
            scheduler=SpiderScheduler(
                filters=[UrlFilterRule("*", delay_s=0.0)], max_hops=3),
            fetcher=Fetcher(n_threads=4, timeout=5.0))
        loop.add_url(site + "/")
        stats = loop.crawl(max_pages=20)
        return coll, loop, stats, site

    def test_crawl_reaches_linked_pages(self, crawled):
        coll, loop, stats, site = crawled
        assert stats.indexed == 3  # home, a, b — not /secret, not robots
        assert stats.robots_blocked >= 1

    def test_crawled_content_searchable(self, crawled):
        coll, _, _, site = crawled
        res = engine.search(coll, "aardvarks")
        assert len(res.results) == 1
        assert res.results[0].url.endswith("/a")
        res = engine.search(coll, "badgers")
        assert res.results[0].title == "Beta"

    def test_robots_page_not_indexed(self, crawled):
        coll, _, _, site = crawled
        assert not engine.search(coll, "zebra").results

    def test_cycle_fetched_once(self, crawled):
        _, loop, stats, _ = crawled
        # a↔b cycle must not refetch: 4 fetch attempts total
        # (/, /a, /b, /secret-blocked)
        assert stats.fetched == 4

    def test_linkdb_counts_external_only(self, tmp_path):
        ldb = Linkdb(tmp_path)
        ldb.add_link("target.com", "linker1.com", "http://linker1.com/x")
        ldb.add_link("target.com", "linker1.com", "http://linker1.com/y")
        ldb.add_link("target.com", "linker2.com", "http://linker2.com/")
        ldb.add_link("target.com", "target.com", "http://target.com/self")
        assert ldb.site_num_inlinks("target.com") == 2  # distinct sites
        assert ldb.site_num_inlinks("other.com") == 0
