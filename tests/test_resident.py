"""Resident serving loop — ordering, freshness, and one-shot parity.

The loop's contract (query/resident.py): submit() is a pure enqueue;
results come back for exactly the plans submitted, in submit order; a
write landing while waves are in flight drains those waves against
their issue-time base and every LATER submit is issued against a
refreshed index (Ticket.generation proves which base scored it).
"""

import threading

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import (
    _compile_cached, get_device_index, get_resident_loop,
    search_device_batch)
from open_source_search_engine_tpu.query.resident import ResidentLoop

DOCS = {
    "http://a.example.com/fruit": """
      <html><head><title>Fruit basics</title></head><body>
      <p>The apple is sweet. A banana is tropical. Apple pie wins.</p>
      </body></html>""",
    "http://b.example.com/apple": """
      <html><head><title>Apple orchard</title></head><body>
      <p>Our orchard grows apple trees. Apple harvest is in fall.</p>
      </body></html>""",
    "http://c.example.org/banana": """
      <html><head><title>Banana farm</title></head><body>
      <p>Banana plantations export banana bunches worldwide.</p>
      </body></html>""",
    "http://d.example.org/cellar": """
      <html><head><title>Vegetables</title></head><body>
      <p>Carrots and beets. Root cellar storage tips.</p></body></html>""",
}

QUERIES = ["apple", "banana", "apple banana", "fruit", "cellar",
           "orchard apple", "zeppelin"]


@pytest.fixture()
def coll(tmp_path):
    c = Collection("res", tmp_path)
    c.conf.pqr_enabled = False
    for u, h in DOCS.items():
        docproc.index_document(c, u, h)
    return c


def _key(r):
    return (-round(r.score, 3), r.docid)


class TestParity:
    def test_resident_matches_one_shot_batch(self, coll):
        """CPU parity: the loop's issue/collect split must reproduce
        one-shot search_device_batch exactly (same plans, same index
        snapshot → same docids and scores)."""
        one_shot = search_device_batch(coll, QUERIES, topk=10,
                                       site_cluster=False)
        res = search_device_batch(coll, QUERIES, topk=10,
                                  site_cluster=False, resident=True)
        for q, a, b in zip(QUERIES, one_shot, res):
            assert b.total_matches == a.total_matches, q
            assert sorted(map(_key, b.results)) == \
                   sorted(map(_key, a.results)), q

    def test_raw_ticket_matches_search_batch(self, coll):
        di = get_device_index(coll)
        plans = [_compile_cached(q, 0) for q in QUERIES]
        ref = di.search_batch(plans, topk=64, lang=0)
        loop = get_resident_loop(coll)
        got = loop.submit(plans, topk=64, lang=0).wait()
        assert len(got) == len(ref)
        for q, (rd, rs, rn), (gd, gs, gn) in zip(QUERIES, ref, got):
            assert gn == rn, q
            assert list(gs) == list(rs), q


class TestOrdering:
    def test_concurrent_submits_get_their_own_results(self, coll):
        """16 threads × 4 rounds enqueue distinct queries concurrently;
        every ticket must resolve to ITS query's results (no swaps, no
        cross-wave mixups), matching a one-shot reference."""
        di = get_device_index(coll)
        ref = {}
        for q in QUERIES:
            plan = _compile_cached(q, 0)
            ((d, s, n),) = di.search_batch([plan], topk=64, lang=0)
            ref[q] = (sorted(d.tolist()), n)
        loop = get_resident_loop(coll)
        errors = []
        start = threading.Barrier(16)

        def worker(i):
            try:
                start.wait(timeout=30)
                for r in range(4):
                    q = QUERIES[(i + r) % len(QUERIES)]
                    t = loop.submit([_compile_cached(q, 0)],
                                    topk=64, lang=0)
                    ((d, s, n),) = t.wait(timeout=60)
                    assert (sorted(d.tolist()), n) == ref[q], q
            except BaseException as exc:  # noqa: BLE001
                errors.append((i, exc))

        ts = [threading.Thread(target=worker, args=(i,), daemon=True)
              for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errors, errors
        assert loop.waves_issued >= 1

    def test_one_submit_many_plans_keeps_plan_order(self, coll):
        loop = get_resident_loop(coll)
        plans = [_compile_cached(q, 0) for q in QUERIES]
        got = loop.submit(plans, topk=64, lang=0).wait()
        di = get_device_index(coll)
        ref = di.search_batch(plans, topk=64, lang=0)
        for (rd, rs, rn), (gd, gs, gn) in zip(ref, got):
            assert gn == rn and list(gs) == list(rs)


class TestFreshness:
    def test_write_bumps_generation_and_serves_fresh(self, coll):
        """A submit after a write must be issued against a refreshed
        base: the new doc is visible and Ticket.generation moved past
        the pre-write generation — the loop never reuses the pre-write
        packed base for post-write tickets."""
        loop = get_resident_loop(coll)
        t0 = loop.submit([_compile_cached("apple", 0)], topk=64, lang=0)
        t0.wait(timeout=60)
        gen0 = t0.generation
        assert gen0 == t0.di._built_version

        docproc.index_document(
            coll, "http://e.example.com/durian",
            "<html><title>Durian</title><body>"
            "<p>The durian fruit is pungent.</p></body></html>")
        assert coll.posdb.version != gen0  # the write moved the Rdb

        t1 = loop.submit([_compile_cached("durian", 0)], topk=64,
                         lang=0)
        ((docids, scores, n),) = t1.wait(timeout=60)
        assert n >= 1 and len(docids) >= 1  # fresh doc is searchable
        assert t1.generation != gen0
        assert t1.generation == t1.di._built_version

    def test_midflight_write_drains_before_refresh(self, coll):
        """Drive the loop's freshness branch directly: with a wave in
        flight, a generation move forces a drain of the old-base waves
        before any new issue — the in-flight ticket keeps its issue
        generation, the post-write ticket gets the new one."""
        di = get_device_index(coll)
        gens = [di._built_version]

        def di_fn():
            return get_device_index(coll)

        def gen_fn():
            return coll.posdb.version

        loop = ResidentLoop(di_fn, gen_fn, name="midflight")
        try:
            plan = _compile_cached("banana", 0)
            first = loop.submit([plan], topk=64, lang=0)
            first.wait(timeout=60)
            docproc.index_document(
                coll, "http://f.example.com/mango",
                "<html><title>Mango</title><body>"
                "<p>Mango season, mango juice.</p></body></html>")
            # burst of submits racing the version bump: every ticket
            # must still score consistently with ITS recorded base
            tickets = [loop.submit([_compile_cached("mango", 0)],
                                   topk=64, lang=0) for _ in range(6)]
            for t in tickets:
                t.wait(timeout=60)
            # the last ticket was certainly issued post-write (the
            # submits happened after index_document returned)
            last = tickets[-1]
            assert last.generation == coll.posdb.version
            ((d, s, n),) = last.wait()
            assert n >= 1
            assert gens[0] != last.generation
        finally:
            loop.stop()


class TestLifecycle:
    def test_stop_fails_fast_and_loop_respawns(self, coll):
        loop = get_resident_loop(coll)
        loop.submit([_compile_cached("apple", 0)], topk=64,
                    lang=0).wait(timeout=60)
        loop.stop()
        t = loop.submit([_compile_cached("apple", 0)], topk=64, lang=0)
        with pytest.raises(RuntimeError):
            t.wait(timeout=10)
        # engine hands out a fresh loop once the old one is dead
        loop2 = get_resident_loop(coll)
        assert loop2 is not loop and loop2.alive
        ((d, s, n),) = loop2.submit(
            [_compile_cached("apple", 0)], topk=64, lang=0
        ).wait(timeout=60)
        assert n >= 1
