"""HTTP API tests — the qainject pattern over the real HTTP boundary
(reference ``qa.cpp:659`` injects + queries through the live server)."""

import json
import urllib.request

import pytest

from open_source_search_engine_tpu.serve import serve

DOC = ("<html><head><title>Solar panels guide</title></head><body>"
       "<p>Solar panels convert sunlight into electricity. Panel "
       "efficiency varies by cell type.</p></body></html>")


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    s = serve(tmp_path_factory.mktemp("serve"), port=0)
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}") as r:
        return r.status, r.read().decode(), r.headers.get_content_type()


def _post(server, path, body: bytes):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}", data=body)
    with urllib.request.urlopen(req) as r:
        return r.status, r.read().decode()


class TestHttpApi:
    def test_root_form(self, server):
        status, body, ctype = _get(server, "/")
        assert status == 200 and "form" in body

    def test_inject_then_search_json(self, server):
        status, body = _post(
            server, "/inject?u=http://solar.example.com/guide",
            DOC.encode())
        assert status == 200
        assert json.loads(body)["numKeys"] > 0

        status, body, ctype = _get(server, "/search?q=sunlight")
        assert status == 200 and ctype == "application/json"
        res = json.loads(body)
        assert res["totalMatches"] == 1
        assert res["results"][0]["url"] == "http://solar.example.com/guide"
        assert res["results"][0]["title"] == "Solar panels guide"

    def test_search_formats(self, server):
        for fmt, ctype, marker in (
                ("xml", "text/xml", "<response>"),
                ("csv", "text/csv", "docid,score,url,title"),
                ("html", "text/html", "<ol>")):
            status, body, ct = _get(server,
                                    f"/search?q=solar&format={fmt}")
            assert status == 200 and ct == ctype and marker in body, fmt

    def test_cached_page_with_highlight(self, server):
        _, body, _ = _get(server, "/search?q=sunlight")
        docid = json.loads(body)["results"][0]["docId"]
        status, page, _ = _get(server, f"/get?d={docid}&q=sunlight")
        assert status == 200
        assert 'background:yellow">sunlight</span>' in page

    def test_missing_query_is_400(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server, "/search")
        assert e.value.code == 400

    def test_unknown_page_is_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server, "/nope")
        assert e.value.code == 404

    def test_admin_stats_and_hosts(self, server):
        status, body, _ = _get(server, "/admin/stats")
        stats = json.loads(body)
        assert status == 200 and stats["queries"] >= 1
        status, body, _ = _get(server, "/admin/hosts")
        assert json.loads(body)["shards"] == 1

    def test_addurl_without_spider_is_503(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server, "/addurl?u=http://x.example.com/")
        assert e.value.code == 503
