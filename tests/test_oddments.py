"""Coverage odds-and-ends: Msg17 result cache, general TtlCache,
Users table auth, Catdb directory, dead-host alerting."""

import json
import os
import time
import urllib.parse
import urllib.request

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.catdb import Catdb
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.serve.server import SearchHTTPServer
from open_source_search_engine_tpu.utils.ttlcache import TtlCache
from open_source_search_engine_tpu.utils.users import Users


class TestTtlCache:
    def test_ttl_and_eviction(self):
        c = TtlCache(ttl_s=0.05, max_entries=4)
        c.put("a", 1)
        assert c.get("a") == 1
        time.sleep(0.06)
        assert c.get("a") is None
        for i in range(5):
            c.put(i, i)
        assert c.stats()["entries"] <= 4

    def test_version_invalidation(self):
        c = TtlCache(ttl_s=60)
        c.put("k", "v")
        c.bump_version()
        assert c.get("k") is None

    def test_put_sheds_dead_entries_before_live(self):
        c = TtlCache(ttl_s=60, max_entries=4)
        for k in ("a", "b", "c"):
            c.put(k, k)
        c.bump_version()  # all three are now dead-generation
        c.put("d", "d")
        c.put("e", "e")   # at cap: the dead entries go, not the live
        assert c.get("d") == "d" and c.get("e") == "e"
        st = c.stats()
        assert st["entries"] == 2
        assert st["live"] == 2


class TestResultCache:
    def test_search_page_cached_and_invalidated(self, tmp_path):
        srv = SearchHTTPServer(str(tmp_path), port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            html = (b"<html><title>Cache</title><body>"
                    b"<p>memoized llama content</p></body></html>")
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/inject?url=http://c.test/1", data=html),
                timeout=60)
            urllib.request.urlopen(f"{base}/search?q=llama&format=json",
                                   timeout=60)
            h0 = srv.stats.get("result_cache_hits", 0)
            urllib.request.urlopen(f"{base}/search?q=llama&format=json",
                                   timeout=60)
            assert srv.stats.get("result_cache_hits", 0) == h0 + 1
            # an index mutation invalidates (version in the key)
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/inject?url=http://c.test/2", data=html),
                timeout=60)
            out = json.load(urllib.request.urlopen(
                f"{base}/search?q=llama&format=json", timeout=60))
            assert out["totalMatches"] == 2  # fresh, not the cached 1
        finally:
            srv.stop()


class TestUsers:
    def test_roles_and_auth(self, tmp_path):
        u = Users(tmp_path)
        u.add("alice", "s3cret", role="admin")
        u.add("bob", "hunter2", role="query")
        assert u.check("alice", "s3cret", min_role="admin")
        assert not u.check("alice", "wrong", min_role="admin")
        assert not u.check("bob", "hunter2", min_role="admin")
        assert u.check("bob", "hunter2", min_role="query")
        assert not u.check("mallory", "x", min_role="query")
        # persisted + reloadable, no cleartext on disk
        raw = (tmp_path / "users.txt").read_text()
        assert "s3cret" not in raw and "hunter2" not in raw
        u2 = Users(tmp_path)
        assert u2.check("alice", "s3cret", min_role="admin")

    def test_server_accepts_user_credentials(self, tmp_path):
        srv = SearchHTTPServer(str(tmp_path), port=0)
        srv.conf.master_password = "masterpw"
        srv.users.add("op", "oppw", role="admin")
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/admin/stats",
                                       timeout=30)
            with urllib.request.urlopen(
                    f"{base}/admin/stats?user=op&upwd=oppw",
                    timeout=30) as r:
                assert r.status == 200
            with urllib.request.urlopen(
                    f"{base}/admin/stats?pwd=masterpw",
                    timeout=30) as r:
                assert r.status == 200  # master password still works
        finally:
            srv.stop()


class TestCatdb:
    TREE = ("1\t0\tScience\n"
            "2\t1\tScience/Physics\n"
            "3\t0\tArts\n")

    def test_tree_and_assignment(self, tmp_path):
        c = Catdb(tmp_path)
        assert c.load_tree(self.TREE) == 3
        c.assign("phys.test", 2)
        assert c.categories_of("phys.test") == [2]
        assert c.ancestors(2) == [2, 1]
        assert c.catid_of_path("science/physics") == 2
        # upward inheritance rides the *_top fields
        f = c.doc_fields("phys.test")
        assert f["catid"] == 2.0 and f["catid_top"] == 1.0
        assert f["category"] == "Science/Physics"
        assert f["category_top"] == "Science"
        c.unassign("phys.test", 2)
        assert c.categories_of("phys.test") == []

    def test_directory_restricted_search(self, tmp_path):
        coll = Collection("c", str(tmp_path))
        coll.catdb.load_tree(self.TREE)
        coll.catdb.assign("phys.test", 2)
        docproc.index_document(
            coll, "http://phys.test/a",
            "<html><body><p>quantum electrodynamics paper about "
            "muons</p></body></html>")
        docproc.index_document(
            coll, "http://other.test/b",
            "<html><body><p>muons appear in this unfiled page "
            "too</p></body></html>")
        res = engine.search(coll, "muons", topk=5)
        assert res.total_matches == 2
        # directory-restricted: only the filed site's doc
        res = engine.search(coll, "muons gbmin:catid:2 gbmax:catid:2",
                            topk=5)
        assert res.total_matches == 1
        assert "phys.test" in res.results[0].url
        # top-level restriction catches the whole subtree
        res = engine.search(
            coll, "muons gbmin:catid_top:1 gbmax:catid_top:1", topk=5)
        assert res.total_matches == 1


class TestAlerting:
    def test_transition_fires_alert_cmd(self, tmp_path, monkeypatch):
        from open_source_search_engine_tpu.parallel import \
            cluster as cluster_mod
        conf = cluster_mod.HostsConf(
            n_shards=1, n_replicas=1, addresses=[["127.0.0.1:1"]])
        cc = cluster_mod.ClusterClient(conf, use_heartbeat=False)
        marker = tmp_path / "alert.txt"
        # the alert_cmd PARM path (env cleared) must work too
        monkeypatch.delenv("OSSE_ALERT_CMD", raising=False)
        import types
        cc.parms = types.SimpleNamespace(
            alert_cmd=f'echo "$OSSE_ALERT_EVENT $OSSE_ALERT_HOST" '
                      f'>> {marker}')
        monkeypatch.setattr(cc, "_ping", lambda s, r: False)
        cc.check_hosts()          # alive → dead fires
        cc.check_hosts()          # still dead: no second alert
        monkeypatch.setattr(cc, "_ping", lambda s, r: True)
        cc.check_hosts()          # dead → recovered fires
        for _ in range(50):
            if marker.exists() and \
                    len(marker.read_text().splitlines()) >= 2:
                break
            time.sleep(0.1)
        lines = marker.read_text().splitlines()
        assert len(lines) == 2
        # the two alert_cmd subprocesses are fire-and-forget (Popen,
        # no wait) — their appends may land in either order
        assert sorted(ln.split()[0] for ln in lines) == \
            ["dead", "recovered"]
