"""Serving hardening: TLS plane, RPC niceness, slow-read liveness.

Reference parity: the reference links -lssl and serves https off
gb.pem (Makefile:113, TcpServer.cpp), tags every UDP slot with a
niceness bit so spider traffic yields to queries (UdpProtocol.h), and
separates request timeout from host death (PingServer owns liveness;
Multicast only reroutes).
"""

import json
import ssl
import subprocess
import time
import urllib.request

import numpy as np
import pytest

from open_source_search_engine_tpu.parallel import cluster as cluster_mod
from open_source_search_engine_tpu.serve.server import SearchHTTPServer


class TestTLS:
    def test_https_search(self, tmp_path):
        pem = tmp_path / "gb.pem"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048",
             "-keyout", str(pem), "-out", str(pem), "-days", "2",
             "-nodes", "-subj", "/CN=localhost"],
            check=True, capture_output=True)
        srv = SearchHTTPServer(str(tmp_path / "d"), port=0)
        srv.conf.ssl_cert = str(pem)
        srv.start()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                    f"https://127.0.0.1:{srv.port}/search?q=x&format=json",
                    context=ctx, timeout=30) as r:
                assert r.status == 200
                assert "results" in json.load(r)
        finally:
            srv.stop()


class TestNiceness:
    def test_nice1_waits_for_interactive(self, tmp_path):
        srv = SearchHTTPServer(str(tmp_path / "d"), port=0)
        srv.nice_gate.max_wait_s = 1.0
        # interactive request in flight → niceness-1 must wait
        srv.nice_gate.enter(0)
        t0 = time.monotonic()
        status, _, _ = srv.handle("GET", "/admin/stats", {}, b"",
                                  niceness=1)
        waited = time.monotonic() - t0
        assert status == 200
        assert waited >= 0.9
        # idle plane → niceness-1 runs without the gate wait (margin
        # generous: the handler itself can be slow under suite load)
        srv.nice_gate.exit(0)
        t0 = time.monotonic()
        srv.handle("GET", "/admin/stats", {}, b"", niceness=1)
        assert time.monotonic() - t0 < 0.5

    def test_header_parsed(self, tmp_path):
        srv = SearchHTTPServer(str(tmp_path / "d"), port=0)
        srv.nice_gate.max_wait_s = 0.5
        srv.start()
        try:
            srv.nice_gate.enter(0)
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/admin/stats",
                headers={"X-Niceness": "1"})
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
            assert time.monotonic() - t0 >= 0.4
        finally:
            srv.nice_gate.exit(0)
            srv.stop()


class TestSlowReadLiveness:
    def test_slow_search_does_not_dead_mark(self, tmp_path, monkeypatch):
        """A read failure with a healthy ping keeps the twin alive
        (penalized), and the twin answers the retry."""
        conf = cluster_mod.HostsConf(
            n_shards=1, n_replicas=2,
            addresses=[["127.0.0.1:1", "127.0.0.1:2"]])
        cc = cluster_mod.ClusterClient(conf, use_heartbeat=False)
        calls = []

        def fake_rpc(addr, path, payload, timeout=1.0, niceness=0):
            calls.append((addr, path))
            if path == "/rpc/ping":
                return {"ok": True}
            if addr.endswith(":1"):
                raise TimeoutError("slow")
            return {"ok": True, "total": 0,
                    "docids": [], "scores": []}

        monkeypatch.setattr(cc.transport, "request", fake_rpc)
        out = cc._read_shard(0, "/rpc/search", {"q": "x"})
        assert out is not None                       # twin answered
        assert bool(cc.hostmap.alive[0, 0])          # NOT dead-marked
        assert cc.hostmap.rtt_s[0, 0] >= 1.0         # but penalized
        assert ("127.0.0.1:1", "/rpc/ping") in calls

    def test_dead_host_still_dead_marks(self, tmp_path, monkeypatch):
        conf = cluster_mod.HostsConf(
            n_shards=1, n_replicas=2,
            addresses=[["127.0.0.1:1", "127.0.0.1:2"]])
        cc = cluster_mod.ClusterClient(conf, use_heartbeat=False)

        def fake_rpc(addr, path, payload, timeout=1.0, niceness=0):
            if addr.endswith(":1"):
                raise ConnectionError("down")
            return {"ok": True, "total": 0,
                    "docids": [], "scores": []}

        monkeypatch.setattr(cc.transport, "request", fake_rpc)
        out = cc._read_shard(0, "/rpc/search", {"q": "x"})
        assert out is not None
        assert not bool(cc.hostmap.alive[0, 0])      # dead-marked
