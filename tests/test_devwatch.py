"""Device telemetry plane (utils/devwatch.py): the HBM ledger across
park/promote and delta-fold lifecycles, the wave flight recorder's
bounded ring and issue→wait→collect split, roofline attribution from
``cost_analysis()`` per (kernel, shape bucket), the OSSE_DEVWATCH=0
true-no-op contract, and the /admin/hbm + /admin/device pages.

Reference: Stats.cpp's performance graph + PageStats/PagePerf in the
ancestor — host-side observability this plane moves to the device
boundary.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import (get_device_index,
                                                        get_resident_loop)
from open_source_search_engine_tpu.serve.server import SearchHTTPServer
from open_source_search_engine_tpu.serve.tenancy import ResidencyManager
from open_source_search_engine_tpu.utils import devwatch
from open_source_search_engine_tpu.utils.membudget import g_membudget
from open_source_search_engine_tpu.utils.stats import g_stats

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DOC = ("<html><head><title>{t}</title></head><body>"
       "<p>walrus {t} herd gathers on the {t} shore. "
       "The walrus colony of {t} dives deep.</p></body></html>")


def _mk_coll(tmp_path, name: str, docs: int = 1) -> Collection:
    c = Collection(name, tmp_path)
    c.conf.pqr_enabled = False
    for i in range(docs):
        docproc.index_document(c, f"http://{name}.test/p{i}",
                               DOC.format(t=f"{name}{i}"))
    return c


@pytest.fixture(autouse=True)
def _devwatch_reset():
    """devwatch is a process-wide singleton; every test starts and
    ends with the plane disarmed and empty."""
    devwatch.disable()
    devwatch.reset()
    g_stats.reset()
    yield
    devwatch.disable()
    devwatch.reset()
    g_membudget.set_label_cap("device", 0)


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------

class TestLedger:
    def test_register_replace_release(self):
        devwatch.enable()
        devwatch.note_columns("ca", "devindex", {"doc": 100, "imp": 50})
        assert devwatch.collection_bytes("ca") == 150
        # re-noting a (coll, plane) REPLACES the slice — a refresh
        # must not leak the previous generation's columns
        devwatch.note_columns("ca", "devindex", {"doc": 200})
        assert devwatch.collection_bytes("ca") == 200
        devwatch.note_buffer("ca", "mesh_stage", "wave1", 30)
        assert devwatch.collection_bytes("ca") == 230
        devwatch.drop_buffer("ca", "mesh_stage", "wave1")
        assert devwatch.collection_bytes("ca") == 200
        devwatch.note_columns("cb", "devindex", {"doc": 10})
        assert devwatch.g_devwatch.total_bytes() == 210
        devwatch.drop("ca")  # every plane dies with the collection
        assert devwatch.collection_bytes("ca") == 0
        assert devwatch.g_devwatch.total_bytes() == 10
        # the plane gauges follow the ledger
        assert g_stats.snapshot()["gauges"]["hbm.devindex.bytes"] == 10
        assert g_stats.snapshot()["gauges"]["hbm.total.bytes"] == 10

    def test_disabled_records_nothing(self):
        devwatch.note_columns("ca", "devindex", {"doc": 100})
        assert devwatch.collection_bytes("ca") == 0
        assert devwatch.wave_begin("test") is None
        snap = devwatch.snapshot()
        assert snap["enabled"] is False
        assert snap["ledger"] == {} and snap["waves"] == []

    def test_reconcile_null_safe_on_cpu(self):
        devwatch.enable()
        devwatch.note_columns("ca", "devindex", {"doc": 100})
        rec = devwatch.reconcile()
        assert rec["ledger_bytes"] == 100
        for d in rec["devices"]:  # CPU: memory_stats() is None
            assert d["bytes_in_use"] is None or d["bytes_in_use"] >= 0
        json.dumps(rec)  # admin/json-serializable

    def test_delta_fold_lifecycle_tracks_resident_bytes(self, tmp_path):
        devwatch.enable()
        coll = _mk_coll(tmp_path, "dfl", docs=2)
        di = get_device_index(coll)
        assert devwatch.collection_bytes("dfl") == di.resident_bytes()
        docproc.index_document(coll, "http://dfl.test/extra",
                               DOC.format(t="extra"))
        # drop the slice by hand: the fold must RE-note it — proof the
        # refresh path re-registers every generation, not just boot
        devwatch.drop("dfl")
        assert di.refresh() is True
        assert devwatch.collection_bytes("dfl") == di.resident_bytes()
        assert devwatch.collection_bytes("dfl") > 0

    def test_park_releases_promote_reregisters(self, tmp_path):
        devwatch.enable()
        rm = ResidencyManager(max_resident=1)
        try:
            ca = _mk_coll(tmp_path, "pka")
            cb = _mk_coll(tmp_path, "pkb")
            rm.loop_for(ca)
            na = devwatch.collection_bytes("pka")
            assert na > 0
            rm.loop_for(cb)  # parks pka (LRU) → ledger drops the slice
            assert devwatch.collection_bytes("pka") == 0
            assert devwatch.collection_bytes("pkb") > 0
            rm.loop_for(ca)  # re-promotion re-registers, bit-identical
            assert devwatch.collection_bytes("pka") == na
        finally:
            rm.stop_all()


# ---------------------------------------------------------------------------
# wave flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        devwatch.enable()
        for _ in range(devwatch.RING + 40):
            devwatch.wave_end(devwatch.wave_begin("test"))
        snap = devwatch.snapshot()
        assert len(snap["waves"]) == devwatch.RING
        assert snap["totals"]["waves"] == devwatch.RING + 40

    def test_resident_waves_record_the_split(self, tmp_path):
        devwatch.enable()
        coll = _mk_coll(tmp_path, "fr", docs=3)
        loop = get_resident_loop(coll)
        plan = engine._compile_cached("walrus", 0)
        for _ in range(3):
            loop.submit([plan], topk=8).wait(timeout=120)
        snap = devwatch.snapshot()
        waves = [w for w in snap["waves"] if w["source"] == "resident"]
        assert waves
        w = waves[-1]
        for k in ("issue_s", "wait_s", "collect_s", "total_s"):
            assert w[k] >= 0.0
        assert w["error"] is None
        assert w["rounds"], "collect must attach at least one round"
        r = w["rounds"][0]
        assert r["device_s"] >= 0.0 and r["bytes_out"] > 0
        assert "escalations" in r

    def test_error_wave_is_recorded(self):
        devwatch.enable()
        obs = devwatch.wave_begin("test", coll="x")
        devwatch.wave_end(obs, error="BoomError")
        snap = devwatch.snapshot()
        assert snap["waves"][-1]["error"] == "BoomError"
        assert snap["totals"]["wave_errors"] == 1


# ---------------------------------------------------------------------------
# roofline attribution
# ---------------------------------------------------------------------------

class TestRoofline:
    def test_first_dispatch_pays_later_ones_count(self):
        devwatch.enable()
        calls = []

        class _Fake:
            def cost_analysis(self):
                return [{"flops": 1000.0, "bytes accessed": 10.0}]

        def thunk():
            calls.append(1)
            return _Fake()

        devwatch.note_cost("kern", (4, 8), thunk, modeled_bytes=9)
        devwatch.note_cost("kern", (4, 8), thunk)
        devwatch.note_cost("kern", (8, 8), thunk)
        assert len(calls) == 2  # one compile per bucket, dict hit after
        roofs = devwatch.snapshot()["rooflines"]
        assert len(roofs) == 2
        ent = next(e for e in roofs if e["bucket"] == [4, 8])
        assert ent["dispatches"] == 2 and ent["modeled_bytes"] == 9
        assert ent["flops"] == 1000.0 and ent["bytes"] == 10.0
        assert ent["verdict"] in ("bandwidth-bound", "compute-bound")

    def test_cost_error_degrades_to_unknown(self):
        devwatch.enable()

        def bad_thunk():
            raise RuntimeError("no cost analysis here")

        devwatch.note_cost("kern", (2,), bad_thunk)
        ent = devwatch.snapshot()["rooflines"][0]
        assert ent["verdict"] == "unknown"
        assert g_stats.snapshot()["counters"]["devwatch.cost_errors"] == 1

    def test_real_query_populates_a_bucket(self, tmp_path):
        devwatch.enable()
        coll = _mk_coll(tmp_path, "rf", docs=3)
        loop = get_resident_loop(coll)
        plan = engine._compile_cached("walrus herd", 0)
        loop.submit([plan], topk=8).wait(timeout=120)
        loop.submit([plan], topk=8).wait(timeout=120)
        roofs = devwatch.snapshot()["rooflines"]
        assert any(e["kernel"].startswith("devindex.") for e in roofs)
        ent = next(e for e in roofs
                   if e["kernel"].startswith("devindex."))
        assert ent["flops"] > 0 and ent["bytes"] > 0
        assert ent["dispatches"] >= 2


# ---------------------------------------------------------------------------
# OSSE_DEVWATCH=0 — true no-op
# ---------------------------------------------------------------------------

class TestNoop:
    @pytest.mark.slow
    def test_subprocess_off_is_true_noop(self):
        code = (
            "import os\n"
            "from open_source_search_engine_tpu.utils import devwatch\n"
            "devwatch.maybe_enable()\n"
            "assert not devwatch.enabled()\n"
            "devwatch.note_columns('c', 'devindex', {'doc': 1})\n"
            "devwatch.note_round(coll='c')\n"
            "devwatch.note_cost('k', (1,), lambda: 1/0)\n"
            "obs = devwatch.wave_begin('t')\n"
            "assert obs is None\n"
            "devwatch.wave_issued(obs); devwatch.wave_collect(obs)\n"
            "devwatch.wave_end(obs)\n"
            "s = devwatch.snapshot()\n"
            "assert s['enabled'] is False and s['ledger'] == {}\n"
            "assert s['waves'] == [] and s['rooflines'] == []\n"
            "print('NOOP-OK')\n")
        env = dict(os.environ)
        env.update({"OSSE_DEVWATCH": "0", "JAX_PLATFORMS": "cpu"})
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           cwd=ROOT, capture_output=True, text=True,
                           timeout=300)
        assert p.returncode == 0, p.stderr
        assert "NOOP-OK" in p.stdout

    def test_disabled_calls_are_cheap(self):
        # the strict 2% gate lives in BENCH_DEVOBS=1; this is the
        # CI-safe sanity bound that the off path stays a few branches
        t0 = time.perf_counter()
        for _ in range(20000):
            devwatch.note_round(coll="c", device_s=0.0)
            devwatch.wave_end(devwatch.wave_begin("t"))
        assert time.perf_counter() - t0 < 1.0


# ---------------------------------------------------------------------------
# admin pages
# ---------------------------------------------------------------------------

def _get(srv, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{srv._httpd.server_port}{path}",
        timeout=30)


class TestAdminPages:
    @pytest.fixture
    def srv(self, tmp_path):
        devwatch.enable()
        s = SearchHTTPServer(tmp_path, port=0)
        coll = s.colldb.get("main")
        coll.conf.pqr_enabled = False
        for i in range(3):
            docproc.index_document(coll, f"http://m.test/p{i}",
                                   DOC.format(t=f"m{i}"))
        s.start()
        yield s
        s.stop()

    def test_hbm_page_and_json(self, srv):
        _get(srv, "/search?q=walrus&format=json").read()
        html = _get(srv, "/admin/hbm").read().decode()
        assert "HBM ledger" in html and "reconciliation" in html
        assert "devindex" in html  # the main collection's slice
        js = json.loads(_get(srv, "/admin/hbm?format=json").read())
        assert js["enabled"] is True
        assert js["total_bytes"] == sum(js["collections"].values())
        assert "reconcile" in js and "planes" in js

    def test_device_page_and_json(self, srv):
        _get(srv, "/search?q=walrus&format=json").read()
        html = _get(srv, "/admin/device").read().decode()
        assert "wave waterfall" in html and "roofline" in html
        js = json.loads(_get(srv, "/admin/device?format=json").read())
        assert js["enabled"] is True
        assert js["totals"]["waves"] >= 1
        assert js["waves"] and js["rooflines"]
        assert "ridge" in js["peaks"] or "label" in js["peaks"]

    def test_perf_page_carries_hbm_row(self, srv):
        js = json.loads(_get(srv, "/admin/perf?format=json").read())
        assert "hbm" in js and js["hbm"]["enabled"] is True
        html = _get(srv, "/admin/perf").read().decode()
        assert "/admin/hbm" in html and "/admin/device" in html

    def test_metrics_export_hbm_series(self, srv):
        _get(srv, "/search?q=walrus&format=json").read()
        text = _get(srv, "/metrics").read().decode()
        assert "# TYPE osse_hbm_bytes gauge" in text
        assert 'osse_hbm_bytes{collection="main",plane="devindex"}' \
            in text
