"""Chaos plane — deterministic fault injection and deadline propagation.

The contract under test (utils/chaos.py + utils/deadline.py):

* off is a true no-op and armed schedules are pure functions of
  ``(seed, point, call#)`` — same seed, same fault sequence;
* a refused scatter leg fast-fails to the twin (no connect-timeout
  ride-out) and takes the dead twin out of rotation at once;
* a query's deadline travels serve edge → scatter leg header → node
  dequeue → device dispatch / resident issue, and each checkpoint
  abandons (counted) instead of burning work nobody waits for;
* expired queries serve the cache plane's just-stale answer marked
  degraded before they refuse, and degraded SERPs are never cached;
* a killed primary mid-query is eaten by the hedge, and the dead
  twin's penalty decays once it answers pings again;
* flipped bytes in a posting run trip CRC quarantine — detected,
  never served.
"""

import threading
import time

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.parallel import cluster as cl
from open_source_search_engine_tpu.query.engine import (
    _compile_cached, get_resident_loop, search_device_batch)
from open_source_search_engine_tpu.serve.server import (QueryBatcher,
                                                        SearchHTTPServer)
from open_source_search_engine_tpu.utils import chaos as chaos_mod
from open_source_search_engine_tpu.utils import deadline as deadline_mod
from open_source_search_engine_tpu.utils import ghash
from open_source_search_engine_tpu.utils.chaos import (DEFAULT_POINTS,
                                                       ChaosError,
                                                       ChaosPlane,
                                                       g_chaos)
from open_source_search_engine_tpu.utils.deadline import (Deadline,
                                                          DeadlineExceeded)
from open_source_search_engine_tpu.utils.membudget import MemBudget
from open_source_search_engine_tpu.utils.stats import g_stats
from open_source_search_engine_tpu.utils.trace import g_tracer

from .polling import wait_until


@pytest.fixture(autouse=True)
def _chaos_reset():
    """Every test starts unarmed with clean counters and leaves the
    process-global plane unarmed (the OSSE_CHAOS-unset no-op that the
    rest of the suite relies on)."""
    g_chaos.disable()
    g_stats.reset()
    yield
    g_chaos.disable()


def _count(name: str) -> int:
    return g_stats.snapshot()["counters"].get(name, 0)


def _await_count(name: str, n: int = 1, timeout: float = 5.0) -> int:
    """Counters bumped on server/background threads land a beat after
    the client call returns — poll instead of asserting a race."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        c = _count(name)
        if c >= n:
            return c
        time.sleep(0.01)
    return _count(name)


def _doc(i, words="cluster shared words"):
    return (f"<html><head><title>Doc {i}</title></head><body>"
            f"<p>{words} token{i}.</p></body></html>")


# ---------------------------------------------------------------------------
# the plane itself: determinism, aiming, arming
# ---------------------------------------------------------------------------

class TestChaosPlane:
    def test_off_is_noop(self):
        p = ChaosPlane()
        assert not p.enabled
        assert all(p.decide(pt) is None for pt in DEFAULT_POINTS)
        # the global plane ships unarmed — the single-flag-check no-op
        # every hot-path seam guards on
        assert g_chaos.enabled is False

    def test_same_seed_replays_same_schedule(self):
        p = ChaosPlane()
        p.enable(42, rate=0.5)
        seq1 = [p.decide("transport.request") for _ in range(64)]
        p.enable(42, rate=0.5)  # re-arm resets the call counters
        seq2 = [p.decide("transport.request") for _ in range(64)]
        assert seq1 == seq2
        assert any(k is not None for k in seq1)  # rate=0.5 fires some
        assert any(k is None for k in seq1)      # ...and skips some
        p.enable(43, rate=0.5)
        seq3 = [p.decide("transport.request") for _ in range(64)]
        assert seq3 != seq1  # a different seed is a different schedule
        p.disable()
        assert p.decide("transport.request") is None

    def test_match_filter_aims_without_skewing_the_schedule(self):
        # the match filter applies AFTER the call counter bump, so an
        # aimed plane and an unaimed one stay call-for-call aligned
        p, q = ChaosPlane(), ChaosPlane()
        p.enable(7, rate=1.0)
        q.enable(7, rate=1.0)
        q.configure("transport.request", match="10.0.0.9:8042")
        keys = ["10.0.0.9:8042/rpc/search", "10.0.0.7:8042/rpc/search",
                "10.0.0.9:8042/rpc/doc", "10.0.0.8:8042/rpc/search"]
        for k in keys:
            kind_all = p.decide("transport.request", key=k)
            kind_aimed = q.decide("transport.request", key=k)
            if "10.0.0.9:8042" in k:
                assert kind_aimed == kind_all
            else:
                assert kind_aimed is None

    def test_configure_narrows_kinds_and_rate(self):
        p = ChaosPlane()
        p.enable(5, rate=0.0)  # armed, but every point quiet...
        assert p.decide("transport.request") is None
        p.configure("transport.request", rate=1.0, kinds=("refuse",))
        assert all(p.decide("transport.request") == "refuse"
                   for _ in range(10))
        # ...and the other points stayed quiet
        assert p.decide("cluster.node") is None
        assert p.fired("transport.request")["refuse"] == 10

    def test_maybe_enable_env(self, monkeypatch):
        monkeypatch.delenv("OSSE_CHAOS", raising=False)
        assert chaos_mod.maybe_enable() is False
        monkeypatch.setenv("OSSE_CHAOS", "not-a-seed")
        assert chaos_mod.maybe_enable() is False
        assert not g_chaos.enabled
        monkeypatch.setenv("OSSE_CHAOS", "7")
        assert chaos_mod.maybe_enable() is True
        assert g_chaos.enabled and g_chaos.seed == 7


# ---------------------------------------------------------------------------
# the Deadline helper
# ---------------------------------------------------------------------------

class TestDeadline:
    def test_budget_arithmetic_and_header_roundtrip(self):
        dl = Deadline.after(5.0)
        assert 0.0 < dl.remaining() <= 5.0
        assert not dl.expired()
        assert dl.clamp(10.0) <= 5.0
        assert dl.clamp(0.001) == pytest.approx(0.001, abs=1e-3)
        # the wire carries remaining BUDGET, not a wall-clock instant
        dl2 = Deadline.from_header(dl.header_value())
        assert abs(dl2.remaining() - dl.remaining()) < 0.1
        assert Deadline.from_header(None) is None
        assert Deadline.from_header("") is None
        assert Deadline.from_header("bogus") is None
        gone = Deadline.after(-1.0)
        assert gone.expired() and gone.clamp(10.0) == 0.0
        assert gone.header_value() == "0.0000"

    def test_check_abandon_counts_and_binds(self):
        # nothing bound: unbudgeted work never abandons
        assert deadline_mod.current() is None
        assert not deadline_mod.check_abandon("nowhere")
        with deadline_mod.bind(Deadline.after(60.0)):
            assert not deadline_mod.check_abandon("early")
            deadline_mod.note_met()
        assert _count("deadline.met") == 1
        with deadline_mod.bind(Deadline.after(-1.0)):
            assert deadline_mod.check_abandon("spot")
        assert deadline_mod.current() is None
        assert _count("deadline.abandoned") == 1
        assert _count("deadline.abandoned.spot") == 1

    def test_query_batcher_deadline_beats_own_timeout(self):
        ev = threading.Event()

        def run_batch(key, qs):
            ev.wait(timeout=2.0)
            return [f"r:{q}" for q in qs]

        qb = QueryBatcher(run_batch)
        try:
            with deadline_mod.bind(Deadline.after(0.05)):
                with pytest.raises(DeadlineExceeded):
                    qb.search(("main", 10, 0), "slow question",
                              timeout=30.0)
            ev.set()
            # an unbudgeted rider on the same batcher still completes
            assert qb.search(("main", 10, 0), "fine") == "r:fine"
        finally:
            ev.set()
            qb.stop()


# ---------------------------------------------------------------------------
# transport chaos: fast-fail on refusal (satellite: dead-peer fast-fail)
# ---------------------------------------------------------------------------

class TestTransportChaos:
    def test_refused_primary_fastfails_to_twin(self, tmp_path):
        a = cl.ShardNodeServer(tmp_path / "a", port=0)
        b = cl.ShardNodeServer(tmp_path / "b", port=0)
        for n in (a, b):  # twins carry the same docs
            for i in range(4):
                n.handle("/rpc/index", {"url": f"http://t.test/d{i}",
                                        "content": _doc(i)})
            n.start()
        conf = cl.HostsConf.parse(
            f"num-mirrors: 1\n127.0.0.1:{a.port}\n127.0.0.1:{b.port}")
        client = cl.ClusterClient(conf, use_heartbeat=False)
        client.hostmap.rtt_s[0, 0] = 0.001  # pin a as primary
        client.hostmap.rtt_s[0, 1] = 0.002
        try:
            g_chaos.enable(11, rate=0.0)
            g_chaos.configure("transport.request", rate=1.0,
                              kinds=("refuse",),
                              match=f"127.0.0.1:{a.port}")
            res = client.search("cluster shared", topk=5)
            # the twin answered in full — no degraded partial, and the
            # refusal cost no connect-timeout ride-out
            assert res.total_matches > 0 and res.results
            assert not res.degraded
            assert _count("transport.fastfail") >= 1
            # actively refused = known dead right now: out of rotation
            # immediately, no ping grace
            assert not client.hostmap.alive[0, 0]
            assert client.hostmap.twin_order(0)[0] == 1
        finally:
            g_chaos.disable()
            client.close()
            a.stop()
            b.stop()

    def test_dropped_leg_degrades_partial_and_stays_uncached(
            self, tmp_path):
        """Satellite: a timed-out/dropped scatter leg yields a partial
        answer marked degraded, counted, and never pinned in the result
        cache for a TTL."""
        a = cl.ShardNodeServer(tmp_path / "a", port=0)
        b = cl.ShardNodeServer(tmp_path / "b", port=0)
        a.start()
        b.start()
        conf = cl.HostsConf.parse(
            f"num-mirrors: 0\n127.0.0.1:{a.port}\n127.0.0.1:{b.port}")
        client = cl.ClusterClient(conf, use_heartbeat=False)
        try:
            per_shard = {0: 0, 1: 0}
            for i in range(16):
                url = f"http://t.test/d{i}"
                s = int(client.hostmap.shard_of_docid(ghash.doc_id(url)))
                per_shard[s] += 1
                client.index_document(url, _doc(i))
            assert per_shard[0] and per_shard[1]  # both shards populated
            g_chaos.enable(13, rate=0.0)
            g_chaos.configure("transport.request", rate=1.0,
                              kinds=("drop",),
                              match=f"127.0.0.1:{b.port}")
            res = client.search("cluster shared words", topk=10)
            assert res.degraded  # shard b's leg dropped: partial answer
            assert res.total_matches > 0  # ...but shard a still answered
            assert _count("results.degraded") >= 1
            # the degraded SERP was served once, not cached: the same
            # query recomputes (and degrades again)
            before = _count("results.degraded")
            res2 = client.search("cluster shared words", topk=10)
            assert res2.degraded
            assert _count("results.degraded") > before
        finally:
            g_chaos.disable()
            client.close()
            a.stop()
            b.stop()


# ---------------------------------------------------------------------------
# deadline propagation through the cluster serve path
# ---------------------------------------------------------------------------

class TestDeadlinePropagation:
    def test_expired_deadline_abandons_at_node_dequeue(self, tmp_path):
        node = cl.ShardNodeServer(tmp_path / "n", port=0)
        for i in range(3):
            node.handle("/rpc/index", {"url": f"http://t.test/d{i}",
                                       "content": _doc(i)})
        node.start()
        conf = cl.HostsConf.parse(f"num-mirrors: 0\n127.0.0.1:{node.port}")
        client = cl.ClusterClient(conf, use_heartbeat=False)
        try:
            with deadline_mod.bind(Deadline.after(0.0001)):
                res = client.search("cluster shared", topk=5)
            # the budget was gone before the scatter: partial/empty
            # answer marked degraded, never a hang
            assert res.degraded
            # the node saw the shipped budget and abandoned at the door
            assert _await_count("deadline.abandoned.node.dequeue") >= 1
            assert _count("deadline.abandoned") >= 1
            # a generously budgeted query on the same plane completes
            with deadline_mod.bind(Deadline.after(60.0)):
                res2 = client.search("cluster shared token1", topk=5)
            assert not res2.degraded and res2.total_matches > 0
        finally:
            client.close()
            node.stop()

    def test_expired_deadline_abandons_device_dispatch(self, tmp_path):
        coll = Collection("chaosdev", tmp_path)
        coll.conf.pqr_enabled = False
        for i in range(3):
            docproc.index_document(coll, f"http://d.test/p{i}", _doc(i))
        with deadline_mod.bind(Deadline.after(-1.0)):
            with pytest.raises(DeadlineExceeded):
                search_device_batch(coll, ["cluster"], topk=5)
        assert _count("deadline.abandoned.device.dispatch") >= 1


# ---------------------------------------------------------------------------
# serve edge: stale-before-refuse, degraded SERPs uncached
# ---------------------------------------------------------------------------

@pytest.fixture
def srv(tmp_path):
    s = SearchHTTPServer(tmp_path, port=0)
    coll = s.colldb.get("main")
    for i in range(6):
        docproc.index_document(
            coll, f"http://a{i % 3}.test/p{i}",
            f"<html><title>t{i}</title><body><p>serve corpus words "
            f"number{i}</p></body></html>")
    return s


def _search(s, **q):
    return s.handle("GET", "/search", {k: str(v) for k, v in q.items()},
                    b"")


class TestServeEdge:
    def test_deadline_met_is_counted(self, srv):
        code, body, _ = _search(srv, q="serve corpus",
                                deadline_ms=60000)
        assert code == 200
        assert _count("deadline.met") >= 1

    def test_expired_query_serves_stale_marked_degraded(self, srv):
        coll = srv.colldb.get("main")
        coll.conf.result_cache_ttl = 0.05
        code, page, _ = _search(srv, q="serve corpus")
        assert code == 200  # primed the result cache
        # ...and poll until the entry expires in place (lookup counts
        # the miss without evicting, so lookup_stale still finds it) —
        # a fixed sleep here flakes on loaded boxes
        gen = srv._result_gen(coll)
        ckey = ("main", "serve corpus", 10, 0, "json")
        wait_until(
            lambda: not srv._result_cache.lookup(ckey, gen=gen)[0],
            timeout=2.0, desc="result cache entry expiry")

        def timed_out_render(*a, **kw):
            raise DeadlineExceeded("chaos: render over budget")

        srv._render_search = timed_out_render
        code2, page2, _ = _search(srv, q="serve corpus")
        # just-stale beats refusal: same page, marked served-stale
        assert code2 == 200 and page2 == page
        assert _count("deadline.stale_served") == 1
        assert srv.stats.get("deadline_stale") == 1
        # no stale entry to fall back on → honest refusal
        code3, body3, _ = _search(srv, q="never cached words")
        assert code3 == 504
        assert _count("deadline.refused") == 1

    def test_degraded_serp_never_cached(self, srv):
        coll = srv.colldb.get("main")
        coll.conf.result_cache_ttl = 30.0
        degrade = True

        def render(query, q, n, s, fmt, rc_coll, debug, tr,
                   degraded_out=None):
            if degrade and degraded_out is not None:
                degraded_out["degraded"] = True
            return 200, '{"results": []}', "application/json"

        srv._render_search = render
        gen = srv._result_gen(coll)
        code, _, _ = _search(srv, q="partial words")
        assert code == 200
        hit, _ = srv._result_cache.lookup(
            ("main", "partial words", 10, 0, "json"), gen=gen)
        assert not hit  # a partial answer must not serve for a TTL
        degrade = False
        code, _, _ = _search(srv, q="whole words")
        assert code == 200
        hit, _ = srv._result_cache.lookup(
            ("main", "whole words", 10, 0, "json"), gen=gen)
        assert hit  # the control: complete answers do cache


# ---------------------------------------------------------------------------
# twin failover end-to-end: kill the primary mid-query
# ---------------------------------------------------------------------------

def _span_tags(node, out):
    out.append(node.get("tags", {}))
    for c in node.get("children", []):
        _span_tags(c, out)
    return out


class TestTwinFailover:
    def test_kill_primary_mid_query_hedge_eats_it(self, tmp_path):
        # 2 shards × 2 twins, replica-major host order: a0 b0 a1 b1
        nodes = [cl.ShardNodeServer(tmp_path / nm, port=0)
                 for nm in ("a0", "b0", "a1", "b1")]
        for n in nodes:
            n.start()
        conf = cl.HostsConf.parse(
            "num-mirrors: 1\n" + "\n".join(
                f"127.0.0.1:{n.port}" for n in nodes))
        client = cl.ClusterClient(conf, use_heartbeat=False)
        client.hostmap.rtt_s[:, 0] = 0.001  # replica 0 is primary
        client.hostmap.rtt_s[:, 1] = 0.002
        a0 = nodes[0]
        a0_port = a0.port
        try:
            for i in range(12):  # writes land on every twin of a shard
                client.index_document(f"http://t.test/d{i}", _doc(i))
            g_chaos.enable(17, rate=0.0)
            g_chaos.configure("cluster.node", rate=1.0, kinds=("kill",),
                              match=str(a0_port), delay_s=0.05)
            with g_tracer.start("killquery", sampled=True) as tr:
                res = client.search("cluster shared words", topk=10)
            # the answer is COMPLETE: the killed twin's shard answered
            # through its mirror, nothing degraded, nothing lost
            assert not res.degraded
            assert res.total_matches > 0 and res.results
            assert g_chaos.fired("cluster.node").get("kill", 0) >= 1
            assert _count("transport.hedge_fired") >= 1
            assert _count("transport.hedge_won") >= 1
            # the trace shows the hedge leg winning the race
            tags = _span_tags(tr.export()["root"], [])
            assert any(t.get("hedge") and t.get("won") for t in tags)
            g_chaos.disable()
            # the killed twin (shard 0 replica 0) fell out of
            # preference: its in-flight penalty demoted it
            pen0 = max(float(client.hostmap.rtt_s[s, 0])
                       for s in range(2))
            assert client.hostmap.twin_order(0)[0] == 1
            # ...and a restart + health pings decay the penalty instead
            # of demoting it forever
            a0.stop()  # idempotent: make sure the kill's stop finished
            restarted = cl.ShardNodeServer(tmp_path / "a0",
                                           port=a0_port)
            give_up = Deadline.after(10.0)
            while True:
                try:
                    restarted.start()
                    break
                except OSError:  # socket still draining from the kill
                    if give_up.expired():
                        raise
                    time.sleep(0.05)
            try:
                for _ in range(3):
                    client.check_hosts()
                assert bool(client.hostmap.alive.all())
                pen1 = max(float(client.hostmap.rtt_s[s, 0])
                           for s in range(2))
                assert pen1 < pen0
            finally:
                restarted.stop()
        finally:
            g_chaos.disable()
            client.close()
            for n in nodes[1:]:
                n.stop()


# ---------------------------------------------------------------------------
# resident loop chaos
# ---------------------------------------------------------------------------

DOCS = {
    "http://a.example.com/fruit": """
      <html><head><title>Fruit basics</title></head><body>
      <p>The apple is sweet. A banana is tropical. Apple pie wins.</p>
      </body></html>""",
    "http://b.example.com/apple": """
      <html><head><title>Apple orchard</title></head><body>
      <p>Our orchard grows apple trees. Apple harvest is in fall.</p>
      </body></html>""",
}


@pytest.fixture
def rescoll(tmp_path):
    c = Collection("chaosres", tmp_path)
    c.conf.pqr_enabled = False
    for u, h in DOCS.items():
        docproc.index_document(c, u, h)
    return c


class TestResidentChaos:
    def test_dropped_collect_fails_wave_not_loop(self, rescoll):
        loop = get_resident_loop(rescoll)
        plans = [_compile_cached("apple", 0)]
        g_chaos.enable(23, rate=0.0)
        g_chaos.configure("resident.loop", rate=1.0,
                          kinds=("drop_collect",), match="collect")
        with pytest.raises(ChaosError):
            loop.submit(plans, topk=16, lang=0).wait(timeout=60)
        # the wave died; the loop did not — the next submit answers
        g_chaos.disable()
        ((d, s, n),) = loop.submit(plans, topk=16,
                                   lang=0).wait(timeout=60)
        assert n > 0

    def test_stalled_wave_still_answers(self, rescoll):
        loop = get_resident_loop(rescoll)
        g_chaos.enable(29, rate=0.0)
        g_chaos.configure("resident.loop", rate=1.0, kinds=("stall",),
                          delay_s=0.01)
        ((d, s, n),) = loop.submit([_compile_cached("apple", 0)],
                                   topk=16, lang=0).wait(timeout=60)
        assert n > 0
        assert g_chaos.fired("resident.loop").get("stall", 0) >= 1

    def test_expired_ticket_abandons_at_issue(self, rescoll):
        loop = get_resident_loop(rescoll)
        t = loop.submit([_compile_cached("apple", 0)], topk=16, lang=0,
                        deadline=Deadline.after(-1.0))
        with pytest.raises(DeadlineExceeded):
            t.wait(timeout=60)
        assert _count("deadline.abandoned.resident.issue") >= 1
        # an unbudgeted ticket right behind it is unaffected
        ((d, s, n),) = loop.submit([_compile_cached("apple", 0)],
                                   topk=16, lang=0).wait(timeout=60)
        assert n > 0


# ---------------------------------------------------------------------------
# rdb corruption: detected, quarantined, never served
# ---------------------------------------------------------------------------

class TestRdbChaos:
    def test_flipped_byte_trips_scrub_quarantine(self, tmp_path):
        coll = Collection("chaosrdb", tmp_path)
        coll.conf.pqr_enabled = False
        for i in range(20):
            docproc.index_document(coll, f"http://r.test/p{i}", _doc(i))
        assert coll.posdb.dump() is not None  # an on-disk run to maim
        g_chaos.enable(31, rate=0.0)
        target = g_chaos.corrupt_one_run(coll.posdb)
        assert target is not None
        assert _count("chaos.rdb.corrupted") == 1
        quarantined = coll.posdb.scrub()
        assert quarantined  # CRC verify tripped — the bytes never serve
        assert _count("rdb.corrupt_quarantined") >= 1
        g_chaos.disable()
        # the engine still answers from the surviving state
        res = search_device_batch(coll, ["cluster"], topk=5)
        assert res is not None

    def test_rdb_read_seam_fires_via_decide(self, tmp_path):
        coll = Collection("chaosrdb2", tmp_path)
        coll.conf.pqr_enabled = False
        for i in range(20):
            docproc.index_document(coll, f"http://r2.test/p{i}", _doc(i))
        coll.posdb.dump()
        g_chaos.enable(37, rate=0.0)
        g_chaos.configure("rdb.read", rate=1.0, kinds=("flipbyte",))
        from open_source_search_engine_tpu.index import posdb
        tid = ghash.term_id("cluster")
        coll.posdb.get_list(posdb.start_key(tid), posdb.end_key(tid))
        assert g_chaos.fired("rdb.read").get("flipbyte", 0) >= 1
        assert coll.posdb.scrub()  # the seam corrupted a real run


# ---------------------------------------------------------------------------
# membudget forced pressure
# ---------------------------------------------------------------------------

class TestMemBudgetChaos:
    def test_forced_pressure_runs_shed_pass(self):
        budget = MemBudget(limit=1 << 20)
        calls = []

        def handler(need):
            calls.append(need)
            return 0

        budget.add_pressure_handler(handler)
        g_chaos.enable(41, rate=0.0)
        g_chaos.configure("membudget.reserve", rate=1.0,
                          kinds=("pressure",))
        # the reservation FITS — chaos still forces the shed pass, so
        # the shed-before-refuse path gets exercised under load
        assert budget.reserve("chaostest", 1024) is True
        assert calls and calls[0] == 1024
        assert g_chaos.fired("membudget.reserve").get("pressure",
                                                      0) >= 1
        budget.release("chaostest", 1024)
        # unarmed, the same reservation never touches the handlers
        g_chaos.disable()
        calls.clear()
        assert budget.reserve("chaostest", 1024) is True
        assert not calls
        budget.release("chaostest", 1024)


# ---------------------------------------------------------------------------
# the soak gate (slow): crawl → index → serve under chaos
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_gate(monkeypatch, tmp_path):
    import bench
    monkeypatch.setenv("BENCH_SOAK_QUERIES", "48")
    monkeypatch.setenv("BENCH_SOAK_PAGES", "24")
    monkeypatch.setenv("BENCH_DIR", str(tmp_path))
    rep = bench.main_soak()
    assert rep["ok"], rep
    assert rep["lost_queries"] == 0
    assert rep["counters"]["deadline.abandoned"] > 0
    assert rep["counters"]["rdb.corrupt_quarantined"] > 0
