"""Admission plane — bounded tiered gate, shed ladder, queue caps.

The contract under test (serve/admission.py + the serve/query wiring):

* tiers classify at the front door (param > header > niceness bit) and
  ride X-OSSE-Priority through scatter legs to the node planes;
* the gate admits by strict tier order (interactive first, FIFO within
  a tier) and sheds cheaply — queue_full / slo-degraded / membudget
  pressure / predicted-delay-eats-deadline — BEFORE work starts;
* the serve edge turns a shed into the cache plane's same-generation
  stale answer marked degraded, else 503 + Retry-After, every one
  counted;
* QueryBatcher and ResidentLoop queues are bounded (QueueFull, counted,
  gauged on the membudget "serve" label) — an overload burst cannot
  grow host memory without bound;
* a banned client hammering the endpoint can never re-extend its own
  ban (AutoBan robustness), and overload composed with a chaos-wedged
  twin still hedges, bounds interactive latency, and loses no request.
"""

import threading
import time

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.parallel import cluster as cl
from open_source_search_engine_tpu.query.resident import ResidentLoop
from open_source_search_engine_tpu.serve import admission as admission_mod
from open_source_search_engine_tpu.serve.admission import (AdmissionGate,
                                                           Shed)
from open_source_search_engine_tpu.serve.server import (QueryBatcher,
                                                        SearchHTTPServer)
from open_source_search_engine_tpu.utils import priority as priority_mod
from open_source_search_engine_tpu.utils.chaos import g_chaos
from open_source_search_engine_tpu.utils.deadline import Deadline
from open_source_search_engine_tpu.utils.membudget import g_membudget
from open_source_search_engine_tpu.utils.priority import (QueueFull,
                                                          classify)
from open_source_search_engine_tpu.utils.stats import g_stats

from .polling import wait_until


@pytest.fixture(autouse=True)
def _stats_reset():
    g_chaos.disable()
    g_stats.reset()
    yield
    g_chaos.disable()


def _count(name: str) -> int:
    return g_stats.snapshot()["counters"].get(name, 0)


# ---------------------------------------------------------------------------
# tier vocabulary
# ---------------------------------------------------------------------------

class TestClassify:
    def test_precedence_param_header_niceness(self):
        assert classify({"tier": "crawlbot"}) == "crawlbot"
        assert classify({}, header_tier="suggest") == "suggest"
        assert classify({"tier": "suggest"},
                        header_tier="crawlbot") == "suggest"
        assert classify({}, niceness=1) == "crawlbot"
        assert classify({}) == "interactive"

    def test_unknown_values_classify_up(self):
        # misclassifying UP is safer than starving a human
        assert classify({"tier": "root"}) == "interactive"
        assert priority_mod.tier_from_header("ADMIN") is None
        assert priority_mod.tier_from_header(" Crawlbot ") == "crawlbot"

    def test_tier_niceness_mapping(self):
        assert priority_mod.tier_niceness("interactive") == 0
        assert priority_mod.tier_niceness("crawlbot") == 1
        assert priority_mod.tier_niceness(None) == 0


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

class TestAdmissionGate:
    def test_admit_release_counts_and_histogram(self):
        g = AdmissionGate(max_inflight=2)
        with g.admit("interactive"):
            assert g.snapshot()["inflight"] == 1
        assert g.idle()
        assert _count("admission.admitted") == 1
        lat = g_stats.snapshot()["latencies"]
        assert lat["admission.queue_delay"]["count"] == 1

    def test_priority_wake_order(self):
        g = AdmissionGate(max_inflight=1, max_queue=8, max_wait_s=5.0)
        tok = g.admit("interactive")
        order = []

        def waiter(tier):
            with g.admit(tier):
                order.append(tier)

        # crawlbot queues FIRST, interactive second — the grant must
        # still go tier-order, not FIFO across tiers
        tc = threading.Thread(target=waiter, args=("crawlbot",))
        tc.start()
        wait_until(lambda: g.snapshot()["queued"]["crawlbot"] == 1,
                   desc="crawlbot queued")
        ti = threading.Thread(target=waiter, args=("interactive",))
        ti.start()
        wait_until(lambda: g.snapshot()["queued"]["interactive"] == 1,
                   desc="interactive queued")
        tok.__exit__(None, None, None)
        ti.join(5.0)
        tc.join(5.0)
        assert order == ["interactive", "crawlbot"]
        assert _count("admission.queued") == 2
        assert g.idle()

    def test_queue_full_sheds(self):
        g = AdmissionGate(max_inflight=1, max_queue=1, max_wait_s=5.0)
        tok = g.admit("interactive")
        t = threading.Thread(
            target=lambda: g.admit("interactive").__exit__(
                None, None, None))
        t.start()
        wait_until(lambda: g.snapshot()["queued_total"] == 1,
                   desc="one waiter queued")
        with pytest.raises(Shed) as ei:
            g.admit("interactive")
        assert ei.value.reason == "queue_full"
        assert _count("admission.queue_full") == 1
        tok.__exit__(None, None, None)
        t.join(5.0)

    def test_degraded_signal_sheds_background_not_interactive(self):
        g = AdmissionGate(degraded_fn=lambda: True)
        for tier in ("crawlbot", "suggest"):
            with pytest.raises(Shed) as ei:
                g.admit(tier)
            assert ei.value.reason == "signal"
        with g.admit("interactive"):
            pass
        assert g.shed_total == 2

    def test_membudget_pressure_sheds_background(self):
        g = AdmissionGate(pressure_fn=lambda: True)
        with pytest.raises(Shed):
            g.admit("crawlbot")
        with g.admit("interactive"):
            pass

    def test_predicted_delay_vs_deadline_sheds_at_door(self):
        g = AdmissionGate(max_inflight=1)
        g._svc_s = 1.0  # pessimistic EWMA: ~1s per admitted slot
        tok = g.admit("interactive")
        with pytest.raises(Shed) as ei:
            g.admit("interactive", deadline=Deadline.after(0.05))
        assert ei.value.reason == "deadline"
        assert ei.value.retry_after_s >= 1.0
        tok.__exit__(None, None, None)

    def test_wait_timeout_sheds_and_unqueues(self):
        g = AdmissionGate(max_inflight=1, max_wait_s=0.05)
        tok = g.admit("interactive")
        with pytest.raises(Shed) as ei:
            g.admit("interactive")
        assert ei.value.reason == "timeout"
        assert g.snapshot()["queued_total"] == 0  # waiter removed
        tok.__exit__(None, None, None)
        assert g.idle()


# ---------------------------------------------------------------------------
# bounded dispatch queues (satellite: unbounded today → capped)
# ---------------------------------------------------------------------------

class _FakeDI:
    """issue/collect stub: issue blocks on an event so tickets pile up
    in the queue (the overload shape the cap exists for)."""
    _built_version = 1

    def __init__(self, ev):
        self.ev = ev

    def issue_batch(self, plans, topk=0, lang=0):
        self.ev.wait(5.0)
        return list(plans)

    def collect_batch(self, pending):
        return [("d", "s", 0) for _ in pending]


class TestQueueCaps:
    def test_batcher_cap_raises_queuefull(self):
        b = QueryBatcher(lambda key, qs: ["r"] * len(qs))
        try:
            b.MAX_QUEUE = 0  # instance override: every enqueue refused
            with pytest.raises(QueueFull):
                b.search(("main", 10, 0), "words")
            assert _count("admission.queue_full") == 1
        finally:
            b.stop()

    def test_batcher_idle_flush_launches_immediately(self):
        b = QueryBatcher(lambda key, qs: ["r"] * len(qs))
        try:
            assert b.search(("main", 10, 0), "words") == "r"
            assert _count("admission.wave.idle_flush") >= 1
        finally:
            b.stop()

    def test_resident_cap_fails_ticket_and_gauges_membudget(self):
        ev = threading.Event()
        di = _FakeDI(ev)
        loop = ResidentLoop(lambda: di, lambda: 1, max_queue=2,
                            name="capped")
        try:
            t1 = loop.submit([b"p1"])  # loop blocks inside issue
            wait_until(lambda: loop.waves_issued == 0
                       and not loop._queue, timeout=2.0,
                       desc="first ticket taken for issue")
            t2 = loop.submit([b"p2"])
            t3 = loop.submit([b"p3"])
            # queue at cap → gauged on the membudget "serve" label
            lbl = g_membudget.snapshot()["labels"].get("serve", {})
            assert lbl.get("gauged", 0) > 0
            t4 = loop.submit([b"p4"])
            with pytest.raises(QueueFull):
                t4.wait(timeout=1.0)
            assert _count("admission.queue_full") == 1
            ev.set()
            for t in (t1, t2, t3):
                assert t.wait(timeout=5.0)
            assert _count("resident.idle_flush") >= 1
        finally:
            ev.set()
            loop.stop()


# ---------------------------------------------------------------------------
# serve-edge integration: classification, shed ladder, autoban
# ---------------------------------------------------------------------------

@pytest.fixture
def srv(tmp_path):
    s = SearchHTTPServer(str(tmp_path), port=0)
    coll = s.colldb.get("main")
    for i in range(4):
        docproc.index_document(
            coll, f"http://adm{i}.test/p{i}",
            f"<html><title>t{i}</title><body><p>admission corpus "
            f"words number{i}</p></body></html>")
    yield s
    s.stop()


def _search(s, niceness=0, **q):
    return s.handle("GET", "/search",
                    {k: str(v) for k, v in q.items()}, b"",
                    client_ip="9.9.9.9", niceness=niceness)


class TestServeEdge:
    def test_front_door_classification_counted(self, srv):
        assert _search(srv, q="admission corpus")[0] == 200
        assert _count("admission.tier.interactive") == 1
        assert _search(srv, q="admission corpus",
                       tier="crawlbot")[0] == 200
        assert _count("admission.tier.crawlbot") == 1
        # the niceness bit self-identifies background callers
        assert _search(srv, q="admission corpus", niceness=1)[0] == 200
        assert _count("admission.tier.crawlbot") == 2

    def test_shed_refuses_with_retry_after(self, srv):
        srv.admission = AdmissionGate(degraded_fn=lambda: True)
        code, body, ctype = _search(srv, q="never cached words",
                                    tier="crawlbot")
        assert code == 503
        assert '"retryAfter"' in body
        assert _count("admission.shed.refused") == 1
        # the Retry-After header rides the side channel for the HTTP
        # handler to emit
        hdrs = dict(admission_mod.pop_response_headers())
        assert "Retry-After" in hdrs
        # interactive still admitted under the same signal
        assert _search(srv, q="admission corpus")[0] == 200

    def test_shed_serves_same_generation_stale_first(self, srv):
        coll = srv.colldb.get("main")
        coll.conf.result_cache_ttl = 0.05
        srv.admission = AdmissionGate(degraded_fn=lambda: True)
        code, page, _ = _search(srv, q="admission corpus")
        assert code == 200  # interactive primed the result cache
        gen = srv._result_gen(coll)
        ckey = ("main", "admission corpus", 10, 0, "json")
        wait_until(
            lambda: not srv._result_cache.lookup(ckey, gen=gen)[0],
            timeout=2.0, desc="result cache entry expiry")
        # crawlbot sheds → the just-expired page beats a refusal
        code2, page2, _ = _search(srv, q="admission corpus",
                                  tier="crawlbot")
        assert code2 == 200 and page2 == page
        assert _count("admission.shed.stale") == 1
        assert srv.stats.get("admission_stale") == 1

    def test_fresh_cache_hit_bypasses_gate(self, srv):
        coll = srv.colldb.get("main")
        coll.conf.result_cache_ttl = 30.0
        code, page, _ = _search(srv, q="admission corpus")
        assert code == 200
        # now close the gate entirely: the hot head must keep answering
        srv.admission = AdmissionGate(max_inflight=0, max_queue=0)
        code2, page2, _ = _search(srv, q="admission corpus")
        assert code2 == 200 and page2 == page

    def test_autoban_cannot_self_extend(self, srv):
        """Satellite (a): a banned client hammering the endpoint must
        be re-admitted after BAN_COOLDOWN_S — rejected requests do NOT
        charge the rate window, so the ban cannot re-extend forever."""
        coll = srv.colldb.get("main")
        coll.conf.autoban_qps = 5
        srv.BAN_COOLDOWN_S = 0.3  # instance override: fast cooldown
        ip = "6.6.6.6"
        t0 = time.monotonic()
        first_429 = None
        readmitted_at = None
        # sustained offered load for ~3 cooldowns, no backoff at all
        while time.monotonic() - t0 < 1.0:
            code, _, _ = srv.handle("GET", "/search",
                                    {"q": "admission corpus"}, b"",
                                    client_ip=ip)
            now = time.monotonic()
            if code == 429 and first_429 is None:
                first_429 = now
            if (first_429 is not None and code == 200
                    and now > first_429 + srv.BAN_COOLDOWN_S):
                readmitted_at = now
                break
            time.sleep(0.002)
        assert first_429 is not None, "hammering never tripped autoban"
        assert readmitted_at is not None, \
            "ban never expired under sustained load (self-extension)"
        assert _count("autoban.rejected") > 0


# ---------------------------------------------------------------------------
# header propagation: the tier rides scatter legs to the node planes
# ---------------------------------------------------------------------------

def _doc(i: int) -> str:
    return (f"<html><title>d{i}</title><body><p>cluster shared words "
            f"number{i}</p></body></html>")


class TestTierPropagation:
    def test_node_honors_priority_header(self, tmp_path):
        node = cl.ShardNodeServer(tmp_path / "n0", port=0)
        node.start()
        conf = cl.HostsConf.parse(
            f"num-mirrors: 0\n127.0.0.1:{node.port}")
        client = cl.ClusterClient(conf, use_heartbeat=False)
        try:
            client.index_document("http://t.test/d0", _doc(0))
            with priority_mod.bind_tier("crawlbot"):
                res = client.search("cluster shared words", topk=5)
            assert res.total_matches > 0
            assert _count("admission.node.crawlbot") >= 1
        finally:
            client.close()
            node.stop()


# ---------------------------------------------------------------------------
# chaos-composed overload: wedge one twin WHILE offered > capacity
# ---------------------------------------------------------------------------

class TestChaosOverload:
    def test_wedged_twin_under_overload_hedges_and_sheds_counted(
            self, tmp_path):
        """Satellite (c): with one twin wedged and more offered work
        than the gate admits, hedges still fire, interactive stays
        bounded, and every shed is accounted for — nothing lost."""
        nodes = [cl.ShardNodeServer(tmp_path / nm, port=0)
                 for nm in ("a0", "b0", "a1", "b1")]
        for n in nodes:
            n.start()
        conf = cl.HostsConf.parse(
            "num-mirrors: 1\n" + "\n".join(
                f"127.0.0.1:{n.port}" for n in nodes))
        client = cl.ClusterClient(conf, use_heartbeat=False)
        client.hostmap.rtt_s[:, 0] = 0.001  # replica 0 is primary
        client.hostmap.rtt_s[:, 1] = 0.002
        srv = SearchHTTPServer(str(tmp_path / "front"), cluster=client)
        srv.admission = AdmissionGate(max_inflight=2, max_queue=4,
                                      max_wait_s=2.0)
        lock = threading.Lock()
        codes: dict[int, int] = {}
        try:
            for i in range(12):
                client.index_document(f"http://t.test/d{i}", _doc(i))
            g_chaos.enable(17, rate=0.0)
            g_chaos.configure("cluster.node", rate=1.0,
                              kinds=("wedge",),
                              match=str(nodes[0].port), delay_s=0.05)

            def one(k: int) -> None:
                tier = "crawlbot" if k % 3 == 0 else "interactive"
                try:
                    code, _, _ = srv.handle(
                        "GET", "/search",
                        {"q": f"cluster shared number{k % 12}",
                         "tier": tier, "deadline_ms": "800"},
                        b"", client_ip="7.7.7.7")
                except Exception:  # noqa: BLE001 — a lost reply IS the bug
                    code = -1
                with lock:
                    codes[code] = codes.get(code, 0) + 1

            n_req = 36
            threads = [threading.Thread(target=one, args=(k,))
                       for k in range(n_req)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            # nothing silently lost: every offered request came back,
            # and the refused ones match the shed counter exactly
            assert sum(codes.values()) == n_req
            assert codes.get(-1, 0) == 0
            refused = codes.get(503, 0)
            assert refused + codes.get(504, 0) > 0  # it DID overload
            assert refused == _count("admission.shed.refused")
            # the wedged twin did not disable hedging
            assert g_chaos.fired("cluster.node").get("wedge", 0) >= 1
            assert _count("transport.hedge_fired") >= 1
            # interactive latency stayed bounded (deadline + gate cap,
            # not the wedge's seconds-long stall)
            lat = g_stats.snapshot()["latencies"].get(
                "serve.search.interactive")
            assert lat is not None and lat["count"] > 0
            assert lat["p99_ms"] < 3000.0
            # the gate drained: no leaked slots, no metastable queue
            wait_until(srv.admission.idle, timeout=5.0,
                       desc="admission gate drained")
        finally:
            g_chaos.disable()
            srv.stop()
            client.close()
            for n in nodes:
                n.stop()
