"""Sharded index + mesh scatter-gather tests on the 8-device CPU mesh.

The reference's "multi-node without a cluster" strategy (SURVEY §4.5 —
N gb processes on loopback) becomes N virtual JAX devices: shard routing,
per-shard intersect, and the in-mesh all-gather top-k merge run exactly
as on a real slice, minus the ICI.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.parallel import (
    HostMap, ShardedCollection, make_mesh, sharded_search)
from open_source_search_engine_tpu.query import engine

DOCS = {
    f"http://site{i % 5}.example.com/page{i}":
        f"""<html><head><title>Page {i} about topic{i % 3}</title></head>
        <body><p>This is page number {i}. It discusses topic{i % 3} at
        length. Common words appear everywhere. {'Rare gem here.' if i == 7
        else ''}</p></body></html>"""
    for i in range(20)
}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(4)


@pytest.fixture(scope="module")
def sc(tmp_path_factory, mesh):
    s = ShardedCollection("ptest", tmp_path_factory.mktemp("ptest"),
                          n_shards=4)
    for _row in s.grid:
        for _c in _row:
            _c.conf.pqr_enabled = False
    for url, html in DOCS.items():
        s.index_document(url, html)
    return s


@pytest.fixture(scope="module")
def flat(tmp_path_factory):
    """Same corpus in one unsharded collection — ranking ground truth."""
    c = Collection("flat", tmp_path_factory.mktemp("flat"))
    c.conf.pqr_enabled = False  # kernel-parity tests pin pre-PQR scores
    for url, html in DOCS.items():
        docproc.index_document(c, url, html)
    return c


class TestHostMap:
    def test_docid_routing_stable_and_balanced(self):
        hm = HostMap(4)
        docids = np.arange(1, 4001, dtype=np.uint64)
        s1 = hm.shard_of_docid(docids)
        s2 = hm.shard_of_docid(docids)
        assert np.array_equal(s1, s2)
        counts = np.bincount(s1, minlength=4)
        assert counts.min() > 700  # ~1000 each, loose balance bound

    def test_mesh_axes(self, mesh):
        assert mesh.axis_names == ("shards",)
        assert mesh.devices.shape == (4,)


class TestShardedBuild:
    def test_docs_land_on_owning_shard(self, sc):
        total = sum(c.num_docs for c in sc.shards)
        assert total == len(DOCS)
        # postings spread across shards
        occupied = sum(
            1 for c in sc.shards if len(c.posdb.get_all()))
        assert occupied >= 3

    def test_get_document_routes(self, sc):
        from open_source_search_engine_tpu.utils import ghash
        from open_source_search_engine_tpu.utils.url import normalize
        url = "http://site2.example.com/page7"
        docid = ghash.doc_id(normalize(url).full)
        rec = sc.get_document(docid)
        assert rec and rec["url"] == url


class TestShardedSearch:
    def test_single_term(self, sc, mesh):
        res = sharded_search(sc, "gem", mesh=mesh)
        assert len(res.results) == 1
        assert res.results[0].url == "http://site2.example.com/page7"
        assert "gem" in res.results[0].snippet.lower()

    def test_matches_unsharded_ranking(self, sc, flat, mesh):
        """The mesh scatter-gather must reproduce the single-shard
        ranking bit-for-bit (same kernel, same global freq weights)."""
        for q in ("topic1", "page number", "common words", "topic0 topic1"):
            # clustering picks arbitrary representatives among exact ties,
            # so compare the raw ranking (clustering has its own tests)
            sharded = sharded_search(sc, q, mesh=mesh, topk=20,
                                     site_cluster=False)
            local = engine.search(flat, q, topk=20, site_cluster=False)
            # equal-score ties may order differently across shard layouts;
            # compare the (score, docid) ranking order-independently
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted([key(r) for r in sharded.results]) == \
                   sorted([key(r) for r in local.results]), q
            assert sharded.total_matches == local.total_matches

    def test_and_across_shards(self, sc, mesh):
        res = sharded_search(sc, "topic2 everywhere", mesh=mesh, topk=20)
        # docs with i % 3 == 2 → 6 docs (i=2,5,8,11,14,17)
        assert res.total_matches == 6

    def test_no_match(self, sc, mesh):
        res = sharded_search(sc, "xylophone", mesh=mesh)
        assert res.total_matches == 0 and not res.results

    def test_freq_weights_count_candidateless_shards(self, tmp_path, mesh):
        """A shard whose required-term list is empty must still contribute
        its other terms' postings to global document frequency, or the
        sharded ranking diverges from the flat one."""
        docs = {}
        # 'common' on many docs across all shards; 'rare unique' on one doc
        for i in range(16):
            docs[f"http://w{i}.ex.com/c{i}"] = (
                f"<html><body><p>common words for document {i} padding "
                f"text</p></body></html>")
        docs["http://w0.ex.com/rare"] = (
            "<html><body><p>common rareterm together in one doc</p>"
            "</body></html>")
        sc2 = ShardedCollection("fw", tmp_path / "fw", n_shards=4)
        for _row in sc2.grid:
            for _c in _row:
                _c.conf.pqr_enabled = False
        flat2 = Collection("fwflat", tmp_path / "fwflat")
        for u, h in docs.items():
            sc2.index_document(u, h)
            docproc.index_document(flat2, u, h)
        s = sharded_search(sc2, "common rareterm", mesh=mesh, topk=5)
        f = engine.search(flat2, "common rareterm", topk=5)
        assert len(s.results) == len(f.results) == 1
        assert s.results[0].score == pytest.approx(f.results[0].score,
                                                   rel=1e-5)

    def test_delete_then_search(self, sc, mesh):
        url = "http://sitex.example.com/doomed"
        sc.index_document(url, "<html><body>unobtainium page</body></html>")
        assert sharded_search(sc, "unobtainium", mesh=mesh).results
        assert sc.remove_document(url)
        assert not sharded_search(sc, "unobtainium", mesh=mesh).results

    def test_suggestion_merges_shards(self, sc, mesh):
        """Zero-result sharded queries get a cluster-wide 'did you
        mean' from the merged per-shard dictionaries."""
        res = sharded_search(sc, "discusses everywere", mesh=mesh)
        assert res.total_matches == 0
        assert res.suggestion == "discusses everywhere"


class TestReplicas:
    """Twin serving + failover on a replicated topology (num-mirrors)."""

    @pytest.fixture()
    def rsc(self, tmp_path, mesh):
        s = ShardedCollection("rtest", tmp_path / "rtest",
                              n_shards=4, n_replicas=2)
        for _row in s.grid:
            for _c in _row:
                _c.conf.pqr_enabled = False
        for url, html in DOCS.items():
            s.index_document(url, html)
        return s

    def test_replicated_search_works(self, rsc, mesh):
        res = sharded_search(rsc, "gem", mesh=mesh)
        assert len(res.results) == 1 and not res.degraded

    def test_twin_failover_serves_identically(self, rsc, mesh):
        baseline = sharded_search(rsc, "topic1", mesh=mesh, topk=20,
                                  site_cluster=False)
        for s in range(rsc.n_shards):
            rsc.hostmap.mark_dead(s, 0)  # replica 1 takes over everywhere
        res = sharded_search(rsc, "topic1", mesh=mesh, topk=20,
                             site_cluster=False)
        assert not res.degraded
        assert [(r.docid, r.score) for r in res.results] == \
               [(r.docid, r.score) for r in baseline.results]

    def test_whole_shard_dead_degrades(self, rsc, mesh):
        baseline = sharded_search(rsc, "topic1", mesh=mesh, topk=20)
        rsc.hostmap.mark_dead(1, 0)
        rsc.hostmap.mark_dead(1, 1)
        res = sharded_search(rsc, "topic1", mesh=mesh, topk=20)
        assert res.degraded
        assert res.total_matches <= baseline.total_matches
        rsc.hostmap.mark_alive(1, 0)
        res2 = sharded_search(rsc, "topic1", mesh=mesh, topk=20)
        assert not res2.degraded
        assert res2.total_matches == baseline.total_matches


class TestMeshResident:
    """The production resident kernel on the mesh: one DeviceIndex per
    shard pinned to its own device, global term stats, host Msg3a
    merge (VERDICT r3 item 2)."""

    def test_matches_flat_resident_ranking(self, sc, flat, mesh):
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        from open_source_search_engine_tpu.query.engine import \
            search_device
        mr = MeshResident(sc)
        for q in ("gem", "gem river", "topic2 everywhere", "quartz"):
            flat_res = search_device(flat, q, topk=20,
                                     with_snippets=False,
                                     site_cluster=False)
            mesh_res = mr.search(q, topk=20, with_snippets=False,
                                 site_cluster=False)
            assert mesh_res.total_matches == flat_res.total_matches, q
            assert [round(r.score, 3) for r in mesh_res.results] == \
                [round(r.score, 3) for r in flat_res.results], q
            assert {r.url for r in mesh_res.results} == \
                {r.url for r in flat_res.results}, q

    def test_indexes_pinned_across_devices(self, sc):
        import jax
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        mr = MeshResident(sc)
        devs = {di.device for di in mr.indexes}
        # one device per shard when enough exist (8 virtual CPU devices)
        assert len(devs) == min(sc.n_shards, len(jax.devices()))
        for di in mr.indexes:
            assert di.d_payload.devices() == {di.device}

    def test_batch_matches_single(self, sc):
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        mr = MeshResident(sc)
        qs = ["gem", "topic0", "river gem"]
        batch = mr.search_batch(qs, topk=10, with_snippets=False)
        for q, b in zip(qs, batch):
            s = mr.search(q, topk=10, with_snippets=False)
            assert [r.docid for r in s.results] == \
                [r.docid for r in b.results]


# distinct per-doc term frequencies: the two merge paths order exact
# score TIES differently (host stable-argsort over shard concat vs
# in-jit top_k over the gathered blocks), so dedup-parity corpora must
# make every score unique
DISTINCT_DOCS = {
    f"http://site{i % 5}.example.com/d{i}":
        "<html><title>Doc number %d</title><body><p>%s</p></body></html>"
        % (i, "apple " * (1 + i) + "banana " * (1 + (i * 3) % 11)
           + f"tok{i} gem ")
    for i in range(20)
}


class TestMeshServe:
    """The mesh-RESIDENT serving path: Msg3a merge + 2-per-site dedup
    inside one shard_map program, driven by a ResidentLoop (this PR's
    tentpole). Parity contract: bit-identical to the host-merge
    MeshResident and the flat engine."""

    @pytest.fixture(scope="class")
    def dsc(self, tmp_path_factory):
        s = ShardedCollection("dmesh", tmp_path_factory.mktemp("dmesh"),
                              n_shards=4)
        for _row in s.grid:
            for _c in _row:
                _c.conf.pqr_enabled = False
        for url, html in DISTINCT_DOCS.items():
            s.index_document(url, html)
        return s

    @pytest.fixture(scope="class")
    def dflat(self, tmp_path_factory):
        c = Collection("dflat", tmp_path_factory.mktemp("dflat"))
        c.conf.pqr_enabled = False
        for url, html in DISTINCT_DOCS.items():
            docproc.index_document(c, url, html)
        return c

    @pytest.fixture(scope="class")
    def mr(self, dsc):
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        m = MeshResident(dsc)
        yield m
        m.stop()

    def test_three_way_parity(self, mr, dflat):
        """flat engine == host-merge MeshResident == in-jit mesh merge,
        on docids, scores, totals AND site-dedup clustered counts."""
        from open_source_search_engine_tpu.query.engine import \
            search_device
        qs = ["apple banana", "gem", "tok7", "apple gem"]
        host = mr.search_batch(qs, topk=5, with_snippets=False)
        meshr = mr.serve_batch(qs, topk=5, with_snippets=False)
        for q, h, m in zip(qs, host, meshr):
            f = search_device(dflat, q, topk=5, with_snippets=False)
            for res in (h, m):
                assert res.total_matches == f.total_matches, q
                assert res.clustered == f.clustered, q
                assert [(r.docid, round(r.score, 3))
                        for r in res.results] == \
                       [(r.docid, round(r.score, 3))
                        for r in f.results], q

    def test_serve_without_site_cluster_routes_host(self, mr):
        h = mr.search_batch(["apple banana"], topk=8,
                            with_snippets=False, site_cluster=False)
        m = mr.serve_batch(["apple banana"], topk=8,
                           with_snippets=False, site_cluster=False)
        assert [r.docid for r in m[0].results] == \
               [r.docid for r in h[0].results]
        assert m[0].clustered == 0

    def test_mixed_wave_filter_subgroups(self, mr):
        """A ticket mixing plain and filtered queries splits into
        sub-waves by the program's statics but resolves in order."""
        qs = ["apple banana", "apple site:site2.example.com", "gem"]
        host = mr.search_batch(qs, topk=5, with_snippets=False)
        meshr = mr.serve_batch(qs, topk=5, with_snippets=False)
        for q, h, m in zip(qs, host, meshr):
            assert [r.docid for r in m.results] == \
                   [r.docid for r in h.results], q
            assert m.total_matches == h.total_matches, q

    def test_no_match_suggestion(self, mr):
        res = mr.serve("aple banana", with_snippets=False)
        assert res.total_matches == 0 and not res.results
        assert res.suggestion is not None

    def test_overfetch_escalation_recall(self, tmp_path_factory):
        """The in-program Msg40 recall loop: when a few sites dominate
        the first k·c merge window, the collect escalates the window
        (×4, same staged operands) until low-scored unique-site docs
        surface — parity with the host ladder's re-intersection."""
        from open_source_search_engine_tpu.parallel.sharded import (
            MeshResident, MeshServeIndex)
        s = ShardedCollection("esc", tmp_path_factory.mktemp("esc"),
                              n_shards=4)
        for _row in s.grid:
            for _c in _row:
                _c.conf.pqr_enabled = False
        # 4 sites × 45 high-tf docs bury 5 unique-site low-tf docs
        # past the first out_k window (2·48 → 128 < 180 dominated rows)
        for i in range(180):
            s.index_document(
                f"http://big{i % 4}.example.com/p{i}",
                "<html><body><p>%s</p></body></html>"
                % ("needle " * (3 + i % 37) + f"pad{i} "))
        for i in range(5):
            s.index_document(
                f"http://unique{i}.example.com/u{i}",
                f"<html><body><p>needle solo{i}</p></body></html>")
        msi = MeshServeIndex(s)
        pend = msi.issue_batch(["needle"], topk=48)
        first_k = pend.waves[0].out_k
        ((docids, scores, total, clustered, shash),) = \
            msi.collect_batch(pend)
        assert pend.waves[0].out_k > first_k   # escalation happened
        assert total == 185
        # 2 per big site + every unique-site doc survived the dedup
        assert len(docids) == 4 * 2 + 5
        assert clustered == 185 - 13
        mr = MeshResident(s)
        try:
            (h,) = mr.search_batch(["needle"], topk=13,
                                   with_snippets=False)
            (m,) = mr.serve_batch(["needle"], topk=13,
                                  with_snippets=False)
            # the big-site corpus ties scores ACROSS sites (same tf on
            # four sites), so compare the ranking order-independently
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted(key(r) for r in m.results) == \
                   sorted(key(r) for r in h.results)
            assert m.clustered == h.clustered
        finally:
            mr.stop()

    def test_twin_failover_zero_lost_queries(self, tmp_path_factory):
        """Kill one mesh shard's serving twin mid-serving: the next
        wave packs from the survivor via the loop's drain-before-
        refresh — same answers, no ticket lost, then whole-shard death
        only degrades."""
        from open_source_search_engine_tpu.parallel.sharded import \
            MeshResident
        s = ShardedCollection("fo", tmp_path_factory.mktemp("fo"),
                              n_shards=4, n_replicas=2)
        for _row in s.grid:
            for _c in _row:
                _c.conf.pqr_enabled = False
        for url, html in DISTINCT_DOCS.items():
            s.index_document(url, html)
        mr = MeshResident(s)
        try:
            base = mr.serve("apple banana", topk=5,
                            with_snippets=False)
            assert base.results and not base.degraded
            loop = mr.serve_loop()
            s.hostmap.mark_dead(0, 0)      # twin 1 takes shard 0 over
            after = mr.serve("apple banana", topk=5,
                             with_snippets=False)
            assert not after.degraded
            assert [(r.docid, round(r.score, 3))
                    for r in after.results] == \
                   [(r.docid, round(r.score, 3)) for r in base.results]
            assert loop.alive                      # zero lost queries
            s.hostmap.mark_dead(0, 1)      # whole shard 0 gone
            deg = mr.serve("apple banana", topk=5, with_snippets=False)
            assert deg.degraded
            assert deg.total_matches <= base.total_matches
            s.hostmap.mark_alive(0, 0)
            back = mr.serve("apple banana", topk=5, with_snippets=False)
            assert not back.degraded
            assert [r.docid for r in back.results] == \
                   [r.docid for r in base.results]
        finally:
            mr.stop()

    def test_generation_moves_on_write_and_on_death(self, dsc):
        from open_source_search_engine_tpu.parallel.sharded import \
            mesh_generation
        g0 = mesh_generation(dsc)
        assert mesh_generation(dsc) == g0      # stable when idle
        dsc.hostmap.mark_dead(0, 0)
        try:
            assert mesh_generation(dsc) != g0
        finally:
            dsc.hostmap.mark_alive(0, 0)
        assert mesh_generation(dsc) == g0

    def test_global_df_memoized(self, mr):
        mr.search("apple", with_snippets=False)
        memo1 = dict(mr._df_memo)
        assert memo1
        mr.search("apple banana", with_snippets=False)
        # apple's df came from the memo, not a re-walk
        assert all(mr._df_memo[k] == v for k, v in memo1.items())
