"""osselint gate — the tree must be invariant-clean, fast, and the
rules themselves must keep working.

This is the tier-1 single lint gate: it replaced the string-match
lints that used to live in test_oddments.py (urlopen-in-parallel,
off-plane TtlCache) and test_trace.py (bare g_stats.timed on the query
path) — those invariants are now AST rules in ``tools/osselint.py``,
exercised here against fixtures with known-violating and known-clean
code, plus seeded regressions for bugs this repo actually shipped
(the PR 4 ``id(conf)`` cache key).
"""

import re
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tools import osselint

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = ROOT / "tests" / "lint_fixtures"


def _lint_file(path: Path):
    return osselint.check_source(path.read_text(encoding="utf-8"),
                                 path.relative_to(ROOT).as_posix())


class TestTreeIsClean:
    def test_zero_unwaived_findings_under_budget(self):
        """The whole package + tools + tests lint clean in < 5s —
        osselint is cheap enough to gate every PR."""
        t0 = time.monotonic()
        files = osselint.iter_py_files(osselint.default_paths(ROOT),
                                       ROOT)
        findings = osselint.lint_files(files, ROOT)
        elapsed = time.monotonic() - t0
        assert not findings, "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.msg}" for f in findings)
        assert len(files) > 100, "scan missed most of the tree?"
        assert elapsed < 5.0, f"osselint took {elapsed:.1f}s (budget 5s)"

    def test_fixtures_are_excluded_from_tree_scan(self):
        files = osselint.iter_py_files(osselint.default_paths(ROOT),
                                       ROOT)
        assert not any("lint_fixtures" in f.parts for f in files)


class TestFixtures:
    def test_every_rule_fires_where_expected(self):
        """The violations fixture carries ``# EXPECT rule`` markers;
        the finding set must equal the marker set exactly — no missed
        violations, no spurious ones."""
        src = (FIXTURES / "violations_parallel.py").read_text()
        expected = set()
        for i, line in enumerate(src.splitlines(), start=1):
            for rule in re.findall(r"# EXPECT ([a-z\-]+)", line):
                expected.add((i, rule))
        got = {(f.line, f.rule) for f in
               _lint_file(FIXTURES / "violations_parallel.py")}
        assert got == expected, (
            f"missed: {sorted(expected - got)}\n"
            f"spurious: {sorted(got - expected)}")

    def test_all_rules_covered_by_fixture(self):
        """Every registered rule has at least one positive case."""
        src = (FIXTURES / "violations_parallel.py").read_text()
        covered = set(re.findall(r"# EXPECT ([a-z\-]+)", src))
        assert covered == osselint.RULE_NAMES

    def test_resident_fence_fixture_matches_markers(self):
        """The resident-loop fixture pins the device-sync rule's
        extended fence (device_put/asarray banned alongside the sync
        calls) to exact lines."""
        src = (FIXTURES / "violations_resident.py").read_text()
        expected = set()
        for i, line in enumerate(src.splitlines(), start=1):
            for rule in re.findall(r"# EXPECT ([a-z\-]+)", line):
                expected.add((i, rule))
        got = {(f.line, f.rule) for f in
               _lint_file(FIXTURES / "violations_resident.py")}
        assert got == expected, (
            f"missed: {sorted(expected - got)}\n"
            f"spurious: {sorted(got - expected)}")

    def test_clean_fixture_has_no_findings(self):
        findings = _lint_file(FIXTURES / "clean_parallel.py")
        assert not findings, [(f.line, f.rule) for f in findings]

    def test_waiver_suppresses_and_scopes_to_named_rule(self):
        src = ("# osselint: path=open_source_search_engine_tpu/"
               "parallel/w.py\n"
               "import time\n"
               "import threading\n"
               "_lock = threading.Lock()\n"
               "def f():\n"
               "    with _lock:\n"
               "        time.sleep(1)  # osselint: ignore["
               "blocking-under-lock] — fixture\n")
        assert osselint.check_source(src, "x.py") == []
        # a waiver for a DIFFERENT rule must not suppress
        wrong = src.replace("ignore[blocking-under-lock]",
                            "ignore[id-key]")
        found = osselint.check_source(wrong, "x.py")
        assert [f.rule for f in found] == ["blocking-under-lock"]


class TestSeededRegressions:
    """Re-lint the literal bug shapes this repo shipped before."""

    def test_pr4_id_conf_cache_key_is_caught(self):
        # the PR 4 SERP-cache bug: conf keyed by id() — address reuse
        # after GC aliases a dead conf to a live one
        src = ("def serp_key(conf, q):\n"
               "    return (q, id(conf))\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/sharded.py")
        assert [f.rule for f in found] == ["id-key"]

    def test_offplane_ttlcache_is_caught(self):
        src = ("from ..utils.ttlcache import TtlCache\n"
               "c = TtlCache(max_items=10)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/serve/server.py")
        assert [f.rule for f in found] == ["ttlcache-offplane"]
        # ...but the cache plane itself may construct them
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/cache/plane.py") == []

    def test_bare_urlopen_in_parallel_is_caught(self):
        src = ("import urllib.request\n"
               "def get(u):\n"
               "    return urllib.request.urlopen(u)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/cluster.py")
        assert {f.rule for f in found} == {"urllib-in-parallel"}
        # transport.py is the sanctioned courier
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/parallel/transport.py") == []

    def test_bare_stats_timed_on_query_path_is_caught(self):
        src = ("def search(q):\n"
               "    with g_stats.timed('query.total'):\n"
               "        pass\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py")
        assert [f.rule for f in found] == ["bare-stats-timed"]
        # outside the query path the plane is free to use it
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/utils/stats.py") == []


class TestCli:
    def test_violating_file_exits_nonzero_with_json(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--format=json",
             str(FIXTURES / "violations_parallel.py")],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        import json
        payload = json.loads(proc.stdout)
        assert payload["files"] == 1
        assert {f["rule"] for f in payload["findings"]} \
            == osselint.RULE_NAMES

    def test_clean_file_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint",
             str(FIXTURES / "clean_parallel.py")],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout

    def test_changed_mode_exits_nonzero_on_findings(self, tmp_path):
        """--changed over a scratch repo holding one violating file."""
        repo = tmp_path / "repo"
        pkg = repo / "open_source_search_engine_tpu" / "parallel"
        pkg.mkdir(parents=True)
        (pkg / "bad.py").write_text(
            "import urllib.request\n"
            "x = urllib.request.urlopen('http://example.com')\n")
        for args in (["git", "init", "-q"],
                     ["git", "add", "-A"],
                     ["git", "-c", "user.email=t@t", "-c",
                      "user.name=t", "commit", "-qm", "seed"]):
            subprocess.run(args, cwd=repo, check=True,
                           capture_output=True)
        # modify post-commit so it shows up as changed vs. HEAD
        (pkg / "bad.py").write_text(
            "import urllib.request\n"
            "y = urllib.request.urlopen('http://example.org')\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--changed",
             "--root", str(repo)],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "urllib-in-parallel" in proc.stdout
        # and a clean tree (nothing changed) exits 0
        subprocess.run(["git", "checkout", "-q", "--", "."], cwd=repo,
                       check=True, capture_output=True)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--changed",
             "--root", str(repo)],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout


class TestRuleMechanics:
    def test_nested_closure_not_flagged_as_blocking(self):
        """A closure DEFINED under a lock runs later — not a
        blocking-under-lock violation."""
        src = ("import time, threading\n"
               "_lock = threading.Lock()\n"
               "def f():\n"
               "    with _lock:\n"
               "        def later():\n"
               "            time.sleep(1)\n"
               "        return later\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/utils/x.py")
        assert [f.rule for f in found] == []

    def test_syntax_error_is_reported_not_raised(self):
        found = osselint.check_source(
            "def broken(:\n", "open_source_search_engine_tpu/x.py")
        assert [f.rule for f in found] == ["syntax-error"]

    def test_device_sync_allowed_at_the_boundary(self):
        src = "import jax\nv = jax.device_get(x)\n"
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/query/devindex.py") == []
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py")
        assert "syntax-error" not in {f.rule for f in found}
        assert [f.rule for f in found] == ["device-sync"]

    def test_device_staging_fenced_only_in_resident_loop(self):
        """device_put/asarray are legal almost everywhere — the
        extended fence applies to query/resident.py alone (its submit
        path must be a pure enqueue)."""
        src = "import jax\nv = jax.device_put(x)\n"
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/resident.py")
        assert [f.rule for f in found] == ["device-sync"]
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py") == []
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/query/devindex.py") == []
