"""osselint gate — the tree must be invariant-clean, fast, and the
rules themselves must keep working.

This is the tier-1 single lint gate: it replaced the string-match
lints that used to live in test_oddments.py (urlopen-in-parallel,
off-plane TtlCache) and test_trace.py (bare g_stats.timed on the query
path) — those invariants are now AST rules in ``tools/osselint.py``,
exercised here against fixtures with known-violating and known-clean
code, plus seeded regressions for bugs this repo actually shipped
(the PR 4 ``id(conf)`` cache key).
"""

import re
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tools import osselint

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = ROOT / "tests" / "lint_fixtures"


def _lint_file(path: Path):
    return osselint.check_source(path.read_text(encoding="utf-8"),
                                 path.relative_to(ROOT).as_posix())


class TestTreeIsClean:
    def test_zero_unwaived_findings_under_budget(self):
        """The whole package + tools + tests lint clean in < 5s —
        osselint is cheap enough to gate every PR."""
        t0 = time.monotonic()
        files = osselint.iter_py_files(osselint.default_paths(ROOT),
                                       ROOT)
        findings = osselint.lint_files(files, ROOT)
        elapsed = time.monotonic() - t0
        assert not findings, "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.msg}" for f in findings)
        assert len(files) > 100, "scan missed most of the tree?"
        assert elapsed < 5.0, f"osselint took {elapsed:.1f}s (budget 5s)"

    def test_fixtures_are_excluded_from_tree_scan(self):
        files = osselint.iter_py_files(osselint.default_paths(ROOT),
                                       ROOT)
        assert not any("lint_fixtures" in f.parts for f in files)


def _violation_fixtures():
    return sorted(FIXTURES.glob("violations_*.py"))


class TestFixtures:
    @pytest.mark.parametrize(
        "fixture", _violation_fixtures(), ids=lambda p: p.stem)
    def test_every_rule_fires_where_expected(self, fixture):
        """Each violations fixture carries ``# EXPECT rule`` markers;
        the finding set must equal the marker set exactly — no missed
        violations, no spurious ones."""
        expected = set()
        for i, line in enumerate(fixture.read_text().splitlines(),
                                 start=1):
            for rule in re.findall(r"# EXPECT ([a-z\-]+)", line):
                expected.add((i, rule))
        got = {(f.line, f.rule) for f in _lint_file(fixture)}
        assert got == expected, (
            f"missed: {sorted(expected - got)}\n"
            f"spurious: {sorted(got - expected)}")

    def test_all_rules_covered_by_fixture(self):
        """Every registered rule has at least one positive case
        somewhere in the violations fixtures."""
        covered = set()
        for fixture in _violation_fixtures():
            covered |= set(re.findall(r"# EXPECT ([a-z\-]+)",
                                      fixture.read_text()))
        assert covered == osselint.RULE_NAMES

    @pytest.mark.parametrize(
        "fixture", sorted(FIXTURES.glob("clean_*.py")),
        ids=lambda p: p.stem)
    def test_clean_fixture_has_no_findings(self, fixture):
        findings = _lint_file(fixture)
        assert not findings, [(f.line, f.rule) for f in findings]

    def test_waiver_suppresses_and_scopes_to_named_rule(self):
        src = ("# osselint: path=open_source_search_engine_tpu/"
               "parallel/w.py\n"
               "import time\n"
               "import threading\n"
               "_lock = threading.Lock()\n"
               "def f():\n"
               "    with _lock:\n"
               "        time.sleep(1)  # osselint: ignore["
               "blocking-under-lock] — fixture\n")
        assert osselint.check_source(src, "x.py") == []
        # a waiver for a DIFFERENT rule must not suppress
        wrong = src.replace("ignore[blocking-under-lock]",
                            "ignore[id-key]")
        found = osselint.check_source(wrong, "x.py")
        assert [f.rule for f in found] == ["blocking-under-lock"]


class TestSeededRegressions:
    """Re-lint the literal bug shapes this repo shipped before."""

    def test_pr4_id_conf_cache_key_is_caught(self):
        # the PR 4 SERP-cache bug: conf keyed by id() — address reuse
        # after GC aliases a dead conf to a live one
        src = ("def serp_key(conf, q):\n"
               "    return (q, id(conf))\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/sharded.py")
        assert [f.rule for f in found] == ["id-key"]

    def test_offplane_ttlcache_is_caught(self):
        src = ("from ..utils.ttlcache import TtlCache\n"
               "c = TtlCache(max_items=10)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/serve/server.py")
        assert [f.rule for f in found] == ["ttlcache-offplane"]
        # ...but the cache plane itself may construct them
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/cache/plane.py") == []

    def test_bare_urlopen_in_parallel_is_caught(self):
        src = ("import urllib.request\n"
               "def get(u):\n"
               "    return urllib.request.urlopen(u)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/cluster.py")
        assert {f.rule for f in found} == {"urllib-in-parallel"}
        # transport.py is the sanctioned courier
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/parallel/transport.py") == []

    def test_mesh_collective_outside_mesh_plane_is_caught(self):
        # the mesh-serving PR's layering rule: the Msg3a merge program
        # in parallel/sharded.py is the ONE home for ICI collectives —
        # a stray all_gather in the scorer couples the flat single-chip
        # kernel to the serving mesh shape
        src = ("import jax\n"
               "def merge(scores):\n"
               "    return jax.lax.all_gather(scores, 'shards')\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/scorer.py")
        assert [f.rule for f in found] == ["mesh-collective"]
        # ...but the mesh plane itself is the sanctioned home
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/parallel/sharded.py") == []

    def test_bare_stats_timed_on_query_path_is_caught(self):
        src = ("def search(q):\n"
               "    with g_stats.timed('query.total'):\n"
               "        pass\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py")
        assert [f.rule for f in found] == ["bare-stats-timed"]
        # outside the query path the plane is free to use it
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/utils/stats.py") == []

    def test_dynamic_stat_name_is_caught_and_table_fixes_it(self):
        # the literal pre-telemetry devindex shape: one time series
        # per observed wave count (devindex.wave_f1+f2_n5, _n7, ...)
        src = ("def collect(kinds, waves, t0, t1):\n"
               "    trace.record(\n"
               "        f'devindex.wave_{kinds}_n{len(waves)}',"
               " t0, t1)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/devindex.py")
        assert [f.rule for f in found] == ["stats-cardinality"]
        # the fix: bucket the count, look the name up from a literal
        # module-level table (f-strings OUTSIDE a stats call are fine)
        fixed = ("_WAVE_STAT = {n: f'devindex.wave_n{n}'\n"
                 "              for n in (1, 2, 4, 8)}\n"
                 "def collect(kinds, waves, t0, t1):\n"
                 "    stat = _WAVE_STAT.get(min(len(waves), 8))\n"
                 "    if stat is not None:\n"
                 "        trace.record(stat, t0, t1)\n")
        assert osselint.check_source(
            fixed,
            "open_source_search_engine_tpu/query/devindex.py") == []
        # the rule is scoped to the query plane
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/serve/server.py") == []

    def test_adhoc_timing_on_query_path_is_caught(self):
        # the literal devindex/engine shape the metrics-plane PR
        # removed: a perf_counter delta feeding g_stats directly, so
        # the interval never reaches the trace waterfall
        src = ("import time\n"
               "def collect(waves):\n"
               "    t0 = time.perf_counter()\n"
               "    out = fetch(waves)\n"
               "    g_stats.record_ms('devindex.wave',\n"
               "                      1000 * (time.perf_counter() - t0))\n"
               "    return out\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/devindex.py")
        assert [f.rule for f in found] == ["adhoc-timing"]
        # the stats plane itself measures however it likes
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/utils/stats.py") == []
        # monotonic budget arithmetic is not latency measurement
        mono = ("import time\n"
                "def hedge_wait(t0):\n"
                "    return time.monotonic() - t0\n")
        assert osselint.check_source(
            mono, "open_source_search_engine_tpu/parallel/cluster.py") \
            == []

    def test_proc_spawn_outside_fleet_plane_is_caught(self):
        # the literal pre-fleet shape: tests/test_cluster.py Popen'd
        # node processes by hand and killed them with raw os.kill —
        # orphans survived any test body that raised
        src = ("import os\n"
               "import subprocess\n"
               "def boot(argv, pid):\n"
               "    p = subprocess.Popen(argv)\n"
               "    os.kill(pid, 9)\n"
               "    return p\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/cluster.py")
        assert [f.rule for f in found] == ["proc-spawn", "proc-spawn"]
        found = osselint.check_source(src, "tests/test_cluster.py")
        assert [f.rule for f in found] == ["proc-spawn", "proc-spawn"]
        # the fleet and chaos planes ARE the sanctioned owners...
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/fleet.py") \
            == []
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/utils/chaos.py") == []
        # ...and tools/ scripts run outside the serving tree
        assert osselint.check_source(src, "tools/opsctl.py") == []
        # method calls on an owned handle stay legal everywhere
        legal = ("def stop(proc):\n"
                 "    proc.kill()\n"
                 "    proc.send_signal(15)\n")
        assert osselint.check_source(
            legal,
            "open_source_search_engine_tpu/parallel/cluster.py") == []

    def test_residency_bypass_outside_tenancy_plane_is_caught(self):
        # the literal pre-tenancy shape: sharded.py built a DeviceIndex
        # per shard and spun its own ResidentLoop — HBM buffers the
        # ResidencyManager never saw, so the tenant LRU couldn't evict
        # them, the 'device' label never billed them, and delColl
        # couldn't unserve them
        src = ("from ..query.devindex import DeviceIndex\n"
               "from ..query.resident import ResidentLoop\n"
               "def boot(coll):\n"
               "    di = DeviceIndex(coll)\n"
               "    return ResidentLoop(lambda: di, lambda: 0)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/sharded.py")
        assert [f.rule for f in found] == ["residency-bypass",
                                          "residency-bypass"]
        # the residency plane and the engine factories ARE the owners
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/serve/tenancy.py") == []
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py") == []
        # tests construct loops directly against fakes — out of scope
        assert osselint.check_source(src, "tests/test_resident.py") == []

    def test_host_sort_in_ingest_plane_is_caught(self):
        # the pre-PR-16 shape: _build_base's merge/docidx ran as host
        # numpy orderings (np.unique + argsort over the whole corpus) —
        # exactly the O(corpus) CPU stage the device ingest plane
        # removed. Re-introducing one in devbuild.py must fire.
        src = ("import numpy as np\n"
               "def docidx_of(docids):\n"
               "    uniq = np.unique(docids)\n"
               "    return np.searchsorted(uniq, docids)\n"
               "def order(keys):\n"
               "    return sorted(keys)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/build/devbuild.py")
        assert [f.rule for f in found] == ["host-sort", "host-sort"]
        # the host oracle pipeline keeps its numpy orderings
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/query/devindex.py") == []
        # and the device orderings the fence steers toward stay clean
        dev = ("import jax.numpy as jnp\n"
               "def order(keys):\n"
               "    return jnp.argsort(keys, stable=True)\n")
        assert osselint.check_source(
            dev, "open_source_search_engine_tpu/build/devbuild.py") == []


class TestJitSeededRegressions:
    """The literal jit hazard shapes the PR 7 rules caught (or
    deliberately exempt) in the live tree."""

    def test_unbucketed_local_k_is_caught_and_bucket_fixes_it(self):
        # the sharded.py bug: local_k derived from topk+offset and a
        # len() max — one shard_map compile per distinct page size
        src = ("import jax\n"
               "def _impl(x, local_k):\n"
               "    return x[:local_k]\n"
               "_shard = jax.jit(_impl, static_argnames=('local_k',))\n"
               "def dispatch(x, plans, topk, offset):\n"
               "    D = max(len(p) for p in plans)\n"
               "    k = min(topk + offset, D)\n"
               "    return _shard(x, local_k=k)\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/parallel/mesh.py")
        assert [f.rule for f in found] == ["jit-unstable-static"]
        fixed = src.replace("k = min(topk + offset, D)",
                            "k = min(_bucket(topk + offset), D)")
        assert osselint.check_source(
            fixed,
            "open_source_search_engine_tpu/parallel/mesh.py") == []

    def test_cached_jit_factory_is_exempt(self):
        # devcheck._checked: an lru_cache'd factory mints one wrapper
        # per key — the safe jit-in-body idiom
        src = ("import functools\n"
               "import jax\n"
               "@functools.lru_cache(maxsize=None)\n"
               "def _checked(name):\n"
               "    return jax.jit(lambda x: x)\n")
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/query/devcheck.py") \
            == []
        bare = src.replace(
            "@functools.lru_cache(maxsize=None)\n", "")
        found = osselint.check_source(
            bare, "open_source_search_engine_tpu/query/devcheck.py")
        assert [f.rule for f in found] == ["jit-in-body"]

    def test_donated_rebind_idiom_is_exempt(self):
        # devindex._build_delta: self.d_X = _write_tail(self.d_X, ...)
        # rebinds the donated buffer — safe; reading it without the
        # rebind is the hazard
        src = ("import jax\n"
               "_wt = jax.jit(lambda b, v: b, donate_argnums=(0,))\n"
               "class D:\n"
               "    def build(self, v):\n"
               "        self.d_pos = _wt(self.d_pos, v)\n"
               "        return self.d_pos\n")
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/query/devindex.py") \
            == []
        bad = src.replace("self.d_pos = _wt(self.d_pos, v)",
                          "out = _wt(self.d_pos, v)")
        found = osselint.check_source(
            bad, "open_source_search_engine_tpu/query/devindex.py")
        assert [f.rule for f in found] == ["jit-donated-reuse"]


class TestCli:
    def test_violating_files_exit_nonzero_with_json(self):
        fixtures = _violation_fixtures()
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--format=json"]
            + [str(f) for f in fixtures],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        import json
        payload = json.loads(proc.stdout)
        assert payload["files"] == len(fixtures)
        assert {f["rule"] for f in payload["findings"]} \
            == osselint.RULE_NAMES

    def test_clean_file_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint",
             str(FIXTURES / "clean_parallel.py")],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout

    def test_changed_mode_exits_nonzero_on_findings(self, tmp_path):
        """--changed over a scratch repo holding one violating file."""
        repo = tmp_path / "repo"
        pkg = repo / "open_source_search_engine_tpu" / "parallel"
        pkg.mkdir(parents=True)
        (pkg / "bad.py").write_text(
            "import urllib.request\n"
            "x = urllib.request.urlopen('http://example.com')\n")
        for args in (["git", "init", "-q"],
                     ["git", "add", "-A"],
                     ["git", "-c", "user.email=t@t", "-c",
                      "user.name=t", "commit", "-qm", "seed"]):
            subprocess.run(args, cwd=repo, check=True,
                           capture_output=True)
        # modify post-commit so it shows up as changed vs. HEAD
        (pkg / "bad.py").write_text(
            "import urllib.request\n"
            "y = urllib.request.urlopen('http://example.org')\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--changed",
             "--root", str(repo)],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "urllib-in-parallel" in proc.stdout
        # and a clean tree (nothing changed) exits 0
        subprocess.run(["git", "checkout", "-q", "--", "."], cwd=repo,
                       check=True, capture_output=True)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--changed",
             "--root", str(repo)],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout

    def test_changed_mode_handles_rename_and_delete(self, tmp_path):
        """A staged rename must be linted under its NEW path and a
        staged delete must contribute nothing — neither may crash the
        diff parse (R/C rows carry two paths, D rows a missing file)."""
        repo = tmp_path / "repo"
        pkg = repo / "open_source_search_engine_tpu" / "parallel"
        pkg.mkdir(parents=True)
        (pkg / "bad.py").write_text(
            "import urllib.request\n"
            "x = urllib.request.urlopen('http://example.com')\n")
        (pkg / "gone.py").write_text("import urllib.request\n"
                                     "y = 1\n")
        for args in (["git", "init", "-q"],
                     ["git", "add", "-A"],
                     ["git", "-c", "user.email=t@t", "-c",
                      "user.name=t", "commit", "-qm", "seed"]):
            subprocess.run(args, cwd=repo, check=True,
                           capture_output=True)
        subprocess.run(["git", "mv", str(pkg / "bad.py"),
                        str(pkg / "moved.py")], cwd=repo, check=True,
                       capture_output=True)
        subprocess.run(["git", "rm", "-q", str(pkg / "gone.py")],
                       cwd=repo, check=True, capture_output=True)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.osselint", "--changed",
             "--format=json", "--root", str(repo)],
            cwd=ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1, proc.stderr
        import json
        payload = json.loads(proc.stdout)
        paths = {f["path"] for f in payload["findings"]}
        assert paths == {
            "open_source_search_engine_tpu/parallel/moved.py"}
        assert {f["rule"] for f in payload["findings"]} \
            == {"urllib-in-parallel"}


class TestCheckGate:
    def test_check_sh_lint_gate_passes_on_tree(self):
        """tools/check.sh --lint-only (tree lint + fixture sanity) is
        the one-command gate; --lint-only stops before the pytest
        slice so this test doesn't recurse into itself."""
        proc = subprocess.run(
            ["bash", str(ROOT / "tools" / "check.sh"), "--lint-only"],
            cwd=ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "lint gate OK" in proc.stdout


class TestRuleMechanics:
    def test_nested_closure_not_flagged_as_blocking(self):
        """A closure DEFINED under a lock runs later — not a
        blocking-under-lock violation."""
        src = ("import time, threading\n"
               "_lock = threading.Lock()\n"
               "def f():\n"
               "    with _lock:\n"
               "        def later():\n"
               "            time.sleep(1)\n"
               "        return later\n")
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/utils/x.py")
        assert [f.rule for f in found] == []

    def test_syntax_error_is_reported_not_raised(self):
        found = osselint.check_source(
            "def broken(:\n", "open_source_search_engine_tpu/x.py")
        assert [f.rule for f in found] == ["syntax-error"]

    def test_device_sync_allowed_at_the_boundary(self):
        src = "import jax\nv = jax.device_get(x)\n"
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/query/devindex.py") == []
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py")
        assert "syntax-error" not in {f.rule for f in found}
        assert [f.rule for f in found] == ["device-sync"]

    def test_device_staging_fenced_only_in_resident_loop(self):
        """device_put/asarray are legal almost everywhere — the
        extended fence applies to query/resident.py alone (its submit
        path must be a pure enqueue)."""
        src = "import jax\nv = jax.device_put(x)\n"
        found = osselint.check_source(
            src, "open_source_search_engine_tpu/query/resident.py")
        assert [f.rule for f in found] == ["device-sync"]
        assert osselint.check_source(
            src, "open_source_search_engine_tpu/query/engine.py") == []
        assert osselint.check_source(
            src,
            "open_source_search_engine_tpu/query/devindex.py") == []
