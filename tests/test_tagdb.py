"""Tagdb + SiteGetter tests.

Reference behaviors pinned (``Tagdb.h:323``, ``SiteGetter.cpp``):
tag set/get/remove with newest-wins replacement; TagRec container walk
(subdirectory site → host → registrable domain); ``manualban`` blocks
indexing and the frontier; ``sitepathdepth`` widens the site boundary so
user directories on a hosting host cluster as distinct sites; restart
persistence rides the normal Rdb save/load path.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index import clusterdb
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.index.tagdb import Tagdb
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.spider.scheduler import SpiderScheduler
from open_source_search_engine_tpu.utils import ghash


@pytest.fixture
def coll(tmp_path):
    return Collection("t", tmp_path)


def test_set_get_remove_roundtrip(tmp_path):
    td = Tagdb(tmp_path)
    assert td.empty
    td.set_tag("example.com", "note", "seed site")
    assert not td.empty
    assert td.tags_for_site("example.com") == {"note": "seed site"}
    td.set_tag("example.com", "note", "updated")  # newest wins
    assert td.tags_for_site("example.com")["note"] == "updated"
    td.remove_tag("example.com", "note")
    assert td.tags_for_site("example.com") == {}


def test_tag_rec_container_walk(tmp_path):
    td = Tagdb(tmp_path)
    td.set_tag("example.co.uk", "a", "domain")
    td.set_tag("www.example.co.uk", "a", "host")
    td.set_tag("www.example.co.uk", "b", "host-only")
    # narrowest container wins for a, domain fills in the rest
    assert td.get_tag("http://www.example.co.uk/x", "a") == "host"
    assert td.get_tag("http://other.example.co.uk/x", "a") == "domain"
    rec = td.tag_rec("http://www.example.co.uk/p")
    assert rec == {"a": "host", "b": "host-only"}


def test_site_of_path_depth(tmp_path):
    td = Tagdb(tmp_path)
    assert td.site_of("http://users.example.com/~alice/page.html") == \
        "users.example.com"
    td.set_tag("users.example.com", "sitepathdepth", 1)
    assert td.site_of("http://users.example.com/~alice/page.html") == \
        "users.example.com/~alice/"
    assert td.site_of("http://users.example.com/~bob/") == \
        "users.example.com/~bob/"
    assert td.site_of("http://users.example.com/") == "users.example.com"
    # a trailing FILENAME segment never counts as a site directory
    # (SiteGetter truncates at directory boundaries)
    assert td.site_of("http://users.example.com/page.html") == \
        "users.example.com"
    # index_gate returns the same answers in one walk
    from open_source_search_engine_tpu.utils.url import normalize
    u = normalize("http://users.example.com/~alice/page.html")
    assert td.index_gate(u) == (False, "users.example.com/~alice/", None)


def test_persistence(tmp_path):
    td = Tagdb(tmp_path)
    td.set_tag("example.com", "manualban", 1)
    td.save()
    td2 = Tagdb(tmp_path)
    assert td2.is_banned("http://spam.example.com/page")
    assert not td2.is_banned("http://clean.org/")


def test_manualban_blocks_indexing_and_removes(coll):
    html = "<html><title>spam</title><body>buy pills now</body></html>"
    ml = docproc.index_document(coll, "http://spam.test/p", html)
    assert ml is not None and coll.num_docs == 1
    coll.tagdb.set_tag("spam.test", "manualban", 1)
    # re-injection is refused AND the existing doc is dropped
    assert docproc.index_document(coll, "http://spam.test/p", html) is None
    assert coll.num_docs == 0
    assert docproc.get_document(coll, url="http://spam.test/p") is None
    r = engine.search(coll, "pills")
    assert r.total_matches == 0


def test_manualban_blocks_frontier(coll):
    coll.tagdb.set_tag("spam.test", "manualban", 1)
    sched = SpiderScheduler(banned=coll.tagdb.is_banned)
    assert not sched.add_url("http://spam.test/x")
    assert sched.add_url("http://ok.test/x")


def test_siterank_override(coll):
    coll.tagdb.set_tag("boosted.test", "siterank", 9)
    ml = docproc.index_document(
        coll, "http://boosted.test/p",
        "<html><title>t</title><body>boosted words</body></html>")
    from open_source_search_engine_tpu.index import posdb
    f = posdb.unpack(ml.posdb_keys)
    assert (f["siterank"] == 9).all()


def test_sitepathdepth_clusters_user_dirs_separately(coll):
    """Two user dirs on one host = two sites: distinct clusterdb
    sitehashes, and site clustering no longer folds them together."""
    coll.tagdb.set_tag("users.test", "sitepathdepth", 1)
    mls = []
    for user in ("alice", "bob"):
        for i in range(3):
            mls.append(docproc.index_document(
                coll, f"http://users.test/~{user}/p{i}",
                f"<html><title>{user} {i}</title><body>"
                f"<p>shared topic words plus {user} page number{i}.</p>"
                "</body></html>"))
    sites = {ml.site for ml in mls}
    assert sites == {"users.test/~alice/", "users.test/~bob/"}
    hashes = {int(clusterdb.unpack_key(
        ml.clusterdb_key.reshape(1))["sitehash"][0]) for ml in mls}
    assert len(hashes) == 2
    # site: fielded search honors the boundary (all 3 match; site
    # clustering then hides the third — one site, MAX_PER_SITE=2)
    r = engine.search(coll, "site:users.test/~alice/ topic")
    assert r.total_matches == 3 and len(r.results) == 2 \
        and r.clustered == 1
    assert all(res.url.startswith("http://users.test/~alice/")
               for res in r.results)
    # clustering keeps MAX_PER_SITE per user dir, not per host
    r2 = engine.search(coll, "shared topic")
    assert len(r2.results) == 4  # 2 per site × 2 sites
    # tombstones regenerate with the stored boundary: removal is clean
    docproc.remove_document(coll, "http://users.test/~alice/p0")
    r3 = engine.search(coll, "site:users.test/~alice/ topic")
    assert {res.url for res in r3.results} == {
        f"http://users.test/~alice/p{i}" for i in (1, 2)}


def test_sharded_tagdb_ban_and_boundary(tmp_path):
    """The sharded path honors the same tagdb semantics: tags route to
    the site's owning shard; bans refuse sharded injects; boundaries
    flow into the sharded clusterdb records."""
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("t", tmp_path, n_shards=2)
    sc.tagdb.set_tag("spam.test", "manualban", 1)
    sc.tagdb.set_tag("users.test", "sitepathdepth", 1)
    assert sc.index_document(
        "http://spam.test/p",
        "<html><title>x</title><body>junk</body></html>") is None
    assert sc.num_docs == 0
    ml = sc.index_document(
        "http://users.test/~alice/p0",
        "<html><title>a</title><body>alpha words</body></html>")
    assert ml is not None and ml.site == "users.test/~alice/"
    # removal tombstones cleanly under the frozen boundary
    assert sc.remove_document("http://users.test/~alice/p0") is not None
    assert sc.num_docs == 0


def test_cluster_rpc_banned_does_not_wedge_writes(tmp_path):
    """A banned inject must ACK (ok) at the RPC layer, or the ordered
    per-host write queue would retry it forever and block every
    subsequent write to that shard."""
    from open_source_search_engine_tpu.parallel.cluster import \
        ShardNodeServer
    node = ShardNodeServer(tmp_path)
    node.coll.tagdb.set_tag("spam.test", "manualban", 1)
    out = node.handle("/rpc/index",
                      {"url": "http://spam.test/p", "content": "<p>x</p>"})
    assert out["ok"] is True and out.get("banned") is True
    out2 = node.handle("/rpc/index",
                       {"url": "http://ok.test/p",
                        "content": "<html><body>fine</body></html>"})
    assert out2["ok"] is True and "docid" in out2


def test_shard_of_tagdb_keys_is_sitehash_stable(tmp_path):
    """Tagdb keys carry the sitehash in n1 so a future sharded tagdb
    routes by site like linkdb routes by linkee site."""
    from open_source_search_engine_tpu.index.tagdb import pack_key
    k1 = pack_key("example.com", "a")
    k2 = pack_key("example.com", "b")
    assert int(k1["n1"]) == int(k2["n1"]) == ghash.hash64("example.com")


def test_deep_site_tag_roundtrip(tmp_path):
    """A tag set on a site string deeper than the probe cap (which
    site_of can itself produce when sitepathdepth >= 4) must round-trip
    through get_tag/is_banned — the exact normalized string probes
    first."""
    from open_source_search_engine_tpu.index.tagdb import (TAG_MANUAL_BAN,
                                                           Tagdb)
    t = Tagdb(tmp_path)
    deep = "host.test/a/b/c/d/"
    t.set_tag(deep, TAG_MANUAL_BAN, True)
    assert t.get_tag(deep, TAG_MANUAL_BAN) is True
    assert t.is_banned(deep)
    assert t.is_banned("http://host.test/a/b/c/d/page.html")
    assert not t.is_banned("http://host.test/a/b/c/other.html")
