"""Fleet plane tests — REAL OS node processes, fast enough for tier 1.

Covers the FleetManager contract end to end across a true process
boundary: spawn + readiness + seat identity, live parm broadcast
(0x3f semantics: applied everywhere, no restart), SIGKILL + journal
replay rejoin, drain-then-restart through the node admission gate,
and the teardown-hygiene guarantee (zero surviving child pids, even
when the test body raises).

Every fixture teardown asserts ``surviving_pids() == []`` — the one
invariant that keeps CI boxes free of orphaned node processes.
"""

import pytest

from open_source_search_engine_tpu.parallel.fleet import FleetManager
from tests.polling import wait_until

DOC = ("<html><head><title>Fleet survivor</title></head><body>"
       "<p>fleet durability words ftoken{i}.</p></body></html>")


def _index(fm, addr, i):
    out = fm.transport.request(
        addr, "/rpc/index",
        {"url": f"http://fleet.test/{i}", "content": DOC.format(i=i)},
        timeout=60.0)
    assert out["ok"], out
    return out


@pytest.fixture
def fleet(tmp_path):
    """One shard, two twins, no supervisor — the tests decide who dies
    and who comes back."""
    fm = FleetManager(tmp_path / "fleet", n_shards=1, n_replicas=2,
                      chaos_seed=5, supervise=False)
    try:
        fm.start_all()
        yield fm
    finally:
        fm.shutdown()
        assert fm.surviving_pids() == []


def test_spawn_readiness_and_identity(fleet):
    fm = fleet
    pids = set()
    for r in range(fm.n_replicas):
        ping = fm.wait_ready(0, r)
        assert ping["ok"] and ping["docs"] == 0
        assert (ping["shard"], ping["replica"]) == (0, r)
        assert ping["draining"] is False
        pids.add(ping["pid"])
    assert len(pids) == fm.n_replicas  # distinct real processes
    # children are spawned with the chaos seed (seams armed, ambient
    # rate 0) and the serialized cluster map
    env = fm._child_env()
    assert env["OSSE_CHAOS"] == "5"
    assert env["OSSE_CHAOS_RATE"] == "0"
    assert fm.hosts_path.read_text()  # hosts.conf handed to every node


def test_parm_broadcast_applies_on_every_node_without_restart(fleet):
    fm = fleet
    pids_before = dict(fm.pids())
    replies = fm.broadcast_parms({"spider_delay_ms": 2718})
    assert len(replies) == fm.n_shards * fm.n_replicas
    for addr, r in replies.items():
        assert r is not None and r["ok"], (addr, r)
        assert "spider_delay_ms" in r["applied"]
        assert r["pid"] == pids_before[
            next(sr for sr in fm.pids()
                 if fm.addr(*sr) == addr)]
    for s in range(fm.n_shards):
        for r in range(fm.n_replicas):
            conf = fm.transport.request(fm.addr(s, r), "/rpc/conf",
                                        {}, timeout=10.0)
            assert conf["conf"]["spider_delay_ms"] == 2718
    assert dict(fm.pids()) == pids_before  # applied live, no restart


def test_sigkill_journal_replay_rejoin(fleet):
    fm = fleet
    for i in range(3):  # write to BOTH twins (the client's fan-out)
        _index(fm, fm.addr(0, 0), i)
        _index(fm, fm.addr(0, 1), i)
    # kill -9 replica 0: no save, no atexit — journals only
    fm.kill(0, 0)
    wait_until(lambda: not fm.alive(0, 0), timeout=10.0,
               desc="node dead after SIGKILL")
    fm.start_node(0, 0, wait=True)
    ping0 = fm.wait_ready(0, 0)
    ping1 = fm.wait_ready(0, 1)
    assert ping0["docs"] == ping1["docs"] == 3  # replay conserved all
    out = fm.transport.request(fm.addr(0, 0), "/rpc/search",
                               {"q": "fleet durability", "topk": 5},
                               timeout=60.0)
    assert out["ok"] and out["total"] == 3
    stats = fm.transport.request(fm.addr(0, 0), "/rpc/stats", {},
                                 timeout=10.0)
    assert stats["ok"] and "stats" in stats


def test_drain_then_restart_through_admission_gate(fleet):
    fm = fleet
    _index(fm, fm.addr(0, 0), 7)
    out = fm.transport.request(fm.addr(0, 0), "/rpc/drain",
                               {"timeout_s": 5.0}, timeout=10.0)
    assert out["ok"] and out["drained"], out
    ping = fm.transport.request(fm.addr(0, 0), "/rpc/ping", {},
                                timeout=10.0)
    assert ping["draining"] is True
    # the gate is closed: data-plane RPCs shed instead of admitting
    shed = fm.transport.request(fm.addr(0, 0), "/rpc/search",
                                {"q": "fleet", "topk": 5},
                                timeout=10.0)
    assert shed["ok"] is False and shed["shed"] == "draining"
    # orderly stop (SIGTERM → save) and rebirth on the same dir
    assert fm.stop_node(0, 0) is not None
    fm.start_node(0, 0, wait=True)
    ping = fm.wait_ready(0, 0)
    assert ping["draining"] is False  # fresh gate
    assert ping["docs"] == 1          # checkpointed state intact


def test_teardown_reaps_even_when_the_body_raises(tmp_path):
    fm = FleetManager(tmp_path / "f2", n_shards=1, n_replicas=1,
                      supervise=False)
    with pytest.raises(RuntimeError, match="boom"):
        with fm:
            assert fm.alive(0, 0)
            raise RuntimeError("boom")
    assert fm.surviving_pids() == []


def test_atexit_reaper_kills_the_process_group(tmp_path):
    """The last-resort finalizer: simulate an owner that never reaches
    shutdown() — _atexit_reap() alone must leave no survivors."""
    fm = FleetManager(tmp_path / "f3", n_shards=1, n_replicas=1,
                      supervise=False)
    fm.start_all()
    assert fm.surviving_pids()
    fm._atexit_reap()
    wait_until(lambda: fm.surviving_pids() == [], timeout=10.0,
               desc="atexit reaper cleared every child")
    fm.shutdown()  # idempotent
    assert fm.surviving_pids() == []
