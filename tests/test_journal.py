"""Msg4 write-journal parity: no acknowledged add is ever lost.

The reference journals every buffered add (``Msg4.cpp:86,115``,
``addsinprogress.dat``) and replays on start. Here EVERY Rdb carries a
write-ahead journal (``rdblite.Rdb._journal_append``): appended before
the memtable applies, replayed on open, truncated when a dump/save
makes it redundant. The headline test kill -9s a serving node right
after an inject returned HTTP 200 and proves the document — postings,
titlerec, clusterdb, fielddb — survives the restart with NO save().
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from open_source_search_engine_tpu.index import posdb, rdblite

REPO = str(__import__("pathlib").Path(__file__).resolve().parent.parent)


def _mk(tmp_path, **kw):
    return rdblite.Rdb("t", tmp_path, posdb.KEY_DTYPE, **kw)


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    return posdb.pack(termid=rng.integers(1, 1 << 40, n),
                      docid=rng.integers(1, 1 << 30, n),
                      wordpos=rng.integers(0, 1000, n))


class TestRdbJournal:
    def test_replay_after_unclean_close(self, tmp_path):
        r = _mk(tmp_path)
        k = _keys(100)
        r.add(k)
        # NO save(), no dump — simulate kill -9 by just dropping the
        # object and reopening the directory
        r2 = _mk(tmp_path)
        got = r2.get_list(np.sort(k, order=("n2", "n1", "n0"))[0],
                          np.sort(k, order=("n2", "n1", "n0"))[-1])
        assert len(got) == 100

    def test_blobs_replay(self, tmp_path):
        r = rdblite.Rdb("b", tmp_path, posdb.KEY_DTYPE, has_data=True)
        k = _keys(3, seed=1)
        r.add(k, [b"alpha", b"", b"\x00bin\xff" * 10])
        r2 = rdblite.Rdb("b", tmp_path, posdb.KEY_DTYPE, has_data=True)
        b = r2.mem.batch()
        assert len(b) == 3
        assert sorted(b.payloads()) == sorted(
            [b"alpha", b"", b"\x00bin\xff" * 10])

    def test_tombstones_replay(self, tmp_path):
        r = _mk(tmp_path)
        k = _keys(10, seed=2)
        r.add(k)
        r.dump()               # journal truncates here
        assert not (r.dir / "addsinprogress.bin").exists()
        r.delete(k[:4])
        r2 = _mk(tmp_path)
        ks = np.sort(k, order=("n2", "n1", "n0"))
        got = r2.get_list(ks[0], ks[-1])
        assert len(got) == 6   # tombstones annihilated 4

    def test_torn_tail_stops_replay(self, tmp_path):
        r = _mk(tmp_path)
        r.add(_keys(50, seed=3))
        r.add(_keys(50, seed=4))
        jp = r.dir / "addsinprogress.bin"
        data = jp.read_bytes()
        jp.write_bytes(data[:-7])  # tear the last batch
        r2 = _mk(tmp_path)
        assert len(r2.mem.batch()) == 50  # first batch intact

    def test_torn_tail_truncates_so_later_batches_survive(self, tmp_path):
        # tear → restart → MORE acknowledged adds → crash again: the
        # post-restart adds must not be stranded behind the torn batch
        r = _mk(tmp_path)
        r.add(_keys(50, seed=30))
        jp = r.dir / "addsinprogress.bin"
        jp.write_bytes(jp.read_bytes()[:-7])
        r2 = _mk(tmp_path)          # replay truncates the torn tail
        r2.add(_keys(10, seed=31))  # acknowledged after restart
        r3 = _mk(tmp_path)          # second crash
        assert len(r3.mem.batch()) == 10

    def test_save_crash_window_keeps_old_checkpoint(self, tmp_path):
        # simulate a crash between publishing saved.new and the swap:
        # whichever checkpoint exists must fully cover the records
        import shutil as sh
        r = _mk(tmp_path)
        r.add(_keys(20, seed=32))
        r.save()
        # hand-craft the crash state: saved.new complete, saved removed
        sh.copytree(r.dir / "saved", r.dir / "saved.new")
        sh.rmtree(r.dir / "saved")
        r2 = _mk(tmp_path)
        assert len(r2.mem.batch()) == 20

    def test_save_truncates_and_no_double_apply(self, tmp_path):
        r = _mk(tmp_path)
        k = _keys(20, seed=5)
        r.add(k)
        r.save()
        assert not (r.dir / "addsinprogress.bin").exists()
        r.add(_keys(5, seed=6))  # journaled after the checkpoint
        r2 = _mk(tmp_path)
        assert len(r2.mem.batch()) == 25  # 20 from saved + 5 replayed


class TestKillMinus9ZeroLoss:
    """The VERDICT contract: kill -9 after HTTP 200 loses nothing."""

    def test_inject_kill9_restart(self, tmp_path):
        node_dir = str(tmp_path / "node")
        # port 0: the OS picks a free port and the child reports it on
        # stdout — a hardcoded port collides with parallel test runs
        code = (
            "import sys; sys.path.insert(0, %r); "
            "from open_source_search_engine_tpu.serve.server import "
            "SearchHTTPServer; "
            "s = SearchHTTPServer(%r, port=0); s.start(); "
            "import time; "
            "print('UP', s.port, flush=True); time.sleep(600)"
            % (REPO, node_dir))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # the serve-layer child shape predates the fleet plane and pins
        # the single-server durability story; the node-level twin of
        # this scenario (below) rides FleetManager
        proc = subprocess.Popen(  # osselint: ignore[proc-spawn] — legacy serve-layer child, see comment above
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE)
        try:
            line = proc.stdout.readline().decode()  # blocks until UP
            assert line.startswith("UP "), \
                f"child died before serving: {line!r}"
            port = int(line.split()[1])
            t0 = time.time()
            while time.time() - t0 < 90:
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/admin/stats",
                        timeout=1.0)
                    break
                except Exception:
                    time.sleep(0.3)
            html = (b"<html><head><title>Survivor page</title></head>"
                    b"<body><p>durability words survive kill nine "
                    b"journal replay test.</p></body></html>")
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/inject"
                "?url=http://kill.test/doc1", data=html)
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.status == 200  # the ACK
        finally:
            # kill -9: no atexit, no save(), no dump
            proc.send_signal(signal.SIGKILL)
            proc.wait()

        # restart on the same directory IN-PROCESS and search
        from open_source_search_engine_tpu.build.docproc import \
            get_document
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.query import engine
        coll = Collection("main", node_dir)
        res = engine.search(coll, "durability journal", topk=5)
        assert res.total_matches == 1
        assert res.results[0].url == "http://kill.test/doc1"
        rec = get_document(coll, url="http://kill.test/doc1")
        assert rec is not None and rec["title"] == "Survivor page"


class TestKillMinus9NodeProcess:
    """The same contract one level up: a REAL ``node`` process (fleet
    plane spawn) SIGKILLed mid-inject restarts from its checkpoint dir,
    replays BOTH journal layers, and serves every acked write —
    ``/rpc/stats`` answers clean afterwards."""

    def test_node_kill9_journal_replay(self, tmp_path):
        from open_source_search_engine_tpu.parallel.fleet import \
            FleetManager

        docs = {
            f"http://kill.test/n{i}": (
                f"<html><head><title>Node survivor {i}</title></head>"
                f"<body><p>node durability words survive kill nine "
                f"ntoken{i}.</p></body></html>")
            for i in range(4)
        }
        with FleetManager(tmp_path / "fleet", n_shards=1, n_replicas=1,
                          supervise=False) as fm:
            addr = fm.addr(0, 0)
            for url, html in docs.items():
                out = fm.transport.request(
                    addr, "/rpc/index",
                    {"url": url, "content": html}, timeout=60.0)
                assert out["ok"], out          # the ACK
            # kill -9: no save(), no atexit — only the journals remain
            fm.kill(0, 0)
            from tests.polling import wait_until
            wait_until(lambda: not fm.alive(0, 0), timeout=10.0,
                       desc="node dead after SIGKILL")

            # restart on the same checkpoint dir; replay must restore
            # every acked write (count AND content)
            fm.start_node(0, 0, wait=True)
            ping = fm.wait_ready(0, 0)
            assert ping["docs"] == len(docs), ping
            out = fm.transport.request(
                addr, "/rpc/search",
                {"q": "node durability", "topk": 10}, timeout=60.0)
            assert out["ok"] and out["total"] == len(docs), out
            stats = fm.transport.request(addr, "/rpc/stats", {},
                                         timeout=10.0)
            assert stats["ok"] and "stats" in stats
        assert fm.surviving_pids() == []
