"""Native C++ core tests — build, load, and bit-parity with the numpy
fallback (the ``rdbtest``/``mergetest`` component binaries of the
reference, SURVEY §4.3, as pytest)."""

import numpy as np
import pytest

from open_source_search_engine_tpu import native
from open_source_search_engine_tpu.index import posdb, rdblite


@pytest.fixture(scope="module")
def lib():
    if not native.available():
        pytest.skip("g++ unavailable — numpy fallback covered elsewhere")
    return native.get_lib()


def _random_keys(n, seed, frac_tombstone=0.2):
    rng = np.random.default_rng(seed)
    keys = posdb.pack(
        termid=rng.integers(0, 50, n), docid=rng.integers(0, 200, n),
        wordpos=rng.integers(0, 1000, n),
        delbit=(rng.random(n) > frac_tombstone).astype(int))
    return keys[rdblite.key_sort_order(keys)]


class TestNativeCore:
    def test_builds_and_loads(self, lib):
        assert lib is not None

    def test_searchsorted_matches_numpy_fallback(self, lib, monkeypatch):
        keys = _random_keys(500, seed=1)
        probes = _random_keys(40, seed=2)
        nat = rdblite.searchsorted_keys(keys, probes, "left")
        natr = rdblite.searchsorted_keys(keys, probes, "right")
        monkeypatch.setattr(native, "available", lambda: False)
        ref = rdblite.searchsorted_keys(keys, probes, "left")
        refr = rdblite.searchsorted_keys(keys, probes, "right")
        np.testing.assert_array_equal(nat, ref)
        np.testing.assert_array_equal(natr, refr)

    @pytest.mark.parametrize("keep_tombstones", [False, True])
    def test_merge_matches_numpy_fallback(self, lib, monkeypatch,
                                          keep_tombstones):
        runs = [_random_keys(300, seed=s) for s in range(4)]
        batches = [rdblite.RecordBatch(r) for r in runs]
        nat = rdblite.merge_batches(batches, keep_tombstones)
        monkeypatch.setattr(native, "available", lambda: False)
        ref = rdblite.merge_batches(batches, keep_tombstones)
        assert len(nat) == len(ref)
        np.testing.assert_array_equal(
            nat.keys.view(np.uint8).reshape(-1),
            ref.keys.view(np.uint8).reshape(-1))

    def test_merge_annihilation(self, lib):
        pos = posdb.pack(termid=7, docid=42, wordpos=5, delbit=1)
        neg = posdb.pack(termid=7, docid=42, wordpos=5, delbit=0)
        keep = posdb.pack(termid=7, docid=43, wordpos=9, delbit=1)
        old = rdblite.RecordBatch(np.stack([pos, keep])[
            rdblite.key_sort_order(np.stack([pos, keep]))])
        new = rdblite.RecordBatch(np.atleast_1d(neg))
        merged = rdblite.merge_batches([old, new], keep_tombstones=False)
        assert len(merged) == 1
        assert posdb.unpack(merged.keys)["docid"][0] == 42 or \
            posdb.unpack(merged.keys)["docid"][0] == 43
        # the tombstone must have killed docid 42's posting
        assert int(posdb.unpack(merged.keys)["docid"][0]) == 43


@pytest.mark.slow
class TestSanitizerParity:
    """ASan+UBSan-instrumented natives pass the same parity checks
    (OSSE_NATIVE_SAN=1 plane): memory errors / UB in rdbcore.cpp or
    doccore.cpp abort the driver instead of corrupting an index."""

    def test_asan_ubsan_parity_clean(self):
        import subprocess
        import sys
        from pathlib import Path
        root = Path(__file__).resolve().parent.parent
        from tools.native_san_check import _sanitizer_libs
        if not _sanitizer_libs():
            pytest.skip("libasan/libubsan not found by g++")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.native_san_check"],
            cwd=root, capture_output=True, text=True, timeout=420)
        assert proc.returncode == 0, \
            f"sanitizer parity failed:\n{proc.stdout}\n{proc.stderr}"
        assert "OK" in proc.stdout
