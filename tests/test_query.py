"""Query plane tests — compiler, packer, scorer, end-to-end search.

Modeled on the reference QA strategy (SURVEY §4): inject a small fixture
corpus, run queries, assert ranking-relevant invariants (the ``qainject``/
``qaSyntax`` pattern from ``qa.cpp:659,1163`` — inject then query every
operator), plus unit checks of scoring semantics against hand-computed
values from the reference weight tables.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import compiler, engine, packer, scorer
from open_source_search_engine_tpu.query import weights


# ---------------------------------------------------------------------------
# compiler
# ---------------------------------------------------------------------------

class TestCompiler:
    def test_plain_words(self):
        plan = compiler.compile_query("street light")
        assert len(plan.groups) == 2
        assert all(g.required and g.scored for g in plan.groups)
        # left word carries the bigram sublist (+ synonym conjugates)
        kinds0 = [sl.kind for sl in plan.groups[0].sublists]
        assert kinds0[0] == compiler.SUB_ORIGINAL
        assert compiler.SUB_BIGRAM in kinds0
        kinds1 = [sl.kind for sl in plan.groups[1].sublists]
        assert compiler.SUB_BIGRAM not in kinds1
        assert compiler.SUB_SYNONYM in kinds0  # streets etc.

    def test_negative(self):
        plan = compiler.compile_query("apple -banana")
        assert plan.groups[0].negative is False
        assert plan.groups[1].negative is True
        # no bigram across a negative term; negatives stay literal
        assert not any(sl.kind == compiler.SUB_BIGRAM
                       for sl in plan.groups[0].sublists)
        assert len(plan.groups[1].sublists) == 1

    def test_site_filter(self):
        plan = compiler.compile_query("news site:example.com")
        assert len(plan.groups) == 2
        f = plan.groups[1]
        assert f.scored is False and f.required is True

    def test_quoted_phrase(self):
        plan = compiler.compile_query('"new york city"')
        # 3 word groups + 2 adjacency (bigram) gate groups
        kinds = [(g.scored, g.required) for g in plan.groups]
        assert len(plan.groups) == 5
        assert kinds.count((False, True)) == 2

    def test_same_word_same_termid(self):
        a = compiler.compile_query("Apple")
        b = compiler.compile_query("apple")
        assert a.groups[0].termids == b.groups[0].termids

    def test_hyphenated_word_is_not_negation(self):
        plan = compiler.compile_query("covid-19 state-of-the-art")
        assert not any(g.negative for g in plan.groups)
        assert [g.display for g in plan.groups] == \
               ["covid", "19", "state", "of", "the", "art"]

    def test_negated_phrase_single_group(self):
        plan = compiler.compile_query('apple -"new york"')
        negs = [g for g in plan.groups if g.negative]
        assert len(negs) == 1
        # one bigram sublist, not per-word negative groups
        assert len(negs[0].sublists) == 1
        assert negs[0].sublists[0].kind == compiler.SUB_BIGRAM


# ---------------------------------------------------------------------------
# end-to-end fixture corpus (qainject pattern)
# ---------------------------------------------------------------------------

DOCS = {
    "http://fruits.example.com/apple": """
      <html><head><title>All about apples</title></head><body>
      <h1>Apple varieties</h1>
      <p>The apple is a sweet fruit. Apples are grown worldwide.
      An apple tree takes years to mature. Apple pie is popular.</p>
      </body></html>""",
    "http://fruits.example.com/banana": """
      <html><head><title>Banana facts</title></head><body>
      <p>The banana is a tropical fruit. Bananas are rich in potassium.
      A banana plant is technically an herb.</p></body></html>""",
    "http://veg.example.org/carrot": """
      <html><head><title>Carrot guide</title></head><body>
      <p>The carrot is a root vegetable. Carrots contain carotene.
      Some say a carrot a day keeps the optometrist away. The fruit
      comparison is unfair to the humble carrot.</p></body></html>""",
    "http://news.example.net/fruit-market": """
      <html><head><title>Fruit market report</title></head><body>
      <p>Apple and banana prices rose this week at the fruit market.
      The market for tropical fruit keeps growing. Traders expect
      banana supply to recover.</p></body></html>""",
}


@pytest.fixture(scope="class")
def coll(tmp_path_factory):
    c = Collection("qtest", tmp_path_factory.mktemp("qtest"))
    for url, html in DOCS.items():
        docproc.index_document(c, url, html)
    return c


class TestEndToEnd:
    def test_single_term(self, coll):
        res = engine.search(coll, "banana", topk=10)
        urls = [r.url for r in res.results]
        assert "http://fruits.example.com/banana" in urls
        assert "http://news.example.net/fruit-market" in urls
        assert "http://fruits.example.com/apple" not in urls
        # title hit + higher density should rank the banana page first
        assert urls[0] == "http://fruits.example.com/banana"

    def test_and_semantics(self, coll):
        res = engine.search(coll, "apple banana", topk=10)
        urls = {r.url for r in res.results}
        assert urls == {"http://news.example.net/fruit-market"}

    def test_negative_excludes(self, coll):
        res = engine.search(coll, "fruit -banana", topk=10)
        urls = {r.url for r in res.results}
        assert "http://news.example.net/fruit-market" not in urls
        assert "http://fruits.example.com/banana" not in urls
        assert "http://fruits.example.com/apple" in urls
        assert "http://veg.example.org/carrot" in urls

    def test_site_filter(self, coll):
        res = engine.search(coll, "fruit site:fruits.example.com", topk=10)
        urls = {r.url for r in res.results}
        assert urls == {"http://fruits.example.com/apple",
                        "http://fruits.example.com/banana"}

    def test_quoted_phrase(self, coll):
        res = engine.search(coll, '"root vegetable"', topk=10)
        urls = {r.url for r in res.results}
        assert urls == {"http://veg.example.org/carrot"}
        # words present but never adjacent in any doc → no matches
        res2 = engine.search(coll, '"vegetable root"', topk=10)
        assert not res2.results

    def test_no_match(self, coll):
        res = engine.search(coll, "zeppelin", topk=10)
        assert res.total_matches == 0 and not res.results

    def test_snippets_and_titles(self, coll):
        res = engine.search(coll, "carotene", topk=5)
        assert res.results[0].title == "Carrot guide"
        assert "carotene" in res.results[0].snippet.lower()

    def test_delete_then_search(self, coll):
        url = "http://tmp.example.com/doomed"
        docproc.index_document(
            coll, url, "<html><title>Doomed</title>"
            "<body>xylophone quartz doomed page</body></html>")
        assert any(r.url == url for r in
                   engine.search(coll, "xylophone").results)
        docproc.remove_document(coll, url)
        assert not engine.search(coll, "xylophone").results

    def test_negated_phrase_keeps_word_matches(self, coll):
        # "tropical fruit" appears in banana + market docs; carrot has
        # "fruit" alone and must survive the phrase negation
        res = engine.search(coll, 'fruit -"tropical fruit"', topk=10)
        urls = {r.url for r in res.results}
        assert "http://veg.example.org/carrot" in urls
        assert "http://fruits.example.com/banana" not in urls
        assert "http://news.example.net/fruit-market" not in urls

    def test_bare_site_filter_query(self, coll):
        res = engine.search(coll, "site:fruits.example.com", topk=10)
        urls = {r.url for r in res.results}
        assert urls == {"http://fruits.example.com/apple",
                        "http://fruits.example.com/banana"}

    def test_total_matches_counts_all(self, coll):
        res = engine.search(coll, "fruit", topk=1)
        assert len(res.results) == 1
        assert res.total_matches == 4  # every fixture doc contains "fruit"

    def test_multipass_matches_single_pass(self, coll):
        full = engine.search(coll, "fruit", topk=10)
        paged = engine.search(coll, "fruit", topk=10, max_docs_per_pass=2)
        assert [r.docid for r in full.results] == \
               [r.docid for r in paged.results]
        assert [round(r.score, 3) for r in full.results] == \
               [round(r.score, 3) for r in paged.results]


# ---------------------------------------------------------------------------
# scoring semantics (hand-checked against reference weight math)
# ---------------------------------------------------------------------------

class TestScoringSemantics:
    def _one_doc_pq(self, payloads_by_term, n_docs=1, freqw=None,
                    siterank=0):
        """Build a minimal PackedQuery by hand: one candidate doc, T terms,
        each with a list of packed (wordpos, hg, den, spam, syn)."""
        T = len(payloads_by_term)
        L = max(max((len(p) for p in payloads_by_term), default=1), 1)
        L = packer._bucket(L)
        doc_idx = np.full((T, L), 1, np.int32)  # 1 == dump row for D=1
        payload = np.zeros((T, L), np.uint32)
        slot = np.zeros((T, L), np.int32)
        valid = np.zeros((T, L), bool)
        for t, plist in enumerate(payloads_by_term):
            for i, (wp, hg, den, spam, syn) in enumerate(plist):
                doc_idx[t, i] = 0
                payload[t, i] = (wp | (hg << 18) | (den << 22)
                                 | (spam << 27) | (syn << 31))
                slot[t, i] = i
                valid[t, i] = True
        return packer.PackedQuery(
            doc_idx=doc_idx, payload=payload, slot=slot, valid=valid,
            freq_weight=np.array(freqw or [0.5] * T, np.float32),
            required=np.ones(T, bool), negative=np.zeros(T, bool),
            scored=np.ones(T, bool), counts=np.ones(T, bool),
            table=packer.pad_table(None),
            cand_docids=np.array([1234], np.uint64),
            siterank=np.full(1, siterank, np.int32),
            doclang=np.zeros(1, np.int32), n_docs=1, qlang=0)

    def test_single_term_body_score(self):
        # one body position, density rank 25, no spam (15), no syn
        den = 25
        pq = self._one_doc_pq([[(100, 0, den, 15, 0)]])
        docids, scores, _ = scorer.run_query(pq, topk=4)
        dw = weights.DENSITY_WEIGHTS[den]
        expect = (100.0 * (1.0 * dw * 1.0) ** 2      # hgw=1 body, spamw=1
                  * 0.5 * 0.5                        # freqw²
                  * 1.0                              # siterank 0 → ×1
                  * weights.SAME_LANG_WEIGHT)
        assert scores[0] == pytest.approx(expect, rel=1e-5)

    def test_title_beats_body(self):
        body = self._one_doc_pq([[(100, 0, 25, 15, 0)]])
        title = self._one_doc_pq([[(100, 1, 25, 15, 0)]])
        _, sb, _ = scorer.run_query(body, topk=1)
        _, st, _ = scorer.run_query(title, topk=1)
        assert st[0] == pytest.approx(sb[0] * 64.0, rel=1e-5)  # 8² hgw

    def test_pair_distance_decay(self):
        # two terms in body, close vs far: score ∝ 1/(dist-qdist+1)
        def pair_pq(gap):
            return self._one_doc_pq(
                [[(100, 0, 31, 15, 0)], [(100 + gap, 0, 31, 15, 0)]])
        _, s_close, _ = scorer.run_query(pair_pq(2), topk=1)
        _, s_far, _ = scorer.run_query(pair_pq(12), topk=1)
        # dist 2-qdist=0 → /1 ; dist 12-qdist=10 → /11
        assert s_close[0] == pytest.approx(s_far[0] * 11.0, rel=1e-4)

    def test_out_of_order_penalty(self):
        fwd = self._one_doc_pq(
            [[(100, 0, 31, 15, 0)], [(110, 0, 31, 15, 0)]])
        rev = self._one_doc_pq(
            [[(110, 0, 31, 15, 0)], [(100, 0, 31, 15, 0)]])
        _, sf, _ = scorer.run_query(fwd, topk=1)
        _, sr, _ = scorer.run_query(rev, topk=1)
        assert sf[0] > sr[0]

    def test_siterank_multiplier(self):
        lo = self._one_doc_pq([[(100, 0, 31, 15, 0)]], siterank=0)
        hi = self._one_doc_pq([[(100, 0, 31, 15, 0)]], siterank=9)
        _, sl, _ = scorer.run_query(lo, topk=1)
        _, sh, _ = scorer.run_query(hi, topk=1)
        assert sh[0] == pytest.approx(
            sl[0] * (9 * weights.SITERANKMULTIPLIER + 1.0), rel=1e-5)

    def test_min_algorithm_takes_weakest_term(self):
        # term B has worse density → min(single) should reflect B
        pq = self._one_doc_pq([[(100, 0, 31, 15, 0)],
                               [(300, 0, 5, 15, 0)]])
        pq_both_good = self._one_doc_pq([[(100, 0, 31, 15, 0)],
                                         [(300, 0, 31, 15, 0)]])
        _, s_mixed, _ = scorer.run_query(pq, topk=1)
        _, s_good, _ = scorer.run_query(pq_both_good, topk=1)
        assert s_mixed[0] < s_good[0]

    def test_inlink_text_positions_sum(self):
        # multiple inlink-text hits add up (no mapped-group dedup),
        # repeated body hits dedup to the best one
        inlink2 = self._one_doc_pq(
            [[(0, 5, 31, 3, 0), (60, 5, 31, 3, 0)]])
        inlink1 = self._one_doc_pq([[(0, 5, 31, 3, 0)]])
        body2 = self._one_doc_pq(
            [[(100, 0, 31, 15, 0), (160, 0, 31, 15, 0)]])
        body1 = self._one_doc_pq([[(100, 0, 31, 15, 0)]])
        _, si2, _ = scorer.run_query(inlink2, topk=1)
        _, si1, _ = scorer.run_query(inlink1, topk=1)
        _, sb2, _ = scorer.run_query(body2, topk=1)
        _, sb1, _ = scorer.run_query(body1, topk=1)
        assert si2[0] == pytest.approx(si1[0] * 2.0, rel=1e-5)
        assert sb2[0] == pytest.approx(sb1[0], rel=1e-5)

    def test_weight_tables_match_reference_formulas(self):
        assert weights.DENSITY_WEIGHTS[0] == pytest.approx(0.35)
        assert weights.DENSITY_WEIGHTS[31] == pytest.approx(1.0)
        assert weights.WORD_SPAM_WEIGHTS[15] == pytest.approx(1.0)
        assert weights.WORD_SPAM_WEIGHTS[0] == pytest.approx(1.0 / 16)
        assert weights.LINKER_WEIGHTS[15] == pytest.approx(4.0)
        assert weights.HASH_GROUP_WEIGHTS[1] == 8.0   # title
        assert weights.HASH_GROUP_WEIGHTS[5] == 16.0  # inlink text
