"""Deep paging (reference ``s=`` start row; TopTree top-X, TopTree.h:15).

Pages must be stable and disjoint: page k at size n equals rows
[k·n, (k+1)·n) of one big fetch, with dedup/site-clustering applied
BEFORE pagination so page boundaries don't shift between requests.
Covers the flat engine, the resident device path, the sharded mesh
path, and the HTTP ``s=`` parameter.
"""

import json
import urllib.request

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine


def _corpus(target, n=30):
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    for i in range(n):
        html = (f"<html><title>page {i}</title><body>"
                f"<p>paging corpus shared words item{i} "
                f"{'extra ' * (n - i)}depth</p></body></html>")
        url = f"http://site{i % 11}.test/p{i}"
        if isinstance(target, ShardedCollection):
            target.index_document(url, html)
        else:
            docproc.index_document(target, url, html)


@pytest.fixture(scope="module")
def coll(tmp_path_factory):
    c = Collection("pg", tmp_path_factory.mktemp("paging"))
    _corpus(c)
    return c


def _urls(res):
    return [r.url for r in res.results]


def test_flat_pages_partition_the_full_list(coll):
    full = engine.search(coll, "shared words", topk=30)
    pages = [engine.search(coll, "shared words", topk=7, offset=off)
             for off in range(0, 28, 7)]
    got = [u for p in pages for u in _urls(p)]
    assert got == _urls(full)[: len(got)]
    assert len(set(got)) == len(got)  # disjoint


def test_flat_offset_past_end_is_empty(coll):
    assert _urls(engine.search(coll, "shared words", topk=10,
                               offset=10000)) == []


def test_device_pages_match_flat(coll):
    full = engine.search_device(coll, "shared words", topk=30,
                                with_snippets=False)
    p2 = engine.search_device(coll, "shared words", topk=5, offset=5,
                              with_snippets=False)
    assert _urls(p2) == _urls(full)[5:10]


def test_sharded_pages_partition(tmp_path):
    from open_source_search_engine_tpu.parallel import sharded_search
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("pg", tmp_path, n_shards=4)
    _corpus(sc)
    full = sharded_search(sc, "shared words", topk=30)
    pages = [sharded_search(sc, "shared words", topk=6, offset=off)
             for off in range(0, 24, 6)]
    got = [u for p in pages for u in _urls(p)]
    assert got == _urls(full)[: len(got)]


def test_http_s_param(tmp_path):
    from open_source_search_engine_tpu.serve.server import SearchHTTPServer
    srv = SearchHTTPServer(tmp_path, port=0)
    _corpus(srv.colldb.get("main"), n=12)
    srv.start()
    try:
        port = srv._httpd.server_port

        def q(s):
            return json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/search?q=shared+words"
                f"&n=4&s={s}&format=json").read())
        p0, p1 = q(0), q(4)
        u0 = [h["url"] for h in p0["results"]]
        u1 = [h["url"] for h in p1["results"]]
        assert len(u0) == 4 and len(u1) == 4
        assert not set(u0) & set(u1)
        full = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/search?q=shared+words"
            f"&n=8&format=json").read())
        assert [h["url"] for h in full["results"]] == u0 + u1
    finally:
        srv.stop()
