"""Control plane tests: stats, process autosave/shutdown, heartbeat
failover, spider persistence, parms endpoint."""

import json
import urllib.request

import pytest

from open_source_search_engine_tpu.control import Heartbeat, Process
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.parallel import HostMap
from open_source_search_engine_tpu.spider import SpiderScheduler
from open_source_search_engine_tpu.utils.stats import Stats


class TestStats:
    def test_counters_and_latency(self):
        s = Stats()
        s.count("q")
        s.count("q", 2)
        s.record_ms("op", 3.0)
        s.record_ms("op", 30.0)
        snap = s.snapshot()
        assert snap["counters"]["q"] == 3
        assert snap["latencies"]["op"]["count"] == 2
        assert 3.0 <= snap["latencies"]["op"]["avg_ms"] <= 30.0
        assert snap["latencies"]["op"]["max_ms"] == 30.0

    def test_timed_context(self):
        s = Stats()
        with s.timed("x"):
            pass
        assert s.snapshot()["latencies"]["x"]["count"] == 1

    def test_timeseries_window(self):
        s = Stats(timeseries_window=3)
        for i in range(5):
            s.sample(v=float(i))
        rows = s.series()
        assert len(rows) == 3 and rows[-1][1]["v"] == 4.0


class TestProcess:
    def test_save_all_and_shutdown(self, tmp_path):
        coll = Collection("proc", tmp_path)
        proc = Process()
        proc.register(coll)
        closed = []
        proc.on_shutdown(lambda: closed.append(1))
        proc.save_all()
        assert proc.saves == 1
        proc.shutdown()
        assert closed == [1] and proc.saves == 2

    def test_restart_recovers_memtable(self, tmp_path):
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.query import engine
        c1 = Collection("re", tmp_path)
        docproc.index_document(
            c1, "http://r.test/p",
            "<html><title>T</title><body>persistent walrus</body></html>")
        Process().register(c1)
        c1.save()
        c2 = Collection("re", tmp_path)  # fresh process
        c2.num_docs = 1  # collstats written by save()
        assert engine.search(c2, "walrus").results


class TestHeartbeat:
    def test_dead_marking_and_recovery(self):
        hm = HostMap(4)
        down = {2}
        hb = Heartbeat(hm, probe=lambda s: s not in down)
        hb.check_once()
        assert list(hm.alive) == [True, True, False, True]
        down.clear()
        hb.check_once()
        assert all(hm.alive)

    def test_dead_shard_degrades_not_fails(self, tmp_path):
        import jax
        from open_source_search_engine_tpu.parallel import (
            ShardedCollection, make_mesh, sharded_search)
        sc = ShardedCollection("hb", tmp_path, n_shards=2)
        mesh = make_mesh(2, devices=jax.devices()[:2])
        for i in range(8):
            sc.index_document(
                f"http://h{i}.test/", f"<html><body>failover doc {i}"
                "</body></html>")
        full = sharded_search(sc, "failover", mesh=mesh, topk=10)
        assert full.total_matches == 8
        sc.hostmap.mark_dead(0)
        part = sharded_search(sc, "failover", mesh=mesh, topk=10)
        assert 0 < part.total_matches < 8  # degraded, not an error


class TestSpiderPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        s = SpiderScheduler()
        s.add_url("http://a.test/1")
        s.add_url("http://a.test/2")
        s.next_batch(1)
        s.save_to(tmp_path / "spider.json")

        s2 = SpiderScheduler()
        assert s2.load_from(tmp_path / "spider.json")
        assert len(s2) == len(s)
        assert s2.seen == s.seen
        assert not s2.add_url("http://a.test/1")  # still deduped
        # remaining queue drains identically
        assert [d.url for d in sorted(s2.heap)] == \
               [d.url for d in sorted(s.heap)]

    def test_load_missing_is_false(self, tmp_path):
        assert not SpiderScheduler().load_from(tmp_path / "nope.json")


class TestParmsEndpoint:
    def test_view_and_live_update(self, tmp_path):
        from open_source_search_engine_tpu.serve import serve
        s = serve(tmp_path, port=0)
        try:
            base = f"http://127.0.0.1:{s.port}"
            r = json.load(urllib.request.urlopen(f"{base}/admin/parms"))
            assert any(row["cgi"] == "langw" for row in r["table"])
            assert r["coll"]["lang_weight"] == 20.0
            r = json.load(urllib.request.urlopen(
                f"{base}/admin/parms?langw=5.5"))
            assert r["updated"] == {"langw": "5.5"}
            assert r["coll"]["lang_weight"] == 5.5
            r = json.load(urllib.request.urlopen(
                f"{base}/admin/perf?format=json"))
            assert "counters" in r["fleet"]
        finally:
            s.stop()
