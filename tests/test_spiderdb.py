"""Durable spiderdb frontier tests (VERDICT round-2 item 6).

Reference contracts: SpiderRequests/Replies in a real Rdb keyed by
(host, urlhash) (Spider.h:388,468), firstIP-style host-hash sharding
(Hostdb.cpp:~2526), and restart-safe doling (the reply record is the
never-refetch witness; an unreplied request always re-doles)."""

from open_source_search_engine_tpu.spider.spiderdb import (
    DurableSpiderScheduler, shard_of_url, urlhash63)


def urls(n, host="a.test"):
    return [f"http://{host}/p{i}" for i in range(n)]


class TestDurableFrontier:
    def test_checkpoint_restart_resumes_exact_frontier(self, tmp_path):
        s = DurableSpiderScheduler(tmp_path, max_hops=5)
        for u in urls(20):
            assert s.add_url(u)
        batch = []
        for i in range(8):  # one per politeness window (same host)
            got = s.next_batch(1, now=1000.0 * (i + 1))
            batch += got
            for r in got:  # fetch completes -> IP lock releases
                s.release(r.url, now=1000.0 * (i + 1))
        assert len(batch) == 8
        for r in batch:
            s.mark_done(r.url)
        s.checkpoint()

        # "kill -9": drop the object without any further save
        done = {r.url for r in batch}
        s2 = DurableSpiderScheduler(tmp_path, max_hops=5)
        assert len(s2) == 12                      # frontier not lost
        doled = []
        t = 1e12
        while not s2.exhausted:
            t += 1000.0
            got = s2.next_batch(50, now=t)
            doled += [r.url for r in got]
            for r in got:  # fetch completes -> IP lock releases
                s2.release(r.url, now=t)
        assert set(doled) == set(urls(20)) - done  # no re-fetches
        # completed + pending urls stay deduped after restart
        for u in urls(20):
            assert not s2.add_url(u)

    def test_unreplied_inflight_redoles(self, tmp_path):
        s = DurableSpiderScheduler(tmp_path)
        for u in urls(4, host="b.test"):
            s.add_url(u)
        inflight = (s.next_batch(1, now=1e9)
                    + s.next_batch(1, now=2e9))  # doled, crash pre-reply
        s.checkpoint()
        s2 = DurableSpiderScheduler(tmp_path)
        redo = {r.url for r in (s2.next_batch(50, now=1e12)
                                + s2.next_batch(50, now=2e12))}
        # the in-flight urls come back (fetch-twice, never lost)
        assert {r.url for r in inflight} <= redo

    def test_every_add_survives_a_crash(self, tmp_path):
        """The addsinprogress journal makes each accepted url durable
        BEFORE the ack — kill -9 at any point loses nothing."""
        s = DurableSpiderScheduler(tmp_path)
        for u in urls(6, host="c.test"):
            s.add_url(u)
        s.add_url("http://c.test/late")           # never checkpointed
        s2 = DurableSpiderScheduler(tmp_path)     # crash-restart
        assert len(s2) == 7
        assert not s2.add_url("http://c.test/late")  # still deduped

    def test_host_hash_sharding_consistent(self):
        for u in ["http://x.test/a", "http://x.test/b"]:
            assert shard_of_url(u, 4) == shard_of_url("http://x.test/z", 4)
        spread = {shard_of_url(f"http://h{i}.test/", 8) for i in range(64)}
        assert len(spread) > 4                    # spreads across shards

    def test_crawl_loop_integration(self, tmp_path):
        from open_source_search_engine_tpu.index.collection import Collection
        from open_source_search_engine_tpu.spider.fetcher import (
            Fetcher, FetchResult)
        from open_source_search_engine_tpu.spider.loop import SpiderLoop

        pages = {
            f"http://crawl.test/p{i}": (
                f"<html><head><title>P{i}</title></head><body>"
                f"<p>page {i} words"
                + (f' <a href="/p{i+1}">next</a>' if i < 5 else "")
                + "</p></body></html>")
            for i in range(6)
        }

        class FakeFetcher(Fetcher):
            def fetch_many(self, urls, **kw):
                return [FetchResult(url=u, status=200,
                                    content=pages.get(u, ""),
                                    content_type="text/html")
                        for u in urls]

        c = Collection("crawl", tmp_path / "coll")
        sched = DurableSpiderScheduler(tmp_path / "sp", max_hops=10)
        loop = SpiderLoop(c, scheduler=sched, fetcher=FakeFetcher(),
                          batch_size=2)
        loop.add_url("http://crawl.test/p0")
        # politeness: same host, so drain with many steps
        for _ in range(30):
            loop.crawl_step()
            sched.ip_ready_at.clear()             # fast-forward politeness
            if sched.exhausted:
                break
        assert loop.stats.indexed == 6
        # restart: everything replied, frontier empty, nothing refetches
        s2 = DurableSpiderScheduler(tmp_path / "sp", max_hops=10)
        assert len(s2) == 0
        assert not s2.add_url("http://crawl.test/p3")


def test_same_ip_hosts_share_shard_and_never_fetch_concurrently(tmp_path):
    """Cluster-wide per-IP discipline (Spider.h:99-108 firstIP keying):
    every host resolving to one IP routes to ONE shard, and that
    shard's scheduler never doles two urls of the IP concurrently — so
    no multi-node crawl can hammer an IP from N nodes."""
    from open_source_search_engine_tpu.spider.spiderdb import \
        shard_of_url
    ips = {"a.cdn.test": "93.1.2.3", "b.cdn.test": "93.1.2.3",
           "c.cdn.test": "93.1.2.3", "solo.test": "94.4.5.6"}
    res = lambda h: ips.get(h, "0.0.0.1")
    # same IP → same shard, for any shard count
    for n in (2, 4, 16):
        shards = {shard_of_url(f"http://{h}.test/x", n, resolver=res)
                  for h in ("a.cdn", "b.cdn", "c.cdn")}
        shards2 = {shard_of_url(f"http://{h}/p{i}", n, resolver=res)
                   for h in ("a.cdn.test", "b.cdn.test", "c.cdn.test")
                   for i in range(5)}
        assert len(shards2) == 1
    # the owning shard's scheduler serializes the IP (in-flight lock)
    s = DurableSpiderScheduler(tmp_path, resolver=res)
    for h in ("a.cdn.test", "b.cdn.test", "c.cdn.test", "solo.test"):
        assert s.add_url(f"http://{h}/page")
    got = s.next_batch(10, now=1e9)
    by_ip = {}
    for r in got:
        by_ip[r.first_ip] = by_ip.get(r.first_ip, 0) + 1
    assert by_ip == {"93.1.2.3": 1, "94.4.5.6": 1}
    assert s.next_batch(10, now=2e9) == []  # both IPs in flight
    for r in got:
        s.release(r.url, now=2e9)
    assert len(s.next_batch(10, now=3e9)) == 1  # next cdn url, one IP
