"""Summary/Title quality goldens: the Title.cpp fallback chain,
field-aware matches, sentence-snapped fragments, conjugate-aware
highlighting, and the meta-description summary fallback."""

import tempfile

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.compiler import compile_query
from open_source_search_engine_tpu.query.summary import (
    choose_title, field_matches, highlight, make_summary)


class TestTitleFallback:
    def test_stored_title_wins(self):
        assert choose_title({"title": "Real Title", "h1": "heading",
                             "url": "http://x.test/a"}) == "Real Title"

    def test_h1_fallback(self):
        assert choose_title({"title": "", "h1": "the heading words",
                             "url": "http://x.test/a"}) \
            == "the heading words"

    def test_anchor_fallback(self):
        rec = {"title": "", "h1": "",
               "inlinks": [["short", 3], ["a longer anchor text", 5]],
               "url": "http://x.test/a"}
        assert choose_title(rec) == "a longer anchor text"

    def test_url_fallback(self):
        rec = {"title": "", "h1": "", "inlinks": [],
               "url": "http://x.test/deep/path/red-pandas_guide"}
        assert choose_title(rec) == "red pandas guide"

    def test_host_fallback_when_no_path(self):
        rec = {"title": "", "h1": "", "url": "http://bare.test/"}
        assert "bare.test" in choose_title(rec)

    def test_truncation(self):
        rec = {"title": "x" * 300, "url": "http://x.test/"}
        assert len(choose_title(rec, max_len=80)) == 80

    def test_end_to_end_titleless_page(self, tmp_path):
        coll = Collection("t", str(tmp_path))
        docproc.index_document(
            coll, "http://t.test/no-title-page",
            "<html><body><h1>Pandas In The Wild</h1>"
            "<p>pandas eat bamboo happily in mountain forests.</p>"
            "</body></html>")
        res = engine.search(coll, "bamboo", topk=5)
        assert res.results
        assert res.results[0].title == "pandas in the wild"


class TestFieldMatches:
    def test_per_field_counts(self):
        rec = {"title": "Tiger Story", "h1": "",
               "meta_description": "about big tigers",
               "text": "the tiger hunts at night",
               "inlinks": [["tiger page", 2]]}
        fm = field_matches(rec, ["tiger", "night"])
        assert fm["title"] == 1       # "tiger" (lowercased match)
        assert fm["body"] == 2        # tiger + night
        assert fm["anchor"] == 1
        assert "h1" not in fm


class TestSummary:
    TEXT = ("The quick brown fox jumps over the lazy dog. "
            "Nothing about cats here at all in this one. "
            "A second sentence mentions foxes and badgers together. "
            "Filler filler filler words continue for a while longer. "
            "The final sentence is about weather patterns.")

    def test_sentence_snapped(self):
        s = make_summary(self.TEXT, ["badgers"])
        # the fragment snaps to the containing sentence's bounds
        assert "A second sentence mentions foxes and badgers" in s
        assert not s.startswith("…")

    def test_description_fallback_when_body_misses(self):
        s = make_summary("body text without the word.", ["zebra"],
                         description="zebra facts and figures")
        assert s == "zebra facts and figures"

    def test_body_head_when_nothing_matches(self):
        s = make_summary("just some body text here.", ["zebra"],
                         description="nothing relevant either")
        assert s.startswith("just some body")

    def test_conjugate_words_matched(self):
        plan = compile_query("running")
        words = plan.match_words()
        assert "running" in words
        assert "run" in words          # conjugate rides along
        s = make_summary("she was seen run after the bus daily.",
                         words)
        assert "run" in s
        h = highlight("run and running", words)
        assert h == "<b>run</b> and <b>running</b>"
