"""Runtime lock auditor tests — order-graph cycles, hold times, and
blocking-call probes (:mod:`..utils.lockcheck`).

These drive a private :class:`LockCheckRegistry` (never the process
singleton) so assertions can't see edges from other tests, and they
work regardless of whether OSSE_LOCKCHECK is set for the suite run.
"""

import threading
import time

import pytest

from open_source_search_engine_tpu.utils import lockcheck
from open_source_search_engine_tpu.utils.lockcheck import (
    LockCheckRegistry, TrackedLock, TrackedRLock,
)
from open_source_search_engine_tpu.utils.stats import g_stats


@pytest.fixture
def reg():
    return LockCheckRegistry()


class TestOrderGraph:
    def test_nested_acquire_records_edge(self, reg):
        a = TrackedLock("A", reg)
        b = TrackedLock("B", reg)
        with a:
            with b:
                pass
        assert reg.edges == {"A": {"B"}}
        assert reg.cycles == []
        info = reg.edge_info[("A", "B")]
        assert threading.current_thread().name in info

    def test_ab_then_ba_is_a_cycle(self, reg):
        """The classic potential deadlock: one code path takes A→B,
        another B→A. Neither run deadlocks alone; the auditor must
        still flag the pair."""
        a = TrackedLock("A", reg)
        b = TrackedLock("B", reg)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(reg.cycles) == 1
        cycle = reg.cycles[0]
        assert set(cycle) == {"A", "B"}
        # the cycle is also visible in the serialized report
        assert reg.report()["cycles"] == [cycle]

    def test_transitive_cycle_detected(self, reg):
        """A→B, B→C, then C→A closes a 3-lock loop."""
        a, b, c = (TrackedLock(n, reg) for n in "ABC")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        assert len(reg.cycles) == 1
        assert set(reg.cycles[0]) == {"A", "B", "C"}

    def test_same_name_reentry_is_not_an_edge(self, reg):
        """Two instances of one lock ROLE (e.g. two per-Rdb locks)
        produce no self-edge — the convention is per role name."""
        a1 = TrackedLock("rdb", reg)
        a2 = TrackedLock("rdb", reg)
        with a1:
            with a2:
                pass
        assert reg.edges == {}
        assert reg.cycles == []

    def test_consistent_order_never_cycles(self, reg):
        a = TrackedLock("A", reg)
        b = TrackedLock("B", reg)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert reg.edges == {"A": {"B"}}
        assert reg.cycles == []

    def test_cross_thread_edges_combine(self, reg):
        """Thread 1 takes A→B, thread 2 takes B→A: the graph is
        global, so the cycle is still caught."""
        a = TrackedLock("A", reg)
        b = TrackedLock("B", reg)

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass
        th = threading.Thread(target=t1, daemon=True)
        th.start()
        th.join()
        th = threading.Thread(target=t2, daemon=True)
        th.start()
        th.join()
        assert len(reg.cycles) == 1


class TestHeldSetAndHoldTimes:
    def test_held_is_per_thread_and_ordered(self, reg):
        a = TrackedLock("outer", reg)
        b = TrackedLock("inner", reg)
        with a:
            with b:
                assert reg.held() == ["outer", "inner"]
            assert reg.held() == ["outer"]
        assert reg.held() == []
        seen = []
        t = threading.Thread(target=lambda: seen.extend(reg.held()),
                             daemon=True)
        with a:
            t.start()
            t.join()
        assert seen == []  # other thread holds nothing

    def test_release_records_hold_time_stat(self, reg):
        name = "lockcheck-test-hold"
        before = g_stats.snapshot()["latencies"].get(
            f"lock.{name}.held_ms", {}).get("count", 0)
        lk = TrackedLock(name, reg)
        with lk:
            time.sleep(0.002)
        snap = g_stats.snapshot()["latencies"][f"lock.{name}.held_ms"]
        assert snap["count"] == before + 1

    def test_rlock_reentry_tracks_outermost_only(self, reg):
        lk = TrackedRLock("R", reg)
        other = TrackedLock("S", reg)
        with lk:
            with lk:  # re-entry: no new ordering info
                assert reg.held() == ["R"]
                with other:
                    pass
            assert reg.held() == ["R"]
        assert reg.held() == []
        assert reg.edges == {"R": {"S"}}

    def test_acquire_release_protocol(self, reg):
        lk = TrackedLock("P", reg)
        assert lk.acquire() is True
        assert lk.locked()
        lk.release()
        assert not lk.locked()
        assert lk.acquire(blocking=False) is True
        lk.release()


@pytest.fixture
def probed(reg):
    """Point the probes at the test registry, restoring whatever was
    installed before (under OSSE_LOCKCHECK=1 the suite itself runs
    with global probes on — install_probes is idempotent, so the test
    must swap them out, not stack on top)."""
    was_global = lockcheck._probes_installed
    lockcheck.uninstall_probes()
    lockcheck.install_probes(reg)
    yield reg
    lockcheck.uninstall_probes()
    if was_global:
        lockcheck.install_probes()


class TestBlockingProbes:
    def test_sleep_under_lock_is_flagged(self, reg, probed):
        lk = TrackedLock("nap", reg)
        with lk:
            time.sleep(0)
        assert len(reg.blocking) == 1
        ev = reg.blocking[0]
        assert ev["call"] == "time.sleep"
        assert ev["held"] == ["nap"]

    def test_sleep_without_lock_is_not_flagged(self, reg, probed):
        time.sleep(0)
        assert reg.blocking == []

    def test_uninstall_restores_originals(self, probed):
        probe_sleep = time.sleep
        lockcheck.uninstall_probes()
        try:
            assert time.sleep is not probe_sleep
            assert not lockcheck._probes_installed
        finally:
            lockcheck.install_probes(probed)


class TestFactoryGating:
    def test_factories_match_env_gate(self):
        a = lockcheck.make_lock("gate-test")
        b = lockcheck.make_rlock("gate-test-r")
        if lockcheck.ENABLED:
            assert isinstance(a, TrackedLock)
            assert isinstance(b, TrackedRLock)
        else:
            # plain primitives: zero audit overhead when off
            assert not isinstance(a, TrackedLock)
            assert not isinstance(b, TrackedLock)
        # both support the context protocol either way
        with a:
            pass
        with b:
            with b:
                pass

    def test_reset_clears_registry(self, reg):
        a = TrackedLock("A", reg)
        b = TrackedLock("B", reg)
        with a:
            with b:
                pass
        reg.reset()
        assert reg.report() == {"edges": {}, "edge_info": {},
                                "cycles": [], "cycle_stacks": [],
                                "blocking": []}


class TestContentionAndCycleStacks:
    def test_contended_acquire_counts_stat(self, reg):
        """A blocked acquire bumps ``lock.<name>.contended`` — the
        telemetry that says WHICH lock serializes the fleet."""
        name = "lockcheck-test-contend"
        key = f"lock.{name}.contended"
        lk = TrackedLock(name, reg)
        before = g_stats.snapshot()["counters"].get(key, 0)
        lk.acquire()
        t = threading.Thread(target=lambda: (lk.acquire(), lk.release()),
                             daemon=True)
        t.start()
        time.sleep(0.02)  # let the thread block on the held lock
        lk.release()
        t.join(timeout=5)
        assert g_stats.snapshot()["counters"][key] == before + 1

    def test_uncontended_acquire_does_not_count(self, reg):
        name = "lockcheck-test-uncontend"
        key = f"lock.{name}.contended"
        before = g_stats.snapshot()["counters"].get(key, 0)
        lk = TrackedLock(name, reg)
        with lk:
            pass
        assert g_stats.snapshot()["counters"].get(key, 0) == before

    def test_cycle_report_carries_both_acquisition_stacks(self, reg):
        """The DFS cycle report names where EACH edge of the inversion
        was taken — both sides of the A→B / B→A pair."""
        a = TrackedLock("A", reg)
        b = TrackedLock("B", reg)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(reg.cycle_stacks) == 1
        stacks = reg.cycle_stacks[0]
        assert set(stacks) == {"A->B", "B->A"}
        me = threading.current_thread().name
        assert all(me in where for where in stacks.values())
        assert reg.report()["cycle_stacks"] == [stacks]
