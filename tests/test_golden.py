"""Golden QA suite — pinned (docid, score) outputs for ~50 queries
covering every operator, compared EXACTLY over the flat, resident, and
sharded execution paths.

Reference model: qa.cpp:3358 ``s_qatests[]`` — responses normalized and
CRC-compared against golden checksums; any drift fails with a readable
diff. Regenerate intentionally with ``python tools/gen_golden.py`` and
review the diff before committing.

Scores are pinned at 2 decimals; tied docids compare as sets per score
level (tie order is not part of the contract — TopTree insertion order
is arbitrary in the reference too).
"""

import json
from pathlib import Path

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import search_device
from tests.golden.corpus import GOLDEN_QUERIES, golden_docs

EXPECTED = json.loads(
    (Path(__file__).parent / "golden" / "expected.json").read_text())


@pytest.fixture(scope="module")
def coll(tmp_path_factory):
    c = Collection("golden", tmp_path_factory.mktemp("golden"))
    # goldens pin the KERNEL ranking; the PostQueryRerank pass is a
    # deliberate post-filter with its own tests (test_rerank)
    c.conf.pqr_enabled = False
    for url, html in golden_docs().items():
        docproc.index_document(c, url, html)
    return c


@pytest.fixture(scope="module")
def sharded(tmp_path_factory):
    from open_source_search_engine_tpu.parallel import (
        ShardedCollection, make_mesh)
    sc = ShardedCollection("goldens", tmp_path_factory.mktemp("goldens"),
                           n_shards=4)
    for row in sc.grid:
        for c in row:
            c.conf.pqr_enabled = False
    for url, html in golden_docs().items():
        sc.index_document(url, html)
    return sc, make_mesh(4)


def _norm(results):
    """[(docid, score)] → {score: {docids}} with 2-decimal scores."""
    by_score = {}
    for docid, score in results:
        by_score.setdefault(round(score, 2), set()).add(int(docid))
    return by_score


def _check(q, total, results, path_name):
    """Exact-contract check against the golden outputs.

    The golden file stores the top-50 (whole tie groups for this
    corpus); a tested path returns a 10-result page. Pinned exactly:
    the total match count, the SEQUENCE of scores on the page (must
    equal the golden score sequence truncated to the page), and every
    returned docid must belong to the golden set at its score level
    (tie order within a level is not part of the contract)."""
    exp = EXPECTED[q]
    assert total == exp["total"], \
        f"[{path_name}] {q!r}: total {total} != golden {exp['total']}"
    got_scores = [round(s_, 2) for _, s_ in results]
    want_scores = [s_ for _, s_ in exp["results"]][: len(got_scores)]
    assert got_scores == want_scores, \
        (f"[{path_name}] {q!r}: score sequence {got_scores} != golden "
         f"{want_scores}")
    assert len(results) == min(10, len(exp["results"])), \
        f"[{path_name}] {q!r}: page size {len(results)}"
    want = _norm(exp["results"])
    for docid, s_ in results:
        assert int(docid) in want.get(round(s_, 2), set()), \
            (f"[{path_name}] {q!r}: docid {docid} not in golden set at "
             f"score {round(s_, 2)}")
    assert len({d for d, _ in results}) == len(results), \
        f"[{path_name}] {q!r}: duplicate docids"


def test_golden_covers_all_queries():
    assert set(GOLDEN_QUERIES) == set(EXPECTED)


@pytest.mark.parametrize("q", GOLDEN_QUERIES)
def test_flat_path(coll, q):
    res = engine.search(coll, q, topk=10, site_cluster=False,
                        with_snippets=False)
    _check(q, res.total_matches,
           [(r.docid, r.score) for r in res.results], "flat")


@pytest.mark.parametrize("q", GOLDEN_QUERIES)
def test_resident_path(coll, q):
    res = search_device(coll, q, topk=10, site_cluster=False,
                        with_snippets=False)
    _check(q, res.total_matches,
           [(r.docid, r.score) for r in res.results], "resident")


@pytest.mark.parametrize("q", GOLDEN_QUERIES)
def test_sharded_path(sharded, q):
    from open_source_search_engine_tpu.parallel import sharded_search
    sc, mesh = sharded
    res = sharded_search(sc, q, mesh=mesh, topk=10, site_cluster=False)
    _check(q, res.total_matches,
           [(r.docid, r.score) for r in res.results], "sharded")
