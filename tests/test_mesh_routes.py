"""Cross-shard golden results for EVERY kernel route on a virtual mesh.

An 8-device CPU mesh serves a corpus shaped so each shard builds real
base columns + dense rows + cube rows, and specific queries
deterministically take each kernel route: two-phase F1 (bounded driver
and an escalating single-term), direct-cube FD (common multi-term), and
the generic assembling F2 (conjugate-rich group whose slot plan is not
quarter-aligned). Golden contract: the MeshResident path, the shard_map
path, and the FLAT single-collection host path agree on match counts
and scores (reference seam: Msg39 per-shard intersect + Msg3a merge,
Msg39.cpp:74 / Msg3a.cpp:971). Corpus + comparators live in
``parallel.routecheck``, shared with the driver's multichip dryrun.
"""

import os
import tempfile

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.parallel import make_mesh, sharded_search
from open_source_search_engine_tpu.parallel.routecheck import (
    ROUTE_ENV, ROUTE_QUERIES, assert_tie_run_parity, route_docs,
    route_hits)
from open_source_search_engine_tpu.parallel.sharded import (
    MeshResident, ShardedCollection)
from open_source_search_engine_tpu.query import engine

N_SHARDS = 8


@pytest.fixture(scope="module")
def mesh_env():
    saved = {k: os.environ.get(k) for k in ROUTE_ENV}
    os.environ.update(ROUTE_ENV)
    try:
        docs = route_docs(48 * N_SHARDS)
        sdir = tempfile.mkdtemp(prefix="mesh_routes_s_")
        sc = ShardedCollection("mesh", sdir, n_shards=N_SHARDS)
        for url, html in docs:
            sc.index_document(url, html)
        for sh in sc.shards:
            sh.posdb.dump()
            sh.titledb.dump()
            sh.save()
        fdir = tempfile.mkdtemp(prefix="mesh_routes_f_")
        flat = Collection("mesh", fdir)
        docproc.index_batch(flat, docs)
        flat.posdb.dump()
        flat.titledb.dump()
        yield sc, MeshResident(sc), flat
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class TestMeshRoutes:
    def test_every_shard_has_real_base(self, mesh_env):
        _, mr, _ = mesh_env
        for s, di in enumerate(mr.indexes):
            assert di.n_docs > 0, s
            assert len(di.dense_slot_of) > 0, s
            assert len(di.cube_slot_of) > 0, s

    @pytest.mark.parametrize("q,route", list(ROUTE_QUERIES.items()))
    def test_route_and_golden(self, mesh_env, q, route):
        sc, mr, flat = mesh_env
        _, hits = route_hits(mr.indexes, lambda: mr.search(q, topk=8))
        assert hits[route] == N_SHARDS, (q, hits)

        # goldens run with site clustering OFF so equal-score ties sit
        # adjacently (see routecheck.assert_tie_run_parity)
        r_mesh = mr.search(q, topk=8, site_cluster=False)
        r_map = sharded_search(sc, q, mesh=make_mesh(N_SHARDS), topk=8,
                               site_cluster=False)
        r_flat = engine.search(flat, q, topk=8, site_cluster=False)
        assert_tie_run_parity(r_mesh, r_map, label=q)
        assert r_mesh.total_matches == r_flat.total_matches, q
        sa = [round(x.score, 2) for x in r_mesh.results]
        sf = [round(z.score, 2) for z in r_flat.results]
        assert sa == sf, q

    def test_escalation_exercised(self, mesh_env):
        _, mr, _ = mesh_env
        esc0 = sum(di.escalations for di in mr.indexes)
        mr.search("alpha", topk=8)
        assert sum(di.escalations for di in mr.indexes) > esc0
