"""Auxiliary subsystems: DailyMerge scheduler + sampling profiler."""

import time
from datetime import datetime

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.control.dailymerge import (DailyMerge,
                                                              in_window,
                                                              parse_window)
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.utils.parms import Conf
from open_source_search_engine_tpu.utils.profiler import SamplingProfiler


def test_window_parsing():
    assert parse_window("2-5") == (2, 5)
    assert parse_window("22-4") == (22, 4)
    assert parse_window("") is None and parse_window("x") is None
    assert in_window(3, (2, 5)) and not in_window(6, (2, 5))
    assert in_window(23, (22, 4)) and in_window(1, (22, 4))
    assert not in_window(12, (22, 4))


def test_daily_merge_sweeps_once_per_day(tmp_path):
    c = Collection("dm", tmp_path)
    for i in range(4):  # several runs so a forced merge has work
        docproc.index_document(c, f"http://dm.test/d{i}",
                               f"<html><body><p>merge words "
                               f"number{i}</p></body></html>")
        c.posdb.dump()
    assert len(c.posdb.runs) >= 2
    conf = Conf()
    conf.merge_quiet_hours = "0-24"  # malformed (24) -> disabled
    dm = DailyMerge([c], conf)
    assert not dm.tick()
    conf.merge_quiet_hours = "2-5"
    assert dm.tick(now=datetime(2026, 7, 30, 3, 0)) is True
    assert len(c.posdb.runs) == 1          # fully merged
    # same day, still in window: no second sweep
    assert dm.tick(now=datetime(2026, 7, 30, 4, 0)) is False
    # next day: sweeps again
    assert dm.tick(now=datetime(2026, 7, 31, 2, 30)) is True


def test_sampling_profiler_catches_hot_function():
    prof = SamplingProfiler(interval_s=0.002)

    def hot_spin(deadline):
        x = 0
        while time.perf_counter() < deadline:
            x += 1
        return x

    prof.start()
    hot_spin(time.perf_counter() + 0.4)
    prof.stop()
    rep = prof.report()
    assert rep["samples"] > 20
    assert any(r["func"] == "hot_spin" for r in rep["top_self"])
    assert any(r["func"] == "hot_spin" for r in rep["top_cumulative"])
    prof.reset()
    assert prof.report()["samples"] == 0
