# osselint: path=open_source_search_engine_tpu/build/devbuild.py
# host-sort fixture — the pragma re-scopes it to the device ingest
# plane, where numpy orderings are fenced out. Each "EXPECT rule"
# comment marks the line a finding must anchor to. Never scanned by
# the real linter (lint_fixtures/ is excluded from directory walks).
import numpy as np


def merge_runs(keys):
    order = np.argsort(keys)  # EXPECT host-sort
    return keys[order]


def doc_index(docids):
    uniq = np.unique(docids)  # EXPECT host-sort
    return np.searchsorted(uniq, docids)


def rank_terms(termids):
    ordered = np.sort(termids)  # EXPECT host-sort
    return sorted(ordered.tolist())  # EXPECT host-sort


def pair_order(termids, docidx):
    return np.lexsort((docidx, termids))  # EXPECT host-sort
