# osselint: path=open_source_search_engine_tpu/query/devindex.py
# stats-cardinality fixture — metric names built at the call site.
# The pragma re-scopes it to the query plane where the rule runs.
# Each shape below mints one time series per distinct runtime value
# (the devindex.wave_f1+f2_n5 class: a gauge per observed wave
# count), which is unbounded dashboard cardinality.


def collect(kinds, waves, route, nbytes, g_stats, trace):
    trace.record(f"devindex.wave_{kinds}_n{len(waves)}", 0, 1)  # EXPECT stats-cardinality
    g_stats.count("devindex.trip." + route)  # EXPECT stats-cardinality
    g_stats.gauge("devindex.%s.bytes" % route, nbytes)  # EXPECT stats-cardinality
    g_stats.record_ms("devindex.{}.ms".format(route), 2.0)  # EXPECT stats-cardinality
