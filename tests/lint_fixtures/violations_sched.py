# osselint: path=open_source_search_engine_tpu/serve/fixture_sched.py
# concurrency fixture — the pragma re-scopes it to the serve plane,
# where the schedcheck static rules apply. Each "EXPECT rule" comment
# marks the line a finding must anchor to. Never scanned by the real
# linter (lint_fixtures/ is excluded from directory walks).
import threading


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._grants = {}
        self._inflight = 0

    def admit(self, key):
        with self._lock:
            self._inflight += 1
            self._grants[key] = True

    def release(self, key):
        # same counter admit() guards — the lost-update interleaving
        self._inflight -= 1  # EXPECT shared-state-unlocked

    def lazy(self, key):
        if key not in self._grants:
            self._grants[key] = object()  # EXPECT check-then-act
        return self._grants[key]

    def wait_one(self):
        with self._cv:
            self._cv.wait(1.0)  # EXPECT cond-wait-no-loop

    def wait_right(self):
        # predicate loop: re-checks after every wakeup — clean
        with self._cv:
            while not self._grants:
                self._cv.wait(1.0)
