# osselint: path=open_source_search_engine_tpu/serve/fixture_sched.py
# clean counterpart to violations_sched.py: every shared write under
# the owning lock, check and act inside one critical section, waits in
# predicate loops, plus the repo's *_locked caller-holds-lock naming
# convention (admission.py style).
import threading


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._grants = {}
        self._inflight = 0

    def admit(self, key):
        with self._lock:
            self._grant_locked(key)

    def release(self, key):
        with self._lock:
            self._inflight -= 1
            self._grants.pop(key, None)
            self._cv.notify_all()

    def _grant_locked(self, key):
        # caller holds self._lock (naming convention) — writes here
        # count as protected
        self._inflight += 1
        self._grants[key] = True

    def lazy(self, key):
        with self._lock:
            if key not in self._grants:
                self._grants[key] = object()
            return self._grants[key]

    def wait_done(self):
        with self._cv:
            while self._inflight:
                self._cv.wait(1.0)
