# osselint: path=open_source_search_engine_tpu/parallel/sharded.py
# negative fixture: parallel/sharded.py IS the mesh plane — the
# shard_map merge program may use cross-chip collectives freely.
# Never scanned by the real linter.
import jax
import jax.numpy as jnp


def mesh_merge(local_scores, out_k):
    gathered = jax.lax.all_gather(local_scores, "shards")
    total = jax.lax.psum(jnp.sum(local_scores), axis_name="shards")
    merged, _pos = jax.lax.top_k(gathered.reshape(-1), out_k)
    return merged, total
