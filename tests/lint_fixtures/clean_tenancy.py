# osselint: path=open_source_search_engine_tpu/serve/fixture_tenancy.py
"""Clean counterpart of violations_tenancy.py: device residency flows
through the engine factories, so the ResidencyManager owns eviction,
device-label billing, and delColl teardown."""
from ..query.engine import build_device_index, get_resident_loop


def serve_collection(coll, deadline=None):
    di = build_device_index(coll)
    loop = get_resident_loop(coll, deadline=deadline)
    return di, loop
