# osselint: path=open_source_search_engine_tpu/query/mesh_fixture.py
# osselint fixture — re-scoped to a virtual query/ path: cross-shard
# collectives are banned everywhere outside parallel/sharded.py, and
# the per-shard kernel layer is exactly where a stray one would
# couple the scorer to the mesh shape. Never scanned by the real
# linter (lint_fixtures/ is excluded from directory walks).
import jax
import jax.numpy as jnp
from jax.lax import all_gather


def merged_scores(local_scores):
    return jax.lax.all_gather(local_scores, "shards")  # EXPECT mesh-collective


def global_df(local_df):
    return jax.lax.psum(local_df, axis_name="shards")  # EXPECT mesh-collective


def mean_latency(lat):
    return jax.lax.pmean(lat, "shards")  # EXPECT mesh-collective


def bare_import_form(block):
    # the from-import spelling must not slip through tail matching
    return all_gather(block, "shards")  # EXPECT mesh-collective


def local_topk_is_fine(scores, k):
    return jax.lax.top_k(scores, k)


def plain_math_is_fine(x):
    return jnp.sum(x)
