# osselint: path=open_source_search_engine_tpu/parallel/fixture_clean.py
# osselint fixture — the NEGATIVE cases: idiomatic code that must lint
# clean under the virtual parallel/ path set by the pragma above.
from ..utils import threads, trace
from ..utils.lockcheck import make_lock

_lock = make_lock("fixture.peers")
peers = {}


def fetch(host, path):
    # cross-shard HTTP through the pooled transport, not urllib
    from .transport import g_transport
    return g_transport.get(host, path)


def timed_rpc():
    with trace.timed_span("rpc.search"):
        pass


def cache_by_key(conf, store):
    # identity-stable key, not id()
    store[(conf.name, conf.generation)] = 1


def register_peer(name):
    with _lock:
        peers[name] = 1  # mutation under the lock: fine


def snapshot():
    with _lock:
        return dict(peers)


def accumulate(x, acc=None):
    if acc is None:
        acc = []
    acc.append(x)
    return acc


def spawn_named():
    return threads.spawn("fixture-worker", snapshot)


def guarded_cleanup(f):
    try:
        f()
    except OSError:
        pass  # specific exception: allowed


def counted_failure(f, stats):
    try:
        f()
    except Exception as exc:
        stats.count("fixture.errors")
        return exc


def waived_sleep():
    import time
    with _lock:
        time.sleep(0)  # osselint: ignore[blocking-under-lock] — test fixture


def budgeted_wait(timeout):
    # deadlines through the helper; now - t0 durations stay legal
    import time
    from ..utils.deadline import Deadline
    dl = Deadline.after(timeout)
    t0 = time.monotonic()
    while not dl.expired() and dl.remaining() > 0:
        break
    return time.monotonic() - t0


def measured_interval(run):
    # latency measurement through the dual-plane helpers: timed_span
    # measures for you; trace.record attributes a self-timed interval
    # (both feed g_stats AND the waterfall, so no adhoc-timing)
    import time
    with trace.timed_span("fixture.run"):
        run()
    t0 = time.perf_counter()
    run()
    trace.record("fixture.run2", t0)
