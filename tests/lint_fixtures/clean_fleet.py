# osselint: path=open_source_search_engine_tpu/parallel/fleet.py
# osselint fixture — the fleet plane IS the sanctioned owner of child
# processes and signals: the same shapes violations_proc.py flags must
# produce zero findings here.
import os
import signal
import subprocess
import sys


def spawn_node(argv):
    return subprocess.Popen([sys.executable] + argv,
                            start_new_session=True)


def kill_node(pid):
    os.kill(pid, signal.SIGKILL)


def reap_group(pid):
    os.killpg(pid, signal.SIGKILL)
