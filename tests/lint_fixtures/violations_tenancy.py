# osselint: path=open_source_search_engine_tpu/serve/fixture_tenancy.py
"""residency-bypass fixture: HBM-resident state minted behind the
ResidencyManager's back — a hand-built DeviceIndex the tenant LRU
can never evict and a hand-spun ResidentLoop delColl can never stop."""
from ..query.devindex import DeviceIndex
from ..query.resident import ResidentLoop


def serve_collection(coll):
    di = DeviceIndex(coll)  # EXPECT residency-bypass
    loop = ResidentLoop(lambda: di, lambda: 0)  # EXPECT residency-bypass
    return loop
