# osselint: path=open_source_search_engine_tpu/query/fixture_jit.py
# osselint jit-family fixture — the pragma above re-scopes it to a
# virtual query/ path so the jit-* rules apply. Each "EXPECT rule"
# comment marks the line a finding must anchor to. Never scanned by
# the real linter (lint_fixtures/ is excluded from directory walks).
import jax
import jax.numpy as jnp
import numpy as np

TUNING = {"tilt": 1.5}


def _score_impl(x, k):
    return jnp.sum(x[:k])


_score = jax.jit(_score_impl, static_argnames=("k",))


def _update_impl(state, x):
    return state + x


_update = jax.jit(_update_impl, donate_argnums=(0,))


@jax.jit
def _tilted(x):
    return x * TUNING["tilt"]  # EXPECT jit-mutable-closure


def unstable_statics(xs, q):
    n = len(xs)
    a = _score(q, k=n)  # EXPECT jit-unstable-static
    b = _score(q, k=1.5)  # EXPECT jit-unstable-static
    return a, b


def wrap_per_call(x):
    fn = jax.jit(lambda v: v * 2)  # EXPECT jit-in-body
    return fn(x)


def donate_then_read(state, x):
    out = _update(state, x)
    return out + state  # EXPECT jit-donated-reuse


def hidden_sync(q):
    s = _score(q, k=8)
    lo = float(s)  # EXPECT jit-implicit-transfer
    hi = np.asarray(s)  # EXPECT jit-implicit-transfer
    return lo, hi
