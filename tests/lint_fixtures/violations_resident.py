# osselint: path=open_source_search_engine_tpu/query/resident.py
# osselint fixture — the pragma re-scopes this file to the resident
# serving loop, where the device-sync rule's EXTENDED fence applies:
# the enqueue path may neither sync the host (device_get /
# block_until_ready) nor stage device buffers (device_put / asarray —
# issue_batch in devindex.py owns host→device transfers). Never
# scanned by the real linter (lint_fixtures/ is excluded from walks).
import jax
import jax.numpy as jnp


def submit_bad(queue, arrs):
    staged = jax.device_put(arrs)  # EXPECT device-sync
    lane = jnp.asarray(arrs)  # EXPECT device-sync
    queue.append((staged, lane))


def collect_bad(wave):
    out = jax.device_get(wave)  # EXPECT device-sync
    wave.block_until_ready()  # EXPECT device-sync
    return out
