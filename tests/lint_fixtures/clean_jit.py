# osselint: path=open_source_search_engine_tpu/query/fixture_jit_ok.py
# negative fixture for the jit-* family: the blessed idioms — bucketed
# statics, a memoized jit factory, donate-with-rebind — must stay
# finding-free. Never scanned by the real linter.
from functools import lru_cache, partial

import jax
import jax.numpy as jnp


def _bucket(n, floor=8):
    b = floor
    while b < n:
        b *= 2
    return b


def _score_impl(x, k):
    return jnp.sum(x[:k])


_score = jax.jit(_score_impl, static_argnames=("k",))
_update = jax.jit(lambda s, x: s + x, donate_argnums=(0,))


def bucketed_static(xs, q):
    k = _bucket(len(xs))
    return _score(q, k=k)


@lru_cache(maxsize=None)
def make_kernel(k):
    return jax.jit(partial(_score_impl, k=k))


def donate_with_rebind(state, x):
    state = _update(state, x)
    return state
