# osselint: path=open_source_search_engine_tpu/query/devindex.py
# stats-cardinality clean counterpart: literal names, a module-level
# lookup table over a bounded bucket set, and dynamic *values* (not
# names) are all fine — the name space stays enumerable.

_WAVE_STAT = {n: f"devindex.wave_n{n}" for n in (1, 2, 4, 8)}


def _nbucket(n):
    for b in (1, 2, 4, 8):
        if n <= b:
            return b
    return 8


def collect(waves, nbytes, g_stats, trace):
    stat = _WAVE_STAT.get(_nbucket(len(waves)))
    if stat is not None:
        trace.record(stat, 0, 1)
    g_stats.count("devindex.rounds")
    g_stats.gauge("devindex.bytes", nbytes)  # dynamic value, fixed name
