# osselint: path=open_source_search_engine_tpu/build/proc_fixture.py
# osselint fixture — proc-spawn cases: child processes and signals
# outside parallel/fleet.py and utils/chaos.py. Legal shapes (method
# calls on a Popen handle someone owns, subprocess.run) ride along
# unmarked to pin that the rule does NOT overreach.
import os
import subprocess
from subprocess import Popen


def spawn_raw(argv):
    return subprocess.Popen(argv)  # EXPECT proc-spawn


def spawn_imported(argv):
    return Popen(argv)  # EXPECT proc-spawn


def shoot(pid):
    os.kill(pid, 9)  # EXPECT proc-spawn


def shoot_group(pid):
    os.killpg(pid, 9)  # EXPECT proc-spawn


def split():
    return os.fork()  # EXPECT proc-spawn


def legal_shapes(argv, proc):
    # a handle someone owns may be signalled; run() is synchronous and
    # cannot leak an orphan past its own return
    proc.kill()
    proc.send_signal(15)
    return subprocess.run(argv, check=False)
