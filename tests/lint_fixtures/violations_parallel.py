# osselint: path=open_source_search_engine_tpu/parallel/fixture.py
# osselint fixture — the pragma above re-scopes it to a virtual
# parallel/ path so every rule applies. Each "EXPECT rule" comment
# marks the line a finding must anchor to. Never scanned by the real
# linter (lint_fixtures/ is excluded from directory walks).
import threading
import time
import urllib.request  # EXPECT urllib-in-parallel

from ..utils.ttlcache import TtlCache

_lock = threading.Lock()
peers = {}


def fetch(url):
    return urllib.request.urlopen(url)  # EXPECT urllib-in-parallel


def make_cache():
    return TtlCache(max_items=64)  # EXPECT ttlcache-offplane


def timed_rpc():
    with g_stats.timed("rpc"):  # EXPECT bare-stats-timed
        pass


def cache_by_id(conf, store):
    store[id(conf)] = 1  # EXPECT id-key
    key = (1, tuple(id(s) for s in store))  # EXPECT id-key
    return key


def hold_and_sleep():
    with _lock:
        time.sleep(0.5)  # EXPECT blocking-under-lock


def swallow():
    try:
        fetch("x")
    except Exception:  # EXPECT silent-except
        pass


def swallow_bare():
    try:
        fetch("x")
    except:  # EXPECT silent-except
        raise


def accumulate(x, acc=[]):  # EXPECT mutable-default
    acc.append(x)
    return acc


def spawn_raw():
    t = threading.Thread(target=fetch)  # EXPECT thread-spawn
    return t


def register_peer(name):
    peers[name] = 1  # EXPECT locked-global


def pull_scores(x):
    import jax
    return jax.device_get(x)  # EXPECT device-sync


def hand_rolled_deadline(timeout):
    deadline = time.time() + timeout  # EXPECT bare-deadline
    left = deadline - time.monotonic()  # EXPECT bare-deadline
    return left


def adhoc_latency(t0):
    elapsed = time.perf_counter() - t0  # EXPECT adhoc-timing
    wall_ms = 1000 * (time.time() - t0)  # EXPECT adhoc-timing
    return elapsed, wall_ms
