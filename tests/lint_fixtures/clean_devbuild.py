# osselint: path=open_source_search_engine_tpu/build/devbuild.py
# clean twin of violations_devbuild.py: the same stages expressed as
# on-device orderings — jnp sorts and segmented scans are exactly what
# the host-sort fence steers toward, so none of these may fire.
import jax.numpy as jnp
import numpy as np


def merge_runs(keys):
    order = jnp.argsort(keys, stable=True)
    return keys[order]


def doc_index(d_lo, d_hi):
    od = jnp.lexsort((d_lo, d_hi))
    return od


def rank_terms(termids):
    return jnp.sort(termids)


def stage(host_rows):
    # plain staging math stays host-side without tripping the fence
    return np.concatenate([host_rows, host_rows]).astype(np.uint32)
