# osselint: path=open_source_search_engine_tpu/serve/fixture_routes.py
# osselint fixture — the pragma re-scopes this file to serve/, where
# the admission-bypass rule applies: routes must go through
# AdmissionGate.admit() before handing work to the dispatch planes
# (QueryBatcher / ResidentLoop). Never scanned by the real linter
# (lint_fixtures/ is excluded from walks).
from ..query.engine import get_resident_loop


def page_search_bad(self, query, q):
    # handing the batcher work straight from a route: no tier, no
    # bound, no shed accounting
    return self._batcher.search(("main", 10, 0), q)  # EXPECT admission-bypass


def page_direct_resident_bad(coll, plans):
    return get_resident_loop(coll).submit(plans)  # EXPECT admission-bypass


def page_tainted_resident_bad(coll, plans):
    loop = get_resident_loop(coll)
    return loop.submit(plans)  # EXPECT admission-bypass


def _render_search(self, query, q, n, s):
    # the sanctioned call site: runs under the admitted token the
    # serve edge took from AdmissionGate.admit()
    return self._batcher.search((query.get("c", "main"), n, s), q)
