"""Multi-process host plane tests — the SURVEY §4.5 model made real:
N node processes on loopback, a client routing by the shared key→shard
maps, twin failover, degraded answers, and restart catch-up.

Reference behaviors pinned here: Msg1 write-to-all-twins with
retry-forever (Msg1.cpp:20), Multicast serving-twin pick with reroute
(Multicast.cpp:520), PingServer liveness (PingServer.h:61), and the
faq.html:586 recovery story (a restarted twin serves again).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = str(__import__("pathlib").Path(__file__).resolve().parent.parent)

N_SHARDS = 2
N_REPLICAS = 2

DOCS = {
    f"http://s.test/doc{i}": (
        f"<html><head><title>Doc {i} cluster</title></head><body>"
        f"<p>cluster words shared everywhere token{i}.</p></body></html>")
    for i in range(12)
}


def _wait_port(port: int, timeout: float = 60.0) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/rpc/ping", data=b"{}",
                    timeout=1.0) as r:
                if json.load(r).get("ok"):
                    return
        except Exception:
            time.sleep(0.3)
    raise TimeoutError(f"node on {port} never came up")


class Nodes:
    """Spawn/kill/restart the node processes of a loopback cluster."""

    def __init__(self, tmp_path, ports):
        self.tmp_path = tmp_path
        self.ports = ports  # [shard][replica]
        self.procs = {}

    def dir_of(self, s, r):
        return str(self.tmp_path / f"node_s{s}r{r}")

    def start(self, s, r):
        proc = subprocess.Popen(
            [sys.executable, "-m", "open_source_search_engine_tpu",
             "node", "--dir", self.dir_of(s, r),
             "--port", str(self.ports[s][r])],
            env={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                 "PATH": "/usr/bin:/bin", "HOME": str(self.tmp_path)},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.procs[(s, r)] = proc

    def kill(self, s, r):
        p = self.procs.pop((s, r))
        p.send_signal(signal.SIGKILL)
        p.wait()

    def stop_all(self):
        for p in self.procs.values():
            p.kill()
        for p in self.procs.values():
            p.wait()


@pytest.fixture
def cluster(tmp_path):
    import socket

    from open_source_search_engine_tpu.parallel.cluster import (
        ClusterClient, HostsConf)

    ports = []
    socks = []
    for s in range(N_SHARDS):
        row = []
        for r in range(N_REPLICAS):
            sk = socket.socket()
            sk.bind(("127.0.0.1", 0))
            row.append(sk.getsockname()[1])
            socks.append(sk)
        ports.append(row)
    for sk in socks:
        sk.close()

    nodes = Nodes(tmp_path, ports)
    for s in range(N_SHARDS):
        for r in range(N_REPLICAS):
            nodes.start(s, r)
    for s in range(N_SHARDS):
        for r in range(N_REPLICAS):
            _wait_port(ports[s][r])

    conf = HostsConf.parse(
        f"num-mirrors: {N_REPLICAS - 1}\n" + "\n".join(
            f"127.0.0.1:{ports[s][r]}"
            for r in range(N_REPLICAS) for s in range(N_SHARDS)))
    client = ClusterClient(conf, use_heartbeat=False)
    try:
        yield nodes, client
    finally:
        client.close()
        nodes.stop_all()


def _search_urls(client, q, **kw):
    kw.setdefault("site_cluster", False)
    res = client.search(q, **kw)
    return res, {r.url for r in res.results}


@pytest.mark.slow
def test_cluster_end_to_end(cluster):
    nodes, client = cluster

    # --- writes fan out to all twins; search spans shards ---
    for url, html in DOCS.items():
        client.index_document(url, html)
    assert client.pending_writes == 0
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert res.total_matches == len(DOCS)
    assert not res.degraded
    assert urls == set(DOCS)

    # --- kill ONE twin of shard 0: reroute serves everything ---
    nodes.kill(0, 0)
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert res.total_matches == len(DOCS)
    assert not res.degraded          # the twin covers the shard
    assert urls == set(DOCS)

    # a write while the twin is down parks in the retry queue
    client.index_document(
        "http://s.test/late",
        "<html><head><title>Late arrival</title></head><body>"
        "<p>cluster latecomer token99.</p></body></html>")
    res, urls = _search_urls(client, "latecomer", topk=5)
    late_shard = int(client.hostmap.shard_of_docid(
        __import__("open_source_search_engine_tpu.utils.ghash",
                   fromlist=["doc_id"]).doc_id("http://s.test/late")))
    assert "http://s.test/late" in urls

    # --- kill the OTHER twin too: whole shard down → degraded ---
    nodes.kill(0, 1)
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert res.degraded
    assert 0 < len(urls) < len(DOCS)

    # --- restart one twin: its durable state + the retry queue catch
    # it up; the shard serves again ---
    nodes.start(0, 0)
    _wait_port(nodes.ports[0][0])
    deadline = time.time() + 30
    while client.pending_writes and time.time() < deadline:
        time.sleep(0.5)
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert not res.degraded
    assert urls == set(DOCS)
    if late_shard == 0:
        res, urls = _search_urls(client, "latecomer", topk=5)
        assert "http://s.test/late" in urls


@pytest.mark.slow
def test_parm_broadcast_reaches_all_nodes_and_survives(cluster):
    """The 0x3f parm broadcast: host0's client sequences a live parm
    update to EVERY node (all shards, all twins), a dead node catches
    up through the retry queue when it returns, and the value survives
    a node restart (persisted coll.conf)."""
    nodes, client = cluster
    import urllib.request

    def parm_on(s, r, name):
        req = urllib.request.Request(
            f"http://127.0.0.1:{nodes.ports[s][r]}/rpc/conf",
            data=b"{}", method="POST")
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            return json.load(resp)["conf"][name]

    client.attach_conf_name = None  # doc marker only
    client.broadcast_parm("spider_delay_ms", 4321)
    for s in range(N_SHARDS):
        for r in range(N_REPLICAS):
            assert parm_on(s, r, "spider_delay_ms") == 4321, (s, r)

    # dead node: update parks in its ordered queue, applies on return
    nodes.kill(0, 1)
    client.check_hosts()
    client.broadcast_parm("spider_delay_ms", 9999)
    assert parm_on(1, 0, "spider_delay_ms") == 9999
    nodes.start(0, 1)
    _wait_port(nodes.ports[0][1])
    t0 = time.time()
    while time.time() - t0 < 30:
        client.check_hosts()
        if client.pending_writes == 0 and \
                parm_on(0, 1, "spider_delay_ms") == 9999:
            break
        time.sleep(0.5)
    assert parm_on(0, 1, "spider_delay_ms") == 9999

    # restart a node with no pending queue: the persisted conf serves
    nodes.kill(1, 0)
    nodes.start(1, 0)
    _wait_port(nodes.ports[1][0])
    assert parm_on(1, 0, "spider_delay_ms") == 9999
