"""Multi-process host plane tests — the SURVEY §4.5 model made real:
N node processes on loopback, a client routing by the shared key→shard
maps, twin failover, degraded answers, and restart catch-up.

Reference behaviors pinned here: Msg1 write-to-all-twins with
retry-forever (Msg1.cpp:20), Multicast serving-twin pick with reroute
(Multicast.cpp:520), PingServer liveness (PingServer.h:61), and the
faq.html:586 recovery story (a restarted twin serves again).

The processes come from the fleet plane (`parallel.fleet.FleetManager`,
supervise=False so THESE tests control death and rebirth by hand) —
the osselint ``proc-spawn`` rule keeps raw Popen/os.kill out of here.
"""

import json
import urllib.request

import pytest

from tests.polling import wait_until

N_SHARDS = 2
N_REPLICAS = 2

DOCS = {
    f"http://s.test/doc{i}": (
        f"<html><head><title>Doc {i} cluster</title></head><body>"
        f"<p>cluster words shared everywhere token{i}.</p></body></html>")
    for i in range(12)
}


@pytest.fixture
def cluster(tmp_path):
    from open_source_search_engine_tpu.parallel.cluster import ClusterClient
    from open_source_search_engine_tpu.parallel.fleet import FleetManager

    fm = FleetManager(tmp_path / "fleet", n_shards=N_SHARDS,
                      n_replicas=N_REPLICAS, supervise=False)
    try:
        fm.start_all()
        client = ClusterClient(fm.conf, use_heartbeat=False)
        try:
            yield fm, client
        finally:
            client.close()
    finally:
        fm.shutdown()
        assert fm.surviving_pids() == []


def _kill(fm, s, r):
    """SIGKILL a node and wait until the corpse is observable (so a
    later start_node never races the not-yet-reaped pid)."""
    fm.kill(s, r)
    wait_until(lambda: not fm.alive(s, r), timeout=10.0,
               desc=f"node s{s}r{r} dead after SIGKILL")


def _search_urls(client, q, **kw):
    kw.setdefault("site_cluster", False)
    res = client.search(q, **kw)
    return res, {r.url for r in res.results}


@pytest.mark.slow
def test_cluster_end_to_end(cluster):
    fm, client = cluster

    # --- writes fan out to all twins; search spans shards ---
    for url, html in DOCS.items():
        client.index_document(url, html)
    assert client.pending_writes == 0
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert res.total_matches == len(DOCS)
    assert not res.degraded
    assert urls == set(DOCS)

    # --- kill ONE twin of shard 0: reroute serves everything ---
    _kill(fm, 0, 0)
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert res.total_matches == len(DOCS)
    assert not res.degraded          # the twin covers the shard
    assert urls == set(DOCS)

    # a write while the twin is down parks in the retry queue
    client.index_document(
        "http://s.test/late",
        "<html><head><title>Late arrival</title></head><body>"
        "<p>cluster latecomer token99.</p></body></html>")
    res, urls = _search_urls(client, "latecomer", topk=5)
    late_shard = int(client.hostmap.shard_of_docid(
        __import__("open_source_search_engine_tpu.utils.ghash",
                   fromlist=["doc_id"]).doc_id("http://s.test/late")))
    assert "http://s.test/late" in urls

    # --- kill the OTHER twin too: whole shard down → degraded ---
    _kill(fm, 0, 1)
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert res.degraded
    assert 0 < len(urls) < len(DOCS)

    # --- restart one twin: its durable state + the retry queue catch
    # it up; the shard serves again ---
    fm.start_node(0, 0, wait=True)
    wait_until(lambda: client.pending_writes == 0, timeout=30.0,
               interval=0.1, desc="retry queue drained into reborn twin")
    res, urls = _search_urls(client, "cluster words", topk=12)
    assert not res.degraded
    assert urls == set(DOCS)
    if late_shard == 0:
        res, urls = _search_urls(client, "latecomer", topk=5)
        assert "http://s.test/late" in urls


@pytest.mark.slow
def test_parm_broadcast_reaches_all_nodes_and_survives(cluster):
    """The 0x3f parm broadcast: host0's client sequences a live parm
    update to EVERY node (all shards, all twins), a dead node catches
    up through the retry queue when it returns, and the value survives
    a node restart (persisted coll.conf)."""
    fm, client = cluster

    def parm_on(s, r, name):
        req = urllib.request.Request(
            f"http://{fm.addr(s, r)}/rpc/conf",
            data=b"{}", method="POST")
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            return json.load(resp)["conf"][name]

    client.attach_conf_name = None  # doc marker only
    client.broadcast_parm("spider_delay_ms", 4321)
    for s in range(N_SHARDS):
        for r in range(N_REPLICAS):
            assert parm_on(s, r, "spider_delay_ms") == 4321, (s, r)

    # dead node: update parks in its ordered queue, applies on return
    _kill(fm, 0, 1)
    client.check_hosts()
    client.broadcast_parm("spider_delay_ms", 9999)
    assert parm_on(1, 0, "spider_delay_ms") == 9999
    fm.start_node(0, 1, wait=True)

    def caught_up():
        client.check_hosts()
        return (client.pending_writes == 0
                and parm_on(0, 1, "spider_delay_ms") == 9999)

    wait_until(caught_up, timeout=30.0, interval=0.1,
               desc="parked parm applied on the reborn node")
    assert parm_on(0, 1, "spider_delay_ms") == 9999

    # restart a node with no pending queue: the persisted conf serves
    _kill(fm, 1, 0)
    fm.start_node(1, 0, wait=True)
    assert parm_on(1, 0, "spider_delay_ms") == 9999
