"""Sampling profiler tests (``utils.profiler``, Profiler.cpp analog).

Pins the contract /admin/profiler relies on: start/stop are idempotent
(double-start keeps ONE sampler thread, double-stop is safe), a busy
thread's frames show up in both the self and cumulative histograms,
and reset() zeroes the aggregation without touching a running sampler.
"""

import threading
import time

from open_source_search_engine_tpu.utils.profiler import SamplingProfiler


def _burn_inner(n=20_000):
    x = 0
    for i in range(n):
        x += i * i
    return x


def _burn_loop(stop):
    while not stop.is_set():
        _burn_inner()


def test_start_stop_idempotent():
    p = SamplingProfiler(interval_s=0.002)
    assert not p.running
    p.stop()  # stop before any start: no-op
    p.start()
    first = p._thread
    p.start()  # second start keeps the SAME sampler thread
    assert p._thread is first and p.running
    p.stop()
    assert not p.running and p._thread is None
    p.stop()  # double-stop: no-op


def test_busy_thread_frames_aggregated():
    p = SamplingProfiler(interval_s=0.001)
    stop = threading.Event()
    th = threading.Thread(target=_burn_loop, args=(stop,), daemon=True)
    th.start()
    p.start()
    try:
        deadline = time.monotonic() + 5.0
        while p.samples < 20 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        p.stop()
        stop.set()
        th.join(2.0)
    assert p.samples >= 20
    cum_funcs = {k[0] for k in p.cum_hits}
    assert "_burn_loop" in cum_funcs
    # the leaf shows up as SELF time, and the report carries the frac
    self_funcs = {k[0] for k in p.self_hits}
    assert "_burn_inner" in self_funcs
    rep = p.report()
    assert rep["samples"] == p.samples and not rep["running"]
    assert any(r["func"] == "_burn_inner" and r["hits"] > 0
               for r in rep["top_self"])
    assert all(0.0 <= r["frac"] <= 1.0 for r in rep["top_cumulative"])


def test_reset_zeroes_aggregation():
    p = SamplingProfiler(interval_s=0.001)
    stop = threading.Event()
    th = threading.Thread(target=_burn_loop, args=(stop,), daemon=True)
    th.start()
    p.start()
    try:
        deadline = time.monotonic() + 5.0
        while p.samples < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        p.stop()
        stop.set()
        th.join(2.0)
    assert p.samples >= 5
    p.reset()
    assert p.samples == 0 and not p.self_hits and not p.cum_hits
    assert p.report()["top_self"] == []
