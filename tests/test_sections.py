"""Sections-lite: tag-path section ids, sectiondb votes, boilerplate
demotion (reference Sections.cpp/h:330 — section tree + cross-page dup
votes demoting repeated chrome at scoring time).
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.build.tokenizer import tokenize_html
from open_source_search_engine_tpu.index import posdb
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.index.sectiondb import Sectiondb
from open_source_search_engine_tpu.query import engine

NAV = ('<nav><ul><li><a href="/x">zebra products catalog</a></li>'
       "<li>zebra support pages</li></ul></nav>")


def _page(i, body):
    return (f"<html><head><title>Page {i}</title></head><body>{NAV}"
            f"<div><p>{body}</p></div></body></html>")


def test_section_ids_stable_across_pages():
    t1 = tokenize_html(_page(1, "alpha beta gamma"), "http://s.test/1")
    t2 = tokenize_html(_page(2, "delta epsilon zeta"), "http://s.test/2")
    s1 = docproc.doc_section_hashes(t1)
    s2 = docproc.doc_section_hashes(t2)
    # the identical nav produces an identical (section id, content
    # hash) on both pages; the differing body paragraphs do not
    shared = set(s1.items()) & set(s2.items())
    assert shared, "identical nav must hash identically"
    assert set(s1.values()) != set(s2.values())


def test_sectiondb_votes_and_removal(tmp_path):
    db = Sectiondb(tmp_path)
    for i in range(3):
        db.add_page_sections("s.test", f"http://s.test/{i}", [0xABC])
    assert db.page_count("s.test", 0xABC) == 3
    assert db.boiler_set("s.test", [0xABC, 0xDEF]) == {0xABC}
    db.remove_page_sections("s.test", "http://s.test/0", [0xABC])
    db.remove_page_sections("s.test", "http://s.test/1", [0xABC])
    assert db.page_count("s.test", 0xABC) == 1
    assert db.boiler_set("s.test", [0xABC]) == set()


def test_boilerplate_demotes_nav_tokens(tmp_path):
    """After enough sibling pages, nav words get docked spam ranks
    while body words keep 15 — and ranking prefers a body hit."""
    from open_source_search_engine_tpu.index.sectiondb import \
        BOILER_SPAMRANK
    coll = Collection("sec", tmp_path)
    coll.conf.pqr_enabled = False
    for i in range(4):
        docproc.index_document(coll, f"http://s.test/chrome{i}",
                               _page(i, f"filler body words number{i}"))
    # a later page whose BODY mentions zebra (nav is boilerplate now)
    ml_body = docproc.index_document(
        coll, "http://s.test/body",
        _page(9, "the zebra animal gallops across plains"))
    f = posdb.unpack(ml_body.posdb_keys)
    zebra_tid = np.uint64(__import__(
        "open_source_search_engine_tpu.utils.ghash",
        fromlist=["x"]).term_id("zebra"))
    z = f["termid"] == zebra_tid
    spam = f["wordspamrank"][z]
    # body occurrence clean (15), nav occurrences docked
    assert spam.max() == 15
    assert spam.min() == BOILER_SPAMRANK
    assert ml_body.boiler_sections  # recorded in the meta list
    # ranking: the body page outscores a chrome-only page for "zebra"
    r = engine.search(coll, "zebra", topk=10, site_cluster=False,
                      with_snippets=False)
    assert r.results[0].url == "http://s.test/body"


def test_tombstones_regenerate_docked_postings(tmp_path):
    """Removal after MORE votes accumulated must still annihilate —
    the boiler set is frozen in the TitleRec at add time."""
    coll = Collection("sec2", tmp_path)
    for i in range(3):
        docproc.index_document(coll, f"http://s.test/p{i}",
                               _page(i, f"unique body {i}"))
    # p2 was indexed when nav was already boilerplate (2 prior pages)
    docproc.index_document(coll, "http://s.test/late",
                           _page(7, "late page body words"))
    # more pages pile on votes AFTER "late" was indexed
    for i in range(3, 6):
        docproc.index_document(coll, f"http://s.test/p{i}",
                               _page(i, f"unique body {i}"))
    assert docproc.remove_document(coll, "http://s.test/late")
    r = engine.search(coll, "late page", topk=5, with_snippets=False)
    assert all(res.url != "http://s.test/late" for res in r.results)
    # the annihilation was exact: no orphan postings for its unique word
    r2 = engine.search(coll, "late", topk=5, with_snippets=False)
    assert r2.total_matches == 0


def test_sharded_sections_route_by_site(tmp_path):
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("sec3", tmp_path, n_shards=2)
    for i in range(4):
        sc.index_document(f"http://s.test/n{i}",
                          _page(i, f"sharded body {i}"))
    ml = sc.index_document("http://s.test/check",
                           _page(9, "checking boiler state here"))
    assert ml.boiler_sections
    sect_shard = int(sc.hostmap.shard_of_site("s.test"))
    assert sc.shards[sect_shard].sectiondb.page_count(
        "s.test", ml.boiler_sections[0]) >= 4
    other = sc.shards[1 - sect_shard].sectiondb
    assert other.page_count("s.test", ml.boiler_sections[0]) == 0
