"""Rdb corruption detection + twin patching (Msg5 error correction).

Reference: ``Msg5.h:50`` / developer.html "Rdb Error Correction" — reads
verify list integrity (out-of-order keys, bad maps); corrupt data is
dropped and patched from the twin host. Ours: runs carry whole-file
CRCs + structural checks, verified at load and on demand (``scrub``);
corrupt runs are quarantined (search degrades but serves) and a twin
rebuild (``resync_replica`` in-process / ``/rpc/heal`` cross-process)
restores byte-identical state.
"""

import json

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index import rdblite
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.index.rdblite import (CorruptRunError,
                                                         Rdb, Run,
                                                         keys_sorted)

KD = np.dtype([("n0", "<u8"), ("n1", "<u8")], align=False)


def _mk_keys(vals):
    k = np.zeros(len(vals), KD)
    k["n1"] = vals
    k["n0"] = 1
    return k


def _flip_byte(path, offset=-3):
    raw = bytearray(path.read_bytes())
    raw[offset] ^= 0xFF
    path.write_bytes(bytes(raw))


def test_keys_sorted_check():
    assert keys_sorted(_mk_keys([1, 2, 3]))
    assert not keys_sorted(_mk_keys([1, 3, 2]))
    # order decided by the most-significant field (n1) first
    k = _mk_keys([5, 5])
    k["n0"] = [2, 1]
    assert not keys_sorted(k)


def test_crc_written_and_verified(tmp_path):
    rdb = Rdb("t", tmp_path, KD)
    rdb.add(_mk_keys([3, 1, 2]))
    run = rdb.dump()
    meta = json.loads((run.path / "meta.json").read_text())
    assert "keys_crc" in meta
    Run(run.path).verify()  # clean run verifies


def test_corrupt_run_quarantined_on_load(tmp_path):
    rdb = Rdb("t", tmp_path, KD)
    rdb.add(_mk_keys(range(100)))
    run = rdb.dump()
    rdb.add(_mk_keys(range(100, 150)))
    rdb.dump()
    _flip_byte(run.path / "keys.npy")
    rdb2 = Rdb("t", tmp_path, KD)
    # the corrupt run is quarantined; the healthy one still serves
    assert len(rdb2.quarantined) == 1
    assert len(rdb2.runs) == 1
    assert len(rdb2.get_all()) == 50
    assert (run.path.parent / (run.path.name + ".corrupt")).exists()


def test_scrub_detects_later_corruption(tmp_path):
    rdb = Rdb("t", tmp_path, KD)
    rdb.add(_mk_keys(range(64)))
    run = rdb.dump()
    assert rdb.scrub() == []
    _flip_byte(run.path / "keys.npy")
    bad = rdb.scrub()
    assert len(bad) == 1 and not rdb.runs
    assert rdb.quarantined == bad


def test_data_crc_covers_payloads(tmp_path):
    rdb = Rdb("t", tmp_path, KD, has_data=True)
    rdb.add(_mk_keys([1, 2]), [b"hello", b"world"])
    run = rdb.dump()
    _flip_byte(run.path / "data.npy")
    with pytest.raises(CorruptRunError):
        Run(run.path)


def test_replace_with_heals(tmp_path):
    src = Rdb("s", tmp_path / "a", KD)
    src.add(_mk_keys(range(10)))
    src.dump()
    dst = Rdb("s", tmp_path / "b", KD)
    dst.add(_mk_keys(range(99)))
    dst.dump()
    dst.replace_with(src.get_all())
    assert np.array_equal(dst.get_all().keys, src.get_all().keys)


def _index_corpus(target, n=12):
    for i in range(n):
        target_index = getattr(target, "index_document", None)
        html = (f"<html><title>doc {i}</title><body>"
                f"<p>healing corpus words number{i}.</p></body></html>")
        if target_index and not isinstance(target, Collection):
            target.index_document(f"http://site{i % 3}.test/p{i}", html)
        else:
            docproc.index_document(target, f"http://site{i % 3}.test/p{i}",
                                   html)


def test_sharded_resync_replica(tmp_path):
    """Corrupt one twin's posdb run → scrub quarantines + heals it from
    the sibling; queries on the healed replica match the healthy one."""
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("t", tmp_path, n_shards=2, n_replicas=2)
    _index_corpus(sc)
    for row in sc.grid:
        for c in row:
            c.dump_all()
    victim = sc.grid[0][1]
    run = victim.posdb.runs[0]
    _flip_byte(run.path / "keys.npy")
    # reload the victim from disk the way a restarted node would
    report = None
    victim.posdb.runs = []
    victim.posdb._next_run_id = 0
    victim.posdb.quarantined = []
    victim.posdb._load_existing_runs()
    assert victim.posdb.quarantined, "corruption must be detected"
    report = sc.scrub()  # heals via resync_replica
    healthy = sc.grid[0][0]
    assert np.array_equal(victim.posdb.get_all().keys,
                          healthy.posdb.get_all().keys)
    assert victim.num_docs == healthy.num_docs


def test_resync_catches_up_recovered_twin(tmp_path):
    """A twin dead during writes rejoins via resync and serves the
    missed documents (the reference's recovered-host catch-up)."""
    from open_source_search_engine_tpu.parallel.sharded import \
        ShardedCollection
    sc = ShardedCollection("t", tmp_path, n_shards=1, n_replicas=2)
    _index_corpus(sc, n=4)
    # twin 1 "dies"; wipe it to simulate lost state, then mark dead
    for rdb in sc.grid[0][1].rdbs().values():
        rdb.wipe()
    sc.grid[0][1].num_docs = 0
    sc.hostmap.mark_dead(0, 1)
    assert sc.resync_replica(0, 1)
    assert bool(sc.hostmap.alive[0, 1])
    assert sc.grid[0][1].num_docs == sc.grid[0][0].num_docs
    assert np.array_equal(sc.grid[0][1].posdb.get_all().keys,
                          sc.grid[0][0].posdb.get_all().keys)


def test_cluster_heal_from_twin(tmp_path):
    """Cross-process twin patch: /rpc/pull + heal_from rebuilds a
    node's Rdbs byte-identically over the RPC plane."""
    from open_source_search_engine_tpu.parallel.cluster import \
        ShardNodeServer
    a = ShardNodeServer(tmp_path / "a")
    b = ShardNodeServer(tmp_path / "b")
    _index_corpus(a.coll)
    a.coll.dump_all()
    a.start()
    try:
        addr = f"127.0.0.1:{a.port}"
        n = b.heal_from(addr)
        assert n == len(b.coll.rdbs())
        assert b.coll.num_docs == a.coll.num_docs
        assert np.array_equal(b.coll.posdb.get_all().keys,
                              a.coll.posdb.get_all().keys)
        assert np.array_equal(b.coll.titledb.get_all().keys,
                              a.coll.titledb.get_all().keys)
        # payloads too (titlerec content survives the wire)
        d = docproc.get_document(b.coll, url="http://site0.test/p0")
        assert d and "healing corpus" in d["text"]
        # speller dictionary travels with the heal
        assert b.coll.speller.counts == a.coll.speller.counts
    finally:
        a.stop()


def test_heal_single_cut_replays_pull_window_writes(tmp_path,
                                                    monkeypatch):
    """Writes delivered to a healing node DURING the pull window must
    survive the snapshot apply (the heal buffers and replays them); the
    snapshot itself arrives as one consistent cut (/rpc/pull-all)."""
    from open_source_search_engine_tpu.parallel import cluster as cl

    a = cl.ShardNodeServer(tmp_path / "a")
    b = cl.ShardNodeServer(tmp_path / "b")
    _index_corpus(a.coll)
    a.coll.dump_all()
    a.start()
    real_rpc = cl._rpc

    def rpc_with_concurrent_write(addr, path, payload, timeout=10.0,
                                  niceness=0):
        # deliver a write to the HEALING node mid-pull: it lands after
        # the buffer is armed and before the snapshot applies
        b.handle("/rpc/index", {
            "url": "http://late.test/during-heal",
            "content": "<html><body>window write survives</body></html>",
        })
        return real_rpc(addr, path, payload, timeout)

    monkeypatch.setattr(cl, "_rpc", rpc_with_concurrent_write)
    try:
        n = b.heal_from(f"127.0.0.1:{a.port}")
        assert n == len(b.coll.rdbs())
        # the pulled corpus is there...
        d = docproc.get_document(b.coll, url="http://site0.test/p0")
        assert d and "healing corpus" in d["text"]
        # ...and so is the write that raced the pull
        d2 = docproc.get_document(b.coll,
                                  url="http://late.test/during-heal")
        assert d2 and "window write" in d2["text"]
        assert b.coll.num_docs == a.coll.num_docs + 1
        assert b._heal_buffer is None  # disarmed after apply
    finally:
        a.stop()
