"""Key codec tests — the model is the reference's ``rdbtest``/key unit tests
(SURVEY §4.3) plus bit-level checks against ``Posdb.h:4-50``'s documented
layout."""

import numpy as np
import pytest

from open_source_search_engine_tpu.index import posdb


def test_key_size_and_dtype():
    assert posdb.KEY_DTYPE.itemsize == 18


def test_pack_unpack_roundtrip_exhaustive_fields():
    rng = np.random.default_rng(0)
    n = 4096
    fields = dict(
        termid=rng.integers(0, 1 << 48, n, dtype=np.uint64),
        docid=rng.integers(0, 1 << 38, n, dtype=np.uint64),
        wordpos=rng.integers(0, posdb.MAXWORDPOS + 1, n, dtype=np.uint64),
        densityrank=rng.integers(0, 32, n, dtype=np.uint64),
        diversityrank=rng.integers(0, 16, n, dtype=np.uint64),
        wordspamrank=rng.integers(0, 16, n, dtype=np.uint64),
        siterank=rng.integers(0, 16, n, dtype=np.uint64),
        hashgroup=rng.integers(0, posdb.HASHGROUP_END, n, dtype=np.uint64),
        langid=rng.integers(0, 64, n, dtype=np.uint64),
        multiplier=rng.integers(0, 16, n, dtype=np.uint64),
        synform=rng.integers(0, 4, n, dtype=np.uint64),
        outlink=rng.integers(0, 2, n, dtype=np.uint64),
        shardbytermid=rng.integers(0, 2, n, dtype=np.uint64),
        delbit=rng.integers(0, 2, n, dtype=np.uint64),
    )
    keys = posdb.pack(**fields)
    out = posdb.unpack(keys)
    for name, want in fields.items():
        np.testing.assert_array_equal(out[name], want, err_msg=name)


def test_bit_positions_match_reference_layout():
    """Spot-check documented bit positions (Posdb.h layout comment):
    termid occupies n2[16:64], docid low 22 bits sit at n1[42:64],
    delbit is n0 bit 0, alignment bit n0 bit 9 is always set."""
    k = posdb.pack(termid=1, docid=1, delbit=1)
    assert int(k["n2"]) == 1 << 16
    assert int(k["n1"]) >> 42 == 1
    assert int(k["n0"]) & 1 == 1
    assert int(k["n0"]) & (1 << 9)  # alignment bit (Posdb.h setAlignmentBit)

    k2 = posdb.pack(termid=0, docid=1 << 22)  # bit 22 of docid → n2 bit 0
    assert int(k2["n2"]) == 1
    assert int(k2["n1"]) >> 42 == 0


def test_byte_image_roundtrip():
    keys = posdb.pack(
        termid=[5, 6], docid=[7, 8], wordpos=[9, 10], siterank=3
    )
    buf = posdb.to_bytes(keys)
    assert len(buf) == 36
    back = posdb.from_bytes(buf)
    np.testing.assert_array_equal(back, keys)


def test_sort_order_is_termid_docid_wordpos():
    """Reference key compare is (n2,n1,n0) high-to-low, which orders by
    termid, then docid, then wordpos — the order termlist intersection
    relies on (Posdb.cpp docIdLoop)."""
    keys = posdb.pack(
        termid=[2, 1, 1, 1], docid=[0, 5, 2, 2], wordpos=[0, 0, 9, 3]
    )
    order = posdb.sort_order(keys)
    f = posdb.unpack(keys[order])
    np.testing.assert_array_equal(f["termid"], [1, 1, 1, 2])
    np.testing.assert_array_equal(f["docid"], [2, 2, 5, 0])
    np.testing.assert_array_equal(f["wordpos"], [3, 9, 0, 0])


def test_start_end_key_bracket_termlist():
    tid = 0xABCDEF
    keys = posdb.pack(
        termid=[tid, tid, tid], docid=[0, 1 << 37, (1 << 38) - 1],
        wordpos=[0, 7, posdb.MAXWORDPOS],
    )
    lo, hi = posdb.start_key(tid), posdb.end_key(tid)
    for k in keys:
        assert (lo["n2"], lo["n1"], lo["n0"]) <= (k["n2"], k["n1"], k["n0"])
        assert (k["n2"], k["n1"], k["n0"]) <= (hi["n2"], hi["n1"], hi["n0"])


def test_shard_assignment_stable_and_balanced():
    docids = np.arange(100_000, dtype=np.uint64)
    s = posdb.shard_of_docid(docids, 8)
    s2 = posdb.shard_of_docid(docids, 8)
    np.testing.assert_array_equal(s, s2)
    counts = np.bincount(s, minlength=8)
    assert counts.min() > 0.8 * counts.max()  # balanced within 20%


def test_shard_by_termid_bit_respected():
    keys = posdb.pack(
        termid=[10, 10], docid=[99, 99], shardbytermid=[0, 1]
    )
    shards = posdb.shard_of_keys(keys, 8)
    assert shards[0] == posdb.shard_of_docid(np.uint64(99), 8)
    assert shards[1] == posdb.shard_of_termid(np.uint64(10), 8)


@pytest.mark.parametrize("field,maxval", [
    ("wordpos", posdb.MAXWORDPOS),
    ("densityrank", posdb.MAXDENSITYRANK),
    ("siterank", posdb.MAXSITERANK),
    ("langid", posdb.MAXLANGID),
])
def test_max_field_values_survive(field, maxval):
    k = posdb.pack(termid=1, docid=1, **{field: maxval})
    assert int(posdb.unpack(k)[field]) == maxval
