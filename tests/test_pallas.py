"""Fused Pallas scoring-kernel parity (interpret mode on CPU).

The fused kernels (pallas_scores.py) reimplement the scoring chain
and the FD assembly; on CPU CI they never run by default (use_fused
gates them to TPU backends), so these tests FORCE them through
interpret mode and pin them against the jnp reference path — both at
the min_scores unit seam and end-to-end through the FD route."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from open_source_search_engine_tpu.query import scorer
from open_source_search_engine_tpu.query.pallas_scores import (
    TILE_D, min_scores_fused)


def _rand_cube(rng, T, P, D, density=0.25, inlink_frac=0.1):
    wordpos = rng.integers(0, 200000, (T, P, D)).astype(np.uint32)
    hg = rng.integers(0, 11, (T, P, D)).astype(np.uint32)
    # force some inlink-text rows (spamw sqrt path + single-term pool)
    hg = np.where(rng.random((T, P, D)) < inlink_frac, 5, hg)
    den = rng.integers(1, 32, (T, P, D)).astype(np.uint32)
    spam = rng.integers(0, 16, (T, P, D)).astype(np.uint32)
    syn = rng.integers(0, 2, (T, P, D)).astype(np.uint32)
    payload = (wordpos | (hg << 18) | (den << 22) | (spam << 27)
               | (syn << 31))
    pv = rng.random((T, P, D)) < density
    cube = np.where(pv, payload, 0).astype(np.uint32)
    pv = cube != 0  # the build-side invariant the kernel relies on
    return cube, pv


class TestMinScoresFused:
    @pytest.mark.parametrize("T,seed", [(4, 0), (8, 1)])
    def test_parity_random_cube(self, T, seed):
        rng = np.random.default_rng(seed)
        P, D = 16, TILE_D * 2
        cube, pv = _rand_cube(rng, T, P, D)
        fw = (rng.random(T) * 0.5 + 0.2).astype(np.float32)
        counts = rng.random(T) < 0.7
        if not counts.any():
            counts[0] = True
        ref, _ = scorer.min_scores(jnp.asarray(cube), jnp.asarray(pv),
                                   jnp.asarray(fw),
                                   jnp.asarray(counts))
        pal = min_scores_fused(jnp.asarray(cube), jnp.asarray(fw),
                               jnp.asarray(counts), interpret=True)
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   rtol=1e-5, atol=1e-7)

    def test_parity_empty_and_degenerate(self):
        T, P, D = 4, 16, TILE_D
        cube = np.zeros((T, P, D), np.uint32)
        fw = np.full(T, 0.5, np.float32)
        counts = np.ones(T, bool)
        ref, _ = scorer.min_scores(
            jnp.asarray(cube), jnp.asarray(cube != 0),
            jnp.asarray(fw), jnp.asarray(counts))
        pal = min_scores_fused(jnp.asarray(cube), jnp.asarray(fw),
                               jnp.asarray(counts), interpret=True)
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref))


class TestFusedEndToEnd:
    def test_fd_route_matches_jnp_path(self, tmp_path):
        """Index a corpus whose common multi-term queries take the FD
        route, then compare the whole search output with the fused
        path forced (interpret) vs disabled."""
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.parallel.routecheck import \
            ROUTE_ENV, route_docs
        from open_source_search_engine_tpu.query import engine
        import open_source_search_engine_tpu.query.devindex as dv

        saved = {k: os.environ.get(k) for k in
                 list(ROUTE_ENV) + ["OSSE_PALLAS"]}
        os.environ.update(ROUTE_ENV)
        try:
            coll = Collection("p", str(tmp_path))
            docproc.index_batch(coll, route_docs(256, "pal"))
            coll.posdb.dump()
            coll.titledb.dump()
            di = engine.get_device_index(coll)
            queries = ["alpha beta", "alpha gamma", "boxes dogs",
                       "alpha", "zeta"]
            outs = {}
            for flag in ("0", "force"):
                os.environ["OSSE_PALLAS"] = flag
                dv._direct_cube.clear_cache()
                di.route_counts = {"f1": 0, "fd": 0, "f2": 0}
                res = di.search_batch(queries, topk=8)
                outs[flag] = res
                if flag == "force":
                    assert di.route_counts["fd"] > 0  # FD exercised
            for q, a, b in zip(queries, outs["0"], outs["force"]):
                assert a[2] == b[2], q                   # n_matched
                np.testing.assert_allclose(b[1], a[1], rtol=1e-5,
                                           err_msg=q)   # scores
                # docids equal at strictly-untied ranks
                for r in range(len(a[1])):
                    tied = ((r > 0 and a[1][r - 1] == a[1][r])
                            or (r + 1 < len(a[1])
                                and a[1][r + 1] == a[1][r]))
                    if not tied:
                        assert a[0][r] == b[0][r], (q, r)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            dv._direct_cube.clear_cache()
