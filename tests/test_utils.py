"""Tests for utils: parm registry (Parms.cpp semantics), term hashing, URL
normalization (Url.cpp semantics)."""

import numpy as np
import pytest

from open_source_search_engine_tpu.utils import ghash, parms
from open_source_search_engine_tpu.utils.url import normalize


class TestGhash:
    def test_hash64_stable_and_spread(self):
        h1 = ghash.hash64("tiger")
        assert h1 == ghash.hash64("tiger")
        assert h1 != ghash.hash64("tigers")
        assert 0 < h1 < 1 << 64

    def test_term_id_case_insensitive_48bit(self):
        assert ghash.term_id("Tiger") == ghash.term_id("tiger")
        assert ghash.term_id("tiger") < 1 << 48

    def test_prefix_separates_term_space(self):
        assert ghash.term_id("foo.com") != ghash.term_id("foo.com", "site")

    def test_bigram_order_sensitive(self):
        assert ghash.bigram_id("new", "york") != ghash.bigram_id("york", "new")

    def test_docid_38bit(self):
        assert ghash.doc_id("http://a.com/") < 1 << 38

    def test_vectorized_matches_scalar_finalizer(self):
        arr = np.arange(1000, dtype=np.uint64)
        out = ghash.hash64_array(arr)
        assert len(np.unique(out)) == 1000


class TestParms:
    def test_defaults_and_set(self):
        conf = parms.Conf()
        assert conf.num_shards == 1
        conf.set("num_shards", 8)
        assert conf.num_shards == 8

    def test_type_coercion(self):
        conf = parms.Conf()
        conf.set("http_port", "9000")
        assert conf.http_port == 9000

    def test_cgi_api(self):
        coll = parms.CollectionConf("test")
        coll.set_from_cgi("n", "25")
        assert coll.docs_wanted == 25
        coll.set_from_cgi("sc", "0")
        assert coll.site_cluster is False

    def test_unknown_parm_rejected(self):
        with pytest.raises(KeyError):
            parms.Conf().set("nope", 1)

    def test_update_listener_fires(self):
        conf = parms.Conf()
        seen = []
        conf.on_update(lambda k, v: seen.append((k, v)))
        conf.set("max_mem", 123)
        assert seen == [("max_mem", 123)]

    def test_save_load_roundtrip(self, tmp_path):
        conf = parms.Conf(num_shards=4)
        p = tmp_path / "gb.conf.json"
        conf.save(p)
        conf2 = parms.Conf()
        conf2.load(p)
        assert conf2.num_shards == 4


class TestUrl:
    def test_normalize_basics(self):
        u = normalize("HTTP://WWW.Example.COM:80/a/../b//c?x=1#frag")
        assert u.scheme == "http"
        assert u.host == "www.example.com"
        assert u.port == 80
        assert u.path == "/b/c"
        assert u.query == "x=1"
        assert u.full == "http://www.example.com/b/c?x=1"

    def test_relative_resolution(self):
        u = normalize("../c.html", base="http://a.com/x/y/z.html")
        assert u.full == "http://a.com/x/c.html"

    def test_domain_extraction(self):
        assert normalize("http://www.a.foo.co.uk/").domain == "foo.co.uk"
        assert normalize("http://blog.example.com/").domain == "example.com"

    def test_idn_punycode(self):
        u = normalize("http://bücher.de/")
        assert u.host.startswith("xn--")

    def test_site_is_host(self):
        assert normalize("http://b.example.com/x").site == "b.example.com"

    def test_malformed_port_does_not_crash(self):
        assert normalize("http://a.com:abc/").full == "http://a.com/"
        assert normalize("http://a.com:99999/").full == "http://a.com/"

    def test_ipv6_brackets_roundtrip(self):
        assert normalize("http://[::1]:8080/x").full == "http://[::1]:8080/x"

    def test_unknown_scheme_no_fabricated_port(self):
        assert normalize("ftp://a.com/x").full == "ftp://a.com/x"


class TestParmAttrAssign:
    def test_plain_assignment_routes_through_registry(self):
        conf = parms.Conf()
        conf.num_shards = 8
        conf.set("num_shards", 4)
        assert conf.num_shards == 4
        assert conf.to_dict()["num_shards"] == 4

    def test_unknown_attr_assignment_rejected(self):
        conf = parms.Conf()
        with pytest.raises(KeyError):
            conf.nonexistent_parm = 1


class TestLangId:
    def test_script_detection(self):
        from open_source_search_engine_tpu.utils import lang
        assert lang.detect_script("Это русский текст о поисковых системах") \
            == lang.LANG_RUSSIAN
        assert lang.detect_script("これは日本語のテキストです漢字も含む") \
            == lang.LANG_JAPANESE
        assert lang.detect_script("这是一段中文文本用于测试语言识别功能") \
            == lang.LANG_CHINESE
        assert lang.detect_script("한국어 텍스트 언어 감지 기능 테스트") \
            == lang.LANG_KOREAN
        assert lang.detect_script("نص عربي لاختبار اكتشاف اللغة هنا") \
            == lang.LANG_ARABIC
        assert lang.detect_script("Ελληνικό κείμενο για τον εντοπισμό") \
            == lang.LANG_GREEK
        assert lang.detect_script("plain latin text") == lang.LANG_UNKNOWN

    def test_stopword_profiles(self):
        from open_source_search_engine_tpu.utils.lang import (LANG_GERMAN,
                                                              LANG_ENGLISH,
                                                              detect_language)
        de = ("der schnelle braune fuchs springt über den faulen hund und "
              "die katze ist auch mit dabei für immer").split()
        assert detect_language(de) == LANG_GERMAN
        en = ("the quick brown fox jumps over the lazy dog and this is "
              "also a test of the language detector").split()
        assert detect_language(en) == LANG_ENGLISH

    def test_charset_sniff(self):
        from open_source_search_engine_tpu.spider.fetcher import \
            sniff_charset
        assert sniff_charset(b"<html>", "iso-8859-1") == "iso-8859-1"
        assert sniff_charset(
            b'<html><meta charset="windows-1251"><body>', None) \
            == "windows-1251"
        assert sniff_charset(
            b"<meta http-equiv=Content-Type content='text/html; "
            b"charset=shift_jis'>", None) == "shift_jis"
        assert sniff_charset(b"\xef\xbb\xbfhello", None) == "utf-8"
        assert sniff_charset(b"<html>", None) == "utf-8"
        assert sniff_charset(b"x", "not-a-charset") == "utf-8"

    def test_nonenglish_doc_langid_flows_to_rerank(self, tmp_path):
        """A Russian doc gets langid=ru at build; the PQR language rule
        demotes it for an English query context (VERDICT r3 item 10)."""
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.utils.lang import LANG_RUSSIAN
        c = Collection("lang", tmp_path)
        docproc.index_document(
            c, "http://ru.test/p",
            "<html><body><p>поиск это русский текст про системы поиска "
            "и не только</p></body></html>")
        rec = docproc.get_document(c, url="http://ru.test/p")
        assert rec["langid"] == LANG_RUSSIAN
