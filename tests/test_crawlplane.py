"""Crawl-plane reality: wire DNS with TTLs, SpiderProxy rotation, and
binary-document converters (VERDICT r4 item 6; reference Dns.cpp,
SpiderProxy.cpp:1048, XmlDoc.cpp:19206-19227)."""

import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from open_source_search_engine_tpu.build.convert import (
    convert_to_text, is_convertible, pdf_text_builtin)
from open_source_search_engine_tpu.spider.fetcher import (Fetcher,
                                                          FetchResult)
from open_source_search_engine_tpu.spider.proxies import (ProxyPool,
                                                          looks_banned)
from open_source_search_engine_tpu.utils import dnsresolver
from open_source_search_engine_tpu.utils.dnsresolver import (
    QTYPE_A, QTYPE_CNAME, QTYPE_NS, DnsResolver, build_query,
    parse_response)


# --------------------------------------------------------------- DNS


def _name_bytes(name: str) -> bytes:
    out = b""
    for lb in name.strip(".").split("."):
        out += bytes([len(lb)]) + lb.encode()
    return out + b"\x00"


def _rr(name: str, rtype: int, ttl: int, rdata: bytes) -> bytes:
    return (_name_bytes(name) +
            struct.pack(">HHIH", rtype, 1, ttl, len(rdata)) + rdata)


def _response(query: bytes, answers=(), authority=(), additional=(),
              rcode: int = 0) -> bytes:
    qid = struct.unpack(">H", query[:2])[0]
    # echo the question section verbatim
    qend = 12
    while query[qend]:
        qend += 1 + query[qend]
    qend += 5
    hdr = struct.pack(">HHHHHH", qid, 0x8000 | rcode, 1,
                      len(answers), len(authority), len(additional))
    return hdr + query[12:qend] + b"".join(answers) + \
        b"".join(authority) + b"".join(additional)


class FakeDnsServer:
    """Canned-answer UDP DNS server; ``responder(name, query)`` builds
    the reply."""

    def __init__(self, responder):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.responder = responder
        self.queries: list[str] = []
        self._stop = False
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _loop(self):
        self.sock.settimeout(0.2)
        while not self._stop:
            try:
                data, peer = self.sock.recvfrom(4096)
            except socket.timeout:
                continue
            except OSError:
                return
            name, _ = dnsresolver._read_name(data, 12)
            self.queries.append(name)
            reply = self.responder(name, data)
            if reply is not None:
                self.sock.sendto(reply, peer)

    def stop(self):
        self._stop = True
        self.sock.close()


class TestDnsResolver:
    def test_a_record_with_ttl_cached(self):
        def responder(name, q):
            return _response(q, answers=[
                _rr(name, QTYPE_A, 300, socket.inet_aton("10.1.2.3"))])
        srv = FakeDnsServer(responder)
        try:
            r = DnsResolver([srv.addr])
            assert r.resolve("example.test") == "10.1.2.3"
            assert r.resolve("example.test") == "10.1.2.3"
            assert len(srv.queries) == 1  # second hit came from cache
            # per-record TTL honored (not a fixed module TTL); entry
            # layout on the cache plane is (expiry, gen, cost, value)
            exp = r._cache._d["example.test"][0]
            assert 200 < exp - time.monotonic() <= 300
        finally:
            srv.stop()

    def test_cname_chain(self):
        def responder(name, q):
            if name == "www.alias.test":
                return _response(q, answers=[
                    _rr(name, QTYPE_CNAME, 60,
                        _name_bytes("real.test")),
                    _rr("real.test", QTYPE_A, 60,
                        socket.inet_aton("10.9.9.9"))])
            return _response(q, rcode=3)
        srv = FakeDnsServer(responder)
        try:
            assert DnsResolver([srv.addr]).resolve("www.alias.test") \
                == "10.9.9.9"
        finally:
            srv.stop()

    def test_nxdomain_negative_cached(self):
        def responder(name, q):
            return _response(q, rcode=3)
        srv = FakeDnsServer(responder)
        try:
            r = DnsResolver([srv.addr])
            assert r.resolve("nope.test") is None
            assert r.resolve("nope.test") is None
            assert len(srv.queries) == 1
        finally:
            srv.stop()

    def test_timeout_budget(self, monkeypatch):
        def responder(name, q):
            return None  # black hole
        srv = FakeDnsServer(responder)
        monkeypatch.setattr(dnsresolver, "TOTAL_BUDGET_S", 1.0)
        monkeypatch.setattr(dnsresolver, "TRY_TIMEOUT_S", 0.3)
        try:
            t0 = time.monotonic()
            assert DnsResolver([srv.addr]).resolve("slow.test") is None
            assert time.monotonic() - t0 < 3.0
        finally:
            srv.stop()

    def test_iterative_referral_walk(self):
        """root-style server refers to the authority (NS + glue A);
        the walk follows and gets the answer — Dns.cpp's descent."""
        auth_holder = {}

        def auth_responder(name, q):
            return _response(q, answers=[
                _rr(name, QTYPE_A, 120,
                    socket.inet_aton("10.77.0.1"))])
        auth = FakeDnsServer(auth_responder)
        auth_ip_port = socket.inet_aton("127.0.0.1")

        def root_responder(name, q):
            return _response(
                q,
                authority=[_rr("test", QTYPE_NS, 120,
                               _name_bytes("ns1.test"))],
                additional=[_rr("ns1.test", QTYPE_A, 120,
                                auth_ip_port)])
        root = FakeDnsServer(root_responder)
        try:
            r = DnsResolver([root.addr], iterative=True,
                            port=auth.port)
            # referral glue carries 127.0.0.1; the resolver's port
            # default routes the follow-up to the authority server
            assert r.resolve("www.deep.test") == "10.77.0.1"
            assert root.queries and auth.queries
        finally:
            root.stop()
            auth.stop()


# --------------------------------------------------------------- proxies


class TestProxyPool:
    def test_sticky_and_ban_rotation(self):
        pool = ProxyPool(["p1:1", "p2:2", "p3:3"])
        first = pool.pick("1.2.3.4")
        pool.release(first)
        again = pool.pick("1.2.3.4")
        pool.release(again)
        assert first == again  # sticky per target ip
        assert pool.report(first, "1.2.3.4", 403)  # ban
        nxt = pool.pick("1.2.3.4")
        pool.release(nxt)
        assert nxt != first
        # other target ips still use the banned proxy
        others = {pool.pick(f"9.9.9.{i}") for i in range(12)}
        assert first in others

    def test_all_banned_goes_direct(self):
        pool = ProxyPool(["p1:1", "p2:2"])
        pool.report("p1:1", "5.5.5.5", 429)
        pool.report("p2:2", "5.5.5.5", 403)
        assert pool.pick("5.5.5.5") is None

    def test_ban_page_detection(self):
        assert looks_banned(403, "")
        assert looks_banned(429, "")
        assert looks_banned(200, "<html>Please solve this CAPTCHA")
        assert not looks_banned(200, "a perfectly fine page " * 20)
        assert not looks_banned(200,
                                "long article mentioning captcha "
                                + "filler " * 2000)

    def test_fetcher_rotates_on_ban(self):
        hits = {"ban": 0, "good": 0}

        class _Proxy(BaseHTTPRequestHandler):
            banned = False

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.banned:
                    hits["ban"] += 1
                    body = b"Access Denied - CAPTCHA required"
                else:
                    hits["good"] += 1
                    body = (b"<html><title>ok</title>"
                            b"<body>proxied page body</body></html>")
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class _Banned(_Proxy):
            banned = True

        s_ban = ThreadingHTTPServer(("127.0.0.1", 0), _Banned)
        s_ok = ThreadingHTTPServer(("127.0.0.1", 0), _Proxy)
        for s in (s_ban, s_ok):
            threading.Thread(target=s.serve_forever,
                             daemon=True).start()
        from open_source_search_engine_tpu.utils import ipresolve
        ipresolve.resolver_override = lambda host: "10.0.0.1"
        try:
            # hash-sticky pick may start on either proxy; the banned
            # one must be detected and rotated away from
            pool = ProxyPool([f"127.0.0.1:{s_ban.server_address[1]}",
                              f"127.0.0.1:{s_ok.server_address[1]}"])
            f = Fetcher(respect_robots=False, cache_ttl_s=0,
                        proxies=pool)
            res = f.fetch_one("http://proxied.test/page")
            assert res.ok and "proxied page body" in res.content
            assert hits["good"] >= 1
        finally:
            ipresolve.resolver_override = None
            ipresolve.clear_cache()
            s_ban.shutdown()
            s_ok.shutdown()


# --------------------------------------------------------------- convert


def _tiny_pdf(text: str) -> bytes:
    stream = f"BT /F1 12 Tf ({text}) Tj ET".encode()
    return (b"%PDF-1.4\n1 0 obj\n<< /Length " +
            str(len(stream)).encode() + b" >>\nstream\n" + stream +
            b"\nendstream\nendobj\ntrailer\n<<>>\n%%EOF\n")


class TestConverters:
    def test_kind_detection(self):
        assert is_convertible("application/pdf")
        assert is_convertible("", "http://x.test/a/b.PDF")
        assert is_convertible("application/msword")
        assert not is_convertible("text/html")

    def test_builtin_pdf_extraction(self):
        pdf = _tiny_pdf("quarterly aardwolf report 2021")
        assert "quarterly aardwolf report 2021" in pdf_text_builtin(pdf)

    def test_builtin_pdf_flate_and_escapes(self):
        import zlib
        raw = (rb"BT (line \(one\)) Tj T* (line two) Tj ET")
        comp = zlib.compress(raw)
        pdf = (b"%PDF-1.4\n1 0 obj\n<< /Filter /FlateDecode /Length " +
               str(len(comp)).encode() + b" >>\nstream\n" + comp +
               b"\nendstream\nendobj\n%%EOF\n")
        out = pdf_text_builtin(pdf)
        assert "line (one)" in out and "line two" in out

    def test_convert_to_text_pdf(self):
        pdf = _tiny_pdf("wombat migration study")
        assert "wombat migration study" in convert_to_text(
            pdf, "application/pdf")

    def test_crawl_ingests_pdf(self, tmp_path):
        """End-to-end: the spider fetches a PDF url, the converter
        plane turns it into text, and the doc becomes searchable."""
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.query import engine
        from open_source_search_engine_tpu.spider import (SpiderLoop,
                                                          SpiderScheduler)

        pdf = _tiny_pdf("subterranean wombat census results")

        class _Site(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/robots.txt":
                    body, ctype = b"", "text/plain"
                elif self.path == "/report.pdf":
                    body, ctype = pdf, "application/pdf"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Site)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            coll = Collection("c", str(tmp_path))
            sched = SpiderScheduler()
            sched.add_url(f"{base}/report.pdf")
            loop = SpiderLoop(coll, sched,
                              fetcher=Fetcher(cache_ttl_s=0))
            loop.crawl_step()
            res = engine.search(coll, "wombat census", topk=5)
            assert res.total_matches == 1
            assert res.results[0].url.endswith("/report.pdf")
        finally:
            httpd.shutdown()
