"""Admin plane: master-password auth, HTML pages, profiler, statsdb
persistence, and the /search micro-batcher.

Reference: Users/PageLogin master passwords (``Conf::m_masterPwds``),
Pages.cpp admin set, Profiler, Statsdb sample ring behind PagePerf.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.serve.server import (QueryBatcher,
                                                        SearchHTTPServer)
from open_source_search_engine_tpu.utils.parms import Conf


@pytest.fixture(autouse=True)
def _reset_slo():
    """The server's request handling feeds the process-global SLO
    tracker; a slow CI box can leave query_p99 burning, and the
    NEXT test file's AdmissionGate (default degraded_fn reads
    g_slo.degraded()) would then shed background tiers with reason
    "signal" — cross-file pollution schedcheck's admission suite
    exists to catch. Scrub the signal both ways."""
    from open_source_search_engine_tpu.utils.slo import g_slo
    g_slo.reset()
    yield
    g_slo.reset()


@pytest.fixture
def srv(tmp_path):
    s = SearchHTTPServer(tmp_path, port=0)
    coll = s.colldb.get("main")
    for i in range(6):
        docproc.index_document(
            coll, f"http://a{i % 3}.test/p{i}",
            f"<html><title>t{i}</title><body><p>admin corpus words "
            f"number{i}</p></body></html>")
    s.start()
    yield s
    s.stop()


def _get(srv, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{srv._httpd.server_port}{path}")


def test_admin_open_when_no_password(srv):
    assert _get(srv, "/admin/stats").status == 200
    html = _get(srv, "/admin/").read().decode()
    assert "profiler" in html and "<table" in html


def test_admin_requires_password_when_set(srv):
    srv.conf.master_password = "sekrit"
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/admin/stats")
    assert e.value.code == 401
    assert _get(srv, "/admin/stats?pwd=sekrit").status == 200
    # public pages stay open (the reference only gates admin)
    assert _get(srv, "/search?q=admin+corpus").status == 200


def test_profiler_page_lists_stages(srv):
    _get(srv, "/search?q=admin+corpus&format=json").read()
    body = _get(srv, "/admin/profiler").read().decode()
    assert "stage timings" in body
    js = json.loads(_get(srv, "/admin/profiler?format=json").read())
    assert any(k.startswith("query.") for k in js)


def test_graph_svg(srv):
    body = _get(srv, "/admin/graph").read().decode()
    assert body.startswith("<svg")


def test_search_uses_device_batcher(srv):
    out = json.loads(
        _get(srv, "/search?q=admin+corpus&format=json").read())
    assert out["totalMatches"] == 6
    # concurrent queries coalesce and all answer correctly
    results = {}

    def one(i):
        r = json.loads(_get(
            srv, f"/search?q=admin+corpus+number{i}&format=json").read())
        results[i] = r["totalMatches"]
    ts = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(results[i] >= 1 for i in range(6))


def test_batcher_propagates_errors():
    def boom(key, queries):
        raise RuntimeError("kernel on fire")
    b = QueryBatcher(boom)
    with pytest.raises(RuntimeError, match="kernel on fire"):
        b.search(("main", 10, 0), "q")
    b.stop()


def test_statsdb_persists_and_reloads(tmp_path):
    s = SearchHTTPServer(tmp_path, port=0)
    s.start()
    # force a couple of samples through the ring + file
    s._stop_sampling.set()
    from open_source_search_engine_tpu.utils.stats import g_stats
    with open(s._statsdb_path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps([1e9, {"qps": 5.0}]) + "\n")
    s.stop()
    s2 = SearchHTTPServer(tmp_path, port=0)
    s2.start()
    try:
        assert any(m.get("qps") == 5.0 for _, m in g_stats.timeseries)
    finally:
        s2.stop()


def test_gbconf_loads_master_password(tmp_path):
    c = Conf()
    c.master_password = "fromfile"
    c.save(tmp_path / "gb.conf")
    s = SearchHTTPServer(tmp_path, port=0)
    assert s.conf.master_password == "fromfile"


def test_inject_and_addurl_require_password_when_set(srv):
    srv.conf.master_password = "sekrit"
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/inject?u=http://x.test/p")
    assert e.value.code == 401
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/addurl?u=http://x.test/p")
    assert e.value.code == 401
    # with the password they pass auth (addurl then 503s: no spider)
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/addurl?u=http://x.test/p&pwd=sekrit")
    assert e.value.code == 503
    r = _get(srv, "/inject?u=http://x.test/p&pwd=sekrit&content=hi")
    assert r.status == 200
    srv.conf.master_password = ""


def test_search_never_creates_collections(srv, tmp_path):
    """Unauthenticated /search with an arbitrary c= name must not mint
    collection directories on disk (404s instead)."""
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/search?q=words&c=doesnotexist")
    assert e.value.code == 404
    assert not (srv.colldb.base_dir / "coll" / "doesnotexist").exists()


def test_perf_page_surfaces_postings_overflow_alert(srv):
    """build.postings_overflow must surface as a shard-split alert on
    /admin/perf (HTML + json) — the operator sees the counter before
    the overflowing node boot-loops on the build ValueError."""
    from open_source_search_engine_tpu.utils.stats import g_stats
    js = json.loads(_get(srv, "/admin/perf?format=json").read())
    assert js["alerts"] == []
    html = _get(srv, "/admin/perf").read().decode()
    assert "shard_split_needed" not in html

    g_stats.count("build.postings_overflow")
    try:
        js = json.loads(_get(srv, "/admin/perf?format=json").read())
        assert len(js["alerts"]) == 1
        a = js["alerts"][0]
        assert a["name"] == "shard_split_needed"
        assert a["count"] >= 1
        assert "split the collection" in a["hint"]
        html = _get(srv, "/admin/perf").read().decode()
        assert "shard_split_needed" in html
        assert "split the collection" in html
    finally:
        with g_stats._lock:
            g_stats.counters.pop("build.postings_overflow", None)
