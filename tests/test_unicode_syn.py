"""Synonym dictionary + Unicode normalization/charset goldens
(Synonyms.cpp / UCNormalizer.cpp / iana_charset.cpp roles)."""

import tempfile
import unicodedata

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.compiler import compile_query
from open_source_search_engine_tpu.spider.fetcher import sniff_charset
from open_source_search_engine_tpu.utils.unicodenorm import (nfc,
                                                             resolve_charset)


class TestSynonymDictionary:
    def test_dictionary_expansion_in_plan(self):
        plan = compile_query("car")
        subs = [s.display for s in plan.groups[0].sublists]
        assert "automobile" in subs

    def test_synonym_doc_found_and_ranked_below_exact(self, tmp_path):
        coll = Collection("s", str(tmp_path))
        docproc.index_document(
            coll, "http://a.test/exact",
            "<html><body><p>a shiny red car parked outside the "
            "office building today</p></body></html>")
        docproc.index_document(
            coll, "http://a.test/syn",
            "<html><body><p>a shiny red automobile parked outside "
            "the office building today</p></body></html>")
        res = engine.search(coll, "car", topk=5, site_cluster=False)
        assert res.total_matches == 2
        urls = [r.url for r in res.results]
        assert urls[0].endswith("/exact")   # exact beats synonym
        assert urls[1].endswith("/syn")     # ×0.90² synonym weight

    def test_conjugates_still_rank(self, tmp_path):
        coll = Collection("c", str(tmp_path))
        docproc.index_document(
            coll, "http://b.test/1",
            "<html><body><p>she was running through the park at "
            "dawn</p></body></html>")
        res = engine.search(coll, "run", topk=5)
        assert res.total_matches == 1


class TestUnicode:
    def test_nfc_fastpath_ascii(self):
        s = "plain ascii"
        assert nfc(s) is s

    def test_nfd_document_matches_nfc_query(self, tmp_path):
        coll = Collection("u", str(tmp_path))
        # document arrives DECOMPOSED (e + combining acute)
        nfd_word = unicodedata.normalize("NFD", "café")
        assert nfd_word != "café"  # really decomposed
        docproc.index_document(
            coll, "http://u.test/1",
            f"<html><body><p>the {nfd_word} serves espresso "
            "daily</p></body></html>")
        # query arrives COMPOSED
        res = engine.search(coll, "café", topk=5)
        assert res.total_matches == 1

    def test_latin1_page_decodes_and_indexes(self, tmp_path):
        raw = "Münchner Straßenfest".encode("latin-1")
        cs = sniff_charset(raw, "iso-8859-1")
        text = raw.decode(cs)
        coll = Collection("l", str(tmp_path))
        docproc.index_document(
            coll, "http://l.test/1",
            f"<html><body><p>{text} beginnt morgen</p></body></html>")
        res = engine.search(coll, "münchner", topk=5)
        assert res.total_matches == 1

    def test_charset_aliases(self):
        assert resolve_charset("x-sjis") == "shift_jis"
        assert resolve_charset("ks_c_5601-1987") == "cp949"
        assert resolve_charset("totally-bogus") is None
        # header charset wins; meta sniff works
        assert sniff_charset(b"<meta charset='gb2312'>", None) \
            == "gb2312"
        assert sniff_charset(b"\xef\xbb\xbfrest", None) == "utf-8"
