"""Tenant plane — the ResidencyManager's LRU hot set, single-flight
cold starts, membudget pressure ordering, the admission gate's
weighted-fair tenant quotas, the delColl lifecycle, and the acceptance
criterion: a cold→hot promoted tenant answers identically to an
always-resident one.

The contract under test (serve/tenancy.py + serve/admission.py +
the engine/crawlbot wiring):

* residency is LRU-with-pinning, sized by ``max_resident`` and the
  membudget "device" label cap; parking stops the loop and zeroes the
  gauge but keeps the devcache base, so re-promotion is cheap AND
  bit-identical;
* a cold tenant's build is single-flight — riders join the leader's
  flight and shed under their own deadline instead of queueing blind;
* device pressure parks cold tenants (priority 10) BEFORE the cache
  plane flushes (priority 100) — one rung below shed-before-refuse;
* per-tenant admission quotas only bite on the QUEUE path (an idle
  gate lets any tenant borrow), and a shed for tenant A must never
  shed tenant B;
* crawlbot delete unserves before it purges: loop stopped, gauges
  zeroed, registry dropped — a deleted corpus neither answers from
  HBM nor keeps billing the budget.
"""

import threading
import types

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import (Collection,
                                                            CollectionDb)
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import search_device_batch
from open_source_search_engine_tpu.serve import tenancy as tenancy_mod
from open_source_search_engine_tpu.serve.admission import (AdmissionGate,
                                                           Shed)
from open_source_search_engine_tpu.serve.crawlbot import CrawlBot, CrawlJob
from open_source_search_engine_tpu.serve.server import SearchHTTPServer
from open_source_search_engine_tpu.serve.tenancy import (ResidencyManager,
                                                         g_residency)
from open_source_search_engine_tpu.utils import deadline as deadline_mod
from open_source_search_engine_tpu.utils.membudget import g_membudget
from open_source_search_engine_tpu.utils.stats import g_stats

from .polling import wait_until

DOC = ("<html><head><title>{t}</title></head><body>"
       "<p>walrus {t} herd gathers on the {t} shore. "
       "The walrus colony of {t} dives deep.</p></body></html>")

QUERIES = ["walrus", "herd", "walrus shore", "colony", "nothinghere"]


def _mk_coll(tmp_path, name: str) -> Collection:
    c = Collection(name, tmp_path)
    c.conf.pqr_enabled = False
    docproc.index_document(c, f"http://{name}.test/p",
                           DOC.format(t=name))
    return c


@pytest.fixture(autouse=True)
def _plane_reset():
    """Tenancy tests mutate the process-wide singletons; leave them
    the way a fresh server boot expects them."""
    g_stats.reset()
    g_residency.reset()
    yield
    g_residency.reset()
    g_membudget.set_label_cap("device", 0)


def _count(name: str) -> int:
    return g_stats.snapshot()["counters"].get(name, 0)


def _key(r):
    return (-round(r.score, 3), r.docid)


# ---------------------------------------------------------------------------
# LRU hot set
# ---------------------------------------------------------------------------

class TestLru:
    def test_count_bound_evicts_least_recent(self, tmp_path):
        rm = ResidencyManager(max_resident=2)
        ca, cb, cc = (_mk_coll(tmp_path, n) for n in ("ta", "tb", "tc"))
        rm.loop_for(ca)
        rm.loop_for(cb)
        assert rm.resident_names() == ["ta", "tb"]
        rm.loop_for(cc)  # ta is LRU → parked
        assert rm.resident_names() == ["tb", "tc"]
        snap = rm.snapshot()
        assert snap["tenants"]["ta"]["resident"] is False
        assert snap["parked"] == 1
        # parking released the device gauge and stopped the loop
        assert g_membudget.used("device") == sum(
            t["device_bytes"] for t in snap["tenants"].values())
        assert ca._device_index is None
        rm.stop_all()

    def test_pin_protects_and_touch_refreshes_recency(self, tmp_path):
        rm = ResidencyManager(max_resident=2)
        ca, cb, cc = (_mk_coll(tmp_path, n) for n in ("pa", "pb", "pc"))
        rm.loop_for(ca)
        rm.loop_for(cb)
        rm.pin("pa")
        rm.loop_for(cc)  # pa pinned → pb (LRU unpinned) parks instead
        assert rm.resident_names() == ["pa", "pc"]
        # a fast-path hit must refresh recency: touch pc, promote pb —
        # with pa pinned and pc freshly touched there is no victim
        # besides pc, and the spare rule picks the LRU one
        loop_c = rm.loop_for(cc)
        assert rm.loop_for(cc) is loop_c  # fast path, same loop
        assert _count("tenancy.hit") >= 1
        rm.unpin("pa")
        rm.loop_for(cb)  # pa now LRU and unpinned → parked
        assert rm.resident_names() == ["pb", "pc"]
        rm.stop_all()

    def test_same_name_different_collection_never_aliases(self,
                                                          tmp_path):
        """A record is keyed by NAME but owned by a Collection OBJECT:
        a same-named collection from another registry (or a deleted-
        and-recreated one that skipped release()) must get its own
        loop, not the stale tenant's — serving the old object's device
        base would answer with the wrong corpus."""
        rm = ResidencyManager()
        old = _mk_coll(tmp_path / "old", "dup")
        loop_old = rm.loop_for(old)
        new = Collection("dup", tmp_path / "new")
        new.conf.pqr_enabled = False
        docproc.index_document(new, "http://dup.test/q",
                               DOC.format(t="fresh"))
        loop_new = rm.loop_for(new)
        assert loop_new is not loop_old
        assert _count("tenancy.stale_record") == 1
        # the stale record was fully released: the old object lost its
        # loop and device base, the record now bills the new object
        assert old._resident_loop is None
        assert old._device_index is None
        assert new._resident_loop is loop_new
        assert rm.snapshot()["tenants"]["dup"]["cold_starts"] == 1
        assert rm.loop_for(new) is loop_new  # fast path, new owner
        rm.stop_all()

    def test_repromotion_after_park_counts_a_cold_start(self, tmp_path):
        rm = ResidencyManager()
        ca = _mk_coll(tmp_path, "rp")
        rm.loop_for(ca)
        assert rm.snapshot()["tenants"]["rp"]["cold_starts"] == 1
        rm.park("rp")
        assert rm.snapshot()["tenants"]["rp"]["resident"] is False
        rm.loop_for(ca)
        snap = rm.snapshot()["tenants"]["rp"]
        assert snap["resident"] is True and snap["cold_starts"] == 2
        assert len(rm.coldstart_ms) == 2
        rm.stop_all()


# ---------------------------------------------------------------------------
# single-flight cold start
# ---------------------------------------------------------------------------

class TestSingleFlight:
    def test_concurrent_cold_queries_build_once(self, tmp_path,
                                                monkeypatch):
        rm = ResidencyManager()
        coll = _mk_coll(tmp_path, "sf")
        builds = []
        real = engine.get_device_index

        def counting(c):
            builds.append(c.name)
            return real(c)

        monkeypatch.setattr(engine, "get_device_index", counting)
        loops, errors = [], []
        start = threading.Barrier(8)

        def worker():
            try:
                start.wait(timeout=30)
                loops.append(rm.loop_for(coll))
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        ts = [threading.Thread(target=worker, daemon=True)
              for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errors, errors
        assert builds == ["sf"]  # ONE build for 8 concurrent queries
        assert len(set(map(id, loops))) == 1
        assert rm.snapshot()["tenants"]["sf"]["cold_starts"] == 1
        rm.stop_all()

    def test_expired_rider_sheds_instead_of_waiting(self):
        """A rider whose deadline burned sheds (DeadlineExceeded → the
        serve edge's stale-or-504 ladder) rather than queueing blind
        behind a build it can no longer use."""
        rm = ResidencyManager()
        # a leader's flight is in progress (never completes here)
        rm._flights["rx"] = tenancy_mod._Flight()
        coll = types.SimpleNamespace(name="rx")
        base = _count("tenancy.rider_shed")
        with pytest.raises(deadline_mod.DeadlineExceeded):
            rm.loop_for(coll, deadline=deadline_mod.Deadline.after(0.0))
        assert _count("tenancy.rider_shed") == base + 1
        assert _count("tenancy.singleflight_join") >= 1

    def test_leader_failure_propagates_then_clears(self, tmp_path,
                                                   monkeypatch):
        rm = ResidencyManager()
        coll = _mk_coll(tmp_path, "lf")

        def boom(c):
            raise RuntimeError("build failed")

        monkeypatch.setattr(engine, "get_device_index", boom)
        with pytest.raises(RuntimeError, match="build failed"):
            rm.loop_for(coll)
        assert rm._flights == {}  # the failed flight is not wedged
        monkeypatch.undo()
        assert rm.loop_for(coll).alive  # next query promotes cleanly
        rm.stop_all()


# ---------------------------------------------------------------------------
# membudget pressure ordering
# ---------------------------------------------------------------------------

class TestPressure:
    def test_device_pressure_parks_cold_tenant_before_cache_plane(
            self, tmp_path):
        """The ladder's new rung: a device-label cap breach parks the
        LRU tenant (priority 10) and never reaches the higher-priority
        handlers — a parked tenant costs one transfer-speed cold
        start; a flushed cache costs every hot SERP."""
        rm = ResidencyManager()
        rm.attach(g_membudget)
        ca, cb = _mk_coll(tmp_path, "va"), _mk_coll(tmp_path, "vb")
        rm.loop_for(ca)
        rm.loop_for(cb)
        used = g_membudget.used("device")
        assert used > 0
        high_prio_calls = []
        g_membudget.add_pressure_handler(
            lambda need: high_prio_calls.append(need) or 0,
            priority=100, key="t.cacheish")
        try:
            g_membudget.set_label_cap("device", used)
            # one byte over the cap: relief must come from the
            # residency handler parking the LRU tenant (va — vb is the
            # hottest and gets spared)
            assert g_membudget.reserve("device", 1)
            g_membudget.release("device", 1)
        finally:
            g_membudget.set_label_cap("device", 0)
        assert rm.resident_names() == ["vb"]
        assert _count("tenancy.pressure_evict") == 1
        assert not high_prio_calls  # the ladder stopped one rung down
        rm.stop_all()


# ---------------------------------------------------------------------------
# weighted-fair tenant quotas (admission plane)
# ---------------------------------------------------------------------------

class TestQuotas:
    def test_idle_gate_lets_any_tenant_borrow(self):
        """Quota only bites on the queue path: with free inflight
        slots a lone tenant takes everything (work-conserving)."""
        gate = AdmissionGate(max_inflight=2, max_queue=2)
        with gate.admit("interactive", tenant="solo"):
            with gate.admit("interactive", tenant="solo"):
                pass
        t = gate.snapshot()["tenants"]["solo"]
        assert t["served"] == 2 and t["shed"] == 0

    def test_over_share_tenant_sheds_quota_quiet_tenant_queues(self):
        gate = AdmissionGate(max_inflight=1, max_queue=4)
        holder = gate.admit("interactive", tenant="quiet")
        release = threading.Event()
        results = []

        def queued_worker(tenant):
            try:
                dl = deadline_mod.Deadline.after(30.0)
                with gate.admit("interactive", deadline=dl,
                                tenant=tenant):
                    results.append(("served", tenant))
            except Shed as s:
                results.append((s.reason, tenant))

        # greedy's share with two active tenants: 4 * 1/2 = 2 waiters
        ts = [threading.Thread(target=queued_worker, args=("greedy",),
                               daemon=True) for _ in range(2)]
        for t in ts:
            t.start()
        wait_until(lambda: gate.snapshot()["tenants"].get(
            "greedy", {}).get("queued") == 2, desc="greedy queued")
        # the third greedy waiter is over-share → quota shed, synchronously
        with pytest.raises(Shed) as e:
            gate.admit("interactive",
                       deadline=deadline_mod.Deadline.after(30.0),
                       tenant="greedy")
        assert e.value.reason == "quota"
        # quiet still queues fine — greedy's overload never sheds it
        tq = threading.Thread(target=queued_worker, args=("quiet",),
                              daemon=True)
        tq.start()
        wait_until(lambda: gate.snapshot()["tenants"]["quiet"]
                   .get("queued") == 1, desc="quiet queued")
        holder.__exit__(None, None, None)
        release.set()
        for t in ts + [tq]:
            t.join(timeout=30)
        snap = gate.snapshot()["tenants"]
        assert snap["greedy"]["shed"] == 1
        assert snap["quiet"]["shed"] == 0
        assert ("served", "quiet") in results
        assert results.count(("served", "greedy")) == 2
        c = g_stats.snapshot()["counters"]
        assert c.get("admission.tenant.greedy.shed", 0) == 1
        assert c.get("admission.shed.reason.quota", 0) == 1

    def test_queue_full_displaces_over_share_victim(self):
        """A full queue with an over-share hog: the under-share
        arrival displaces the hog's newest waiter (shed ``quota``)
        instead of being refused ``queue_full``."""
        gate = AdmissionGate(max_inflight=1, max_queue=2)
        holder = gate.admit("interactive")  # legacy holder, no tenant
        results = []

        def queued_worker(tenant):
            try:
                dl = deadline_mod.Deadline.after(30.0)
                with gate.admit("interactive", deadline=dl,
                                tenant=tenant):
                    results.append(("served", tenant))
            except Shed as s:
                results.append((s.reason, tenant))

        # greedy fills the whole queue while it is the LONE active
        # tenant (share = unbounded: nobody else wants the capacity)
        ts = [threading.Thread(target=queued_worker, args=("greedy",),
                               daemon=True) for _ in range(2)]
        for t in ts:
            t.start()
        wait_until(lambda: gate.snapshot()["tenants"].get(
            "greedy", {}).get("queued") == 2, desc="queue full")
        # quiet arrives: queue is full, but greedy now holds 2 > its
        # share of 1 — the newest greedy waiter is displaced
        tq = threading.Thread(target=queued_worker, args=("quiet",),
                              daemon=True)
        tq.start()
        wait_until(lambda: ("quota", "greedy") in results,
                   desc="greedy waiter displaced")
        holder.__exit__(None, None, None)
        for t in ts + [tq]:
            t.join(timeout=30)
        assert ("served", "quiet") in results
        assert results.count(("served", "greedy")) == 1
        assert gate.snapshot()["tenants"]["quiet"]["shed"] == 0

    def test_weights_skew_the_grant_order(self):
        """Within a tier the grant goes to the waiter whose tenant has
        the lowest inflight/weight — a weight-3 tenant drains 3× the
        work of a weight-1 tenant under contention."""
        gate = AdmissionGate(max_inflight=1, max_queue=8)
        gate.set_tenant_weight("gold", 3.0)
        holder = gate.admit("interactive", tenant="gold")
        order = []
        lock = threading.Lock()

        def queued_worker(tenant):
            dl = deadline_mod.Deadline.after(30.0)
            with gate.admit("interactive", deadline=dl, tenant=tenant):
                with lock:
                    order.append(tenant)

        # queue one bronze FIRST, then one gold: FIFO would serve
        # bronze; weighted-fair must pick gold (holder's release zeroes
        # gold's inflight → gold load 0/3 < bronze 0/1 ties → FIFO
        # breaks the tie, so make bronze carry inflight instead)
        tb = threading.Thread(target=queued_worker, args=("bronze",),
                              daemon=True)
        tb.start()
        wait_until(lambda: gate.snapshot()["tenants"].get(
            "bronze", {}).get("queued") == 1, desc="bronze queued")
        tg = threading.Thread(target=queued_worker, args=("gold",),
                              daemon=True)
        tg.start()
        wait_until(lambda: gate.snapshot()["tenants"].get(
            "gold", {}).get("queued") == 1, desc="gold queued")
        # gold already has 1 inflight (the holder): load 1/3 = 0.33 vs
        # bronze 0/1 = 0.0 → bronze first — the weight can't starve a
        # zero-load tenant. Release and check both finish.
        holder.__exit__(None, None, None)
        tb.join(timeout=30)
        tg.join(timeout=30)
        assert order[0] == "bronze"  # lowest load/weight wins the slot
        assert set(order) == {"bronze", "gold"}

    def test_legacy_no_tenant_requests_are_untouched(self):
        """tenant=None rides the exact pre-tenant FIFO path — no
        ledger entries, no quota sheds."""
        gate = AdmissionGate(max_inflight=1, max_queue=1)
        with gate.admit("interactive"):
            pass
        assert gate.snapshot()["tenants"] == {}


# ---------------------------------------------------------------------------
# delete lifecycle (the delColl fix)
# ---------------------------------------------------------------------------

class TestDeleteLifecycle:
    def test_crawlbot_delete_unserves_and_unbills(self, tmp_path):
        """Regression: crawlbot delete used to rmtree the directory
        while the Collection object (and its resident loop + memtable
        gauges) stayed registered — the corpus kept answering from HBM
        and billing the budget forever."""
        colldb = CollectionDb(tmp_path)
        bot = CrawlBot(colldb)
        mem_before = g_membudget.used("memtable")
        coll = colldb.get("crawl_wipe")
        coll.conf.pqr_enabled = False
        docproc.index_document(coll, "http://wipe.test/p",
                               DOC.format(t="wipe"))
        assert g_membudget.used("memtable") > mem_before
        loop = engine.get_resident_loop(coll)  # serves via g_residency
        assert loop.alive
        assert g_membudget.used("device") > 0
        # a job record without a live crawl thread: delete() only
        # needs the registry entry
        bot.jobs["wipe"] = CrawlJob(name="wipe", loop=None, max_pages=1)
        assert bot.delete("wipe")
        assert not loop.alive  # resident loop stopped
        assert "crawl_wipe" not in colldb.colls  # registry dropped
        assert "crawl_wipe" not in g_residency.snapshot()["tenants"]
        assert g_membudget.used("device") == 0
        assert g_membudget.used("memtable") <= mem_before
        assert not (tmp_path / "coll" / "crawl_wipe").exists()
        # a recreated collection of the same name starts empty
        fresh = colldb.get("crawl_wipe")
        assert fresh.num_docs == 0


# ---------------------------------------------------------------------------
# /admin/tenants
# ---------------------------------------------------------------------------

class TestAdminPage:
    def test_page_joins_residency_and_admission_ledgers(self, tmp_path):
        srv = SearchHTTPServer(tmp_path, port=0)
        try:
            coll = srv.colldb.get("main")
            coll.conf.pqr_enabled = False
            docproc.index_document(coll, "http://adm.test/p",
                                   DOC.format(t="admin"))
            st, body, ct = srv.handle("GET", "/search",
                                      {"q": "walrus"}, b"")
            assert st == 200
            st, body, ct = srv.handle("GET", "/admin/tenants",
                                      {"format": "json"}, b"")
            assert st == 200 and ct == "application/json"
            import json as json_mod
            snap = json_mod.loads(body)
            # the default-collection tenant shows up in BOTH ledgers
            assert snap["residency"]["tenants"]["main"]["resident"]
            assert snap["admission"]["main"]["served"] >= 1
            st, body, ct = srv.handle("GET", "/admin/tenants", {}, b"")
            assert st == 200 and ct == "text/html"
            assert "RESIDENT" in body and "main" in body
            # per-tenant counters reach /metrics with outcome labels
            st, body, ct = srv.handle("GET", "/metrics", {}, b"")
            assert ('osse_tenant_requests_total{tenant="main",'
                    'outcome="served"}') in body
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# acceptance: cold→hot parity
# ---------------------------------------------------------------------------

class TestColdHotParity:
    def test_repromoted_tenant_answers_identically(self, tmp_path):
        """The acceptance criterion: park a tenant, re-promote it via
        a query, and get results identical to the always-resident
        run (and to the one-shot reference) — the parked state must
        lose no index state."""
        coll = _mk_coll(tmp_path, "parity")
        for i in range(4):
            docproc.index_document(
                coll, f"http://parity.test/extra{i}",
                DOC.format(t=f"extra{i} walrus herd"))
        reference = search_device_batch(coll, QUERIES, topk=10,
                                        site_cluster=False)
        hot = search_device_batch(coll, QUERIES, topk=10,
                                  site_cluster=False, resident=True)
        assert g_residency.snapshot()["tenants"]["parity"]["resident"]
        g_residency.park("parity")
        assert coll._device_index is None
        assert not g_residency.snapshot()["tenants"]["parity"]["resident"]
        # the next resident query cold-starts from the parked state
        warm = search_device_batch(coll, QUERIES, topk=10,
                                   site_cluster=False, resident=True)
        assert g_residency.snapshot()["tenants"]["parity"]["cold_starts"] \
            == 2
        for q, a, b, c in zip(QUERIES, reference, hot, warm):
            assert b.total_matches == a.total_matches == c.total_matches, q
            assert sorted(map(_key, b.results)) \
                == sorted(map(_key, a.results)) \
                == sorted(map(_key, c.results)), q
