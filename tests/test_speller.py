"""Speller tests — dictionary maintenance + did-you-mean suggestions
(the reference's ``dictlookuptest``/``spellcheck`` CLI tests, SURVEY §4.3)."""

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.speller import (
    Speller, _edit_distance_le2)


class TestEditDistance:
    @pytest.mark.parametrize("a,b,d", [
        ("cat", "cat", 0), ("cat", "cut", 1), ("cat", "cats", 1),
        ("cat", "at", 1), ("kitten", "sitten", 1), ("kitten", "sittin", 2),
    ])
    def test_small_distances(self, a, b, d):
        assert _edit_distance_le2(a, b) == d

    def test_beyond_two_is_none(self):
        assert _edit_distance_le2("cat", "elephant") is None
        assert _edit_distance_le2("kitten", "sitting") is None  # d=3


class TestSpeller:
    def test_suggest_popular_neighbor(self, tmp_path):
        sp = Speller(tmp_path)
        sp.add_doc_words(["banana"] )
        sp.add_doc_words(["banana", "apple"])
        sp.add_doc_words(["banana"])
        assert sp.suggest_word("bananna") == "banana"
        assert sp.suggest_word("banana") is None  # already the best
        assert sp.suggest_word("zzzzqqq") is None

    def test_persistence(self, tmp_path):
        sp = Speller(tmp_path)
        sp.add_doc_words(["persistent"])
        sp.save()
        sp2 = Speller(tmp_path)
        assert sp2.counts["persistent"] == 1

    def test_remove(self, tmp_path):
        sp = Speller(tmp_path)
        sp.add_doc_words(["gone"])
        sp.remove_doc_words(["gone"])
        assert "gone" not in sp.counts


class TestDidYouMean:
    def test_zero_match_query_suggests(self, tmp_path):
        coll = Collection("sp", tmp_path)
        for i in range(3):
            docproc.index_document(
                coll, f"http://s{i}.test/",
                "<html><title>Chocolate</title><body>"
                "<p>chocolate recipes galore</p></body></html>")
        res = engine.search(coll, "chocolote")
        assert res.total_matches == 0
        assert res.suggestion == "chocolate"
        res = engine.search_device(coll, "chocolote recipes")
        assert res.suggestion == "chocolate recipes"
        # matching queries carry no suggestion
        assert engine.search(coll, "chocolate").suggestion is None
