"""Distributed tracing plane tests — span trees across shards.

Pins the tentpole behaviors of :mod:`..utils.trace`: head-based
sampling with a slow-query escape hatch, span trees assembled across
threads via explicit parent handoff, cross-host propagation
(``X-OSSE-Trace`` header out, ``"_trace"`` subtree back, grafted and
rebased client-side), the slowlog file, and the acceptance scenario —
a 2-shard cluster with a wedged primary produces ONE assembled trace
holding both shards' ``rpc/search`` legs with the hedge winner tagged.
The no-bare-``g_stats.timed``-on-the-query-path guard now lives in
``tools/osselint.py`` (rule ``bare-stats-timed``, gated by
``tests/test_lint.py``).
"""

import json
import re
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from open_source_search_engine_tpu.parallel import cluster as cl
from open_source_search_engine_tpu.utils import trace as tm
from open_source_search_engine_tpu.utils.stats import g_stats
from open_source_search_engine_tpu.utils.trace import g_tracer


@pytest.fixture(autouse=True)
def _tracer_guard():
    """g_tracer is process-global: save/restore its config and ring so
    these tests can't leak sampling or slowlog paths into the suite."""
    saved = (g_tracer.sample_n, g_tracer.slow_ms,
             g_tracer.slowlog_path, g_tracer.host)
    yield
    g_tracer.sample_n, g_tracer.slow_ms = saved[0], saved[1]
    g_tracer.slowlog_path, g_tracer.host = saved[2], saved[3]
    g_tracer.ring.clear()


def _walk(node):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


def _doc(i, words="cluster shared words"):
    return (f"<html><head><title>Doc {i}</title></head><body>"
            f"<p>{words} token{i}.</p></body></html>")


# ---------------------------------------------------------------------------
# span trees + sampling
# ---------------------------------------------------------------------------

class TestSpanTree:
    def test_nested_spans_export_as_tree(self):
        g_tracer.configure(sample_n=1, slow_ms=1e9)
        with g_tracer.start("q", sampled=True, q="hello") as t:
            with tm.span("outer", k=1):
                with tm.span("inner"):
                    tm.tag(deep=True)
                tm.record("pre", time.perf_counter() - 0.001)
            assert tm.current_trace_id() == t.trace_id
        tr = g_tracer.find(t.trace_id)
        assert tr is not None and tr["sampled"]
        names = [n["name"] for n in _walk(tr["root"])]
        assert names == ["q", "outer", "inner", "pre"]
        inner = next(n for n in _walk(tr["root"]) if n["name"] == "inner")
        assert inner["tags"]["deep"] is True
        outer = next(n for n in _walk(tr["root"]) if n["name"] == "outer")
        # child offsets are ms from trace start, nested inside parent
        assert outer["start_ms"] >= 0.0
        assert inner["start_ms"] >= outer["start_ms"]
        assert tr["root"]["tags"]["q"] == "hello"

    def test_unsampled_trace_spans_are_noops(self):
        g_tracer.configure(sample_n=10 ** 9, slow_ms=1e9)
        g_tracer.ring.clear()
        with g_tracer.start("q") as t:
            assert t is not None and not t.sampled
            with tm.span("work") as sp:
                assert sp is None          # span bookkeeping skipped...
            assert tm.current_span() is None
            assert tm.current_trace_id() == t.trace_id  # ...id still set
        assert g_tracer.find(t.trace_id) is None  # dropped, not kept

    def test_head_sampling_one_in_n(self):
        g_tracer.configure(sample_n=4, slow_ms=1e9)
        g_tracer.ring.clear()
        g_tracer._n = 0
        for _ in range(8):
            with g_tracer.start("q"):
                pass
        assert len(g_tracer.ring) == 2  # kept exactly 1 in 4

    def test_sample_n_zero_disables_tracing(self):
        g_tracer.configure(sample_n=0)
        with g_tracer.start("q", sampled=True) as t:
            assert t is None
            assert tm.current_trace_id() is None

    def test_abandoned_span_tagged_on_export(self):
        g_tracer.configure(sample_n=1, slow_ms=1e9)
        with g_tracer.start("q", sampled=True) as t:
            leak = t.root.child("never-finished")
        tr = g_tracer.find(t.trace_id)
        node = next(n for n in _walk(tr["root"])
                    if n["name"] == "never-finished")
        assert node["tags"]["abandoned"] is True
        assert leak._t1 is None

    def test_explicit_parent_crosses_threads(self):
        """begin(parent=...) + attach(): the pattern the cluster client
        and batchers use to carry a trace into pool threads."""
        g_tracer.configure(sample_n=1, slow_ms=1e9)
        with g_tracer.start("q", sampled=True) as t:
            leg = tm.begin("leg", parent=t.root, addr="x")

            def worker():
                assert tm.current_span() is None  # fresh ctx is empty
                with tm.attach(leg):
                    with tm.span("inside"):
                        pass
            th = threading.Thread(target=worker)
            th.start()
            th.join()
            leg.finish()
        tr = g_tracer.find(t.trace_id)
        names = [n["name"] for n in _walk(tr["root"])]
        assert names == ["q", "leg", "inside"]


# ---------------------------------------------------------------------------
# header + graft
# ---------------------------------------------------------------------------

class TestPropagation:
    def test_header_roundtrip(self):
        sp = tm.Span("abcd1234", "rpc/search")
        hdr = tm.header_for(sp)
        assert hdr == f"abcd1234:{sp.span_id}"
        assert tm.parse_header(hdr) == ("abcd1234", sp.span_id)
        assert tm.header_for(None) is None
        assert tm.parse_header("") is None
        assert tm.parse_header("no-colon") is None

    def test_graft_rebases_remote_offsets_onto_rpc_span(self):
        """Remote subtree offsets are relative to the REMOTE root; the
        export shifts them by the local RPC span's start so no two
        hosts' clocks are ever compared."""
        root = tm.Span("t1", "q")
        time.sleep(0.005)
        rpc = root.child("rpc/search")
        rpc.graft({"name": "remote", "host": "n1", "start_ms": 0.0,
                   "dur_ms": 2.0, "tags": {},
                   "children": [{"name": "inner", "host": "n1",
                                 "start_ms": 1.5, "dur_ms": 0.5,
                                 "tags": {}}]})
        rpc.finish()
        root.finish()
        d = root.to_dict(root._t0, root._t1)
        rpc_d = d["children"][0]
        remote = rpc_d["children"][0]
        assert remote["start_ms"] == pytest.approx(
            rpc_d["start_ms"], abs=0.01)
        assert remote["children"][0]["start_ms"] == pytest.approx(
            rpc_d["start_ms"] + 1.5, abs=0.01)
        assert tm.span_count(d) == 4


# ---------------------------------------------------------------------------
# slowlog
# ---------------------------------------------------------------------------

class TestSlowlog:
    def test_slow_unsampled_trace_kept_and_logged(self, tmp_path):
        path = tmp_path / "slowlog.jsonl"
        g_tracer.configure(sample_n=10 ** 9, slow_ms=1.0,
                           slowlog_path=path)
        g_tracer.ring.clear()
        with g_tracer.start("q", q="slowone") as t:
            time.sleep(0.01)
        tr = g_tracer.find(t.trace_id)
        assert tr is not None and tr["slow"] and not tr["sampled"]
        entries = [json.loads(x) for x in
                   path.read_text().splitlines()]
        assert len(entries) == 1
        assert entries[0]["trace_id"] == t.trace_id
        assert entries[0]["dur_ms"] >= 1.0
        # unsampled slow trace keeps only the root skeleton
        assert "children" not in entries[0]["root"]

    def test_slowlog_tail_skips_torn_lines(self, tmp_path):
        path = tmp_path / "slowlog.jsonl"
        good = {"trace_id": "aa", "dur_ms": 5.0, "root": {}}
        path.write_text(json.dumps(good) + "\n" +
                        '{"trace_id": "bb", "dur_')  # kill-9 mid-append
        g_tracer.configure(slowlog_path=path)
        tail = g_tracer.slowlog_tail()
        assert tail == [good]


# ---------------------------------------------------------------------------
# acceptance: cross-host tree with the hedge winner tagged
# ---------------------------------------------------------------------------

def test_cluster_trace_spans_both_shards_and_tags_hedge_winner(tmp_path):
    """2 shards x 2 twins; shard0's primary twin wedges on the search,
    the hedge fires, and the coordinator's SINGLE assembled trace holds
    both shards' rpc/search legs, with the shard0 winner tagged
    hedge_won and each node's grafted subtree carrying its host label."""
    nodes = {name: cl.ShardNodeServer(tmp_path / name)
             for name in ("a", "b", "c", "d")}
    for node in nodes.values():
        for i in range(3):
            node.handle("/rpc/index", {"url": f"http://t.test/h{i}",
                                       "content": _doc(i)})
        node.start()
    a, b, c, d = (nodes[k] for k in "abcd")
    # hosts.conf layout: replica-0 rows first — shard0 twins are (a, c)
    conf = cl.HostsConf.parse(
        f"num-mirrors: 1\n127.0.0.1:{a.port}\n127.0.0.1:{b.port}\n"
        f"127.0.0.1:{c.port}\n127.0.0.1:{d.port}")
    client = cl.ClusterClient(conf, use_heartbeat=False)

    wedge = threading.Event()
    real_handle = a.handle

    def wedged_handle(path, payload):
        if path == "/rpc/search":
            wedge.wait(10.0)
        return real_handle(path, payload)

    a.handle = wedged_handle
    # pin the WEDGED node as shard0's primary pick; shard1 stays sane
    client.hostmap.rtt_s[0, 0] = 0.001
    client.hostmap.rtt_s[0, 1] = 0.002
    client.hostmap.rtt_s[1, 0] = 0.001
    client.hostmap.rtt_s[1, 1] = 0.002
    g_stats.reset()
    g_tracer.configure(sample_n=1, slow_ms=1e9)
    g_tracer.ring.clear()
    try:
        with g_tracer.start("search", sampled=True) as t:
            res = client.search("cluster shared", topk=5,
                                with_snippets=False, site_cluster=False)
        assert not res.degraded and res.total_matches > 0
        snap = g_stats.snapshot()["counters"]
        assert snap["transport.hedge_fired"] >= 1
        assert snap["transport.hedge_won"] >= 1

        tr = g_tracer.find(t.trace_id)
        assert tr is not None
        spans = list(_walk(tr["root"]))

        # both shards' rpc/search legs live in ONE tree
        legs = [s for s in spans if s["name"] == "rpc/search"
                and "addr" in s["tags"]]
        leg_ports = {int(s["tags"]["addr"].rsplit(":", 1)[1])
                     for s in legs}
        assert leg_ports & {a.port, c.port}, "no shard0 leg"
        assert leg_ports & {b.port, d.port}, "no shard1 leg"

        # the shard0 winner is the HEDGE attempt, tagged as such
        winners = [s for s in legs if s["tags"].get("won")]
        shard0_win = [s for s in winners
                      if s["tags"]["addr"].endswith(str(c.port))]
        assert shard0_win and shard0_win[0]["tags"]["hedge_won"] is True
        assert shard0_win[0]["tags"]["hedge"] is True

        # each answering node shipped its subtree back: grafted spans
        # carry the remote host label and node-side work
        remote_hosts = {s["host"] for s in spans
                        if s["host"].startswith("127.0.0.1:")}
        assert f"127.0.0.1:{c.port}" in remote_hosts
        assert remote_hosts & {f"127.0.0.1:{b.port}",
                               f"127.0.0.1:{d.port}"}
        remote_roots = [s for s in spans
                        if s["host"] == f"127.0.0.1:{c.port}"
                        and s["name"] == "rpc/search"
                        and "parent" in s["tags"]]
        assert remote_roots, "no grafted subtree from the hedge winner"
    finally:
        wedge.set()
        client.close()
        for node in nodes.values():
            node.stop()


# ---------------------------------------------------------------------------
# serving: debug echo, /admin/traces, slowlog end-to-end, statsdb
# ---------------------------------------------------------------------------

def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", "replace")


def test_slow_query_lands_in_slowlog_and_renders(tmp_path):
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    srv = SearchHTTPServer(tmp_path, port=0)
    srv.start()
    # every query is slow at a 0.01ms threshold; sample everything
    g_tracer.configure(sample_n=1, slow_ms=0.01)
    g_tracer.ring.clear()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        out = json.loads(_get(f"{base}/search?q=anything&format=json"
                              f"&debug=1"))
        tid = out["traceId"]
        assert re.fullmatch(r"[0-9a-f]{16}", tid)

        slowlog = tmp_path / "slowlog.jsonl"
        assert slowlog.exists()
        assert any(json.loads(x)["trace_id"] == tid
                   for x in slowlog.read_text().splitlines())

        page = _get(f"{base}/admin/traces")
        assert tid in page and "slowlog.jsonl" in page
        water = _get(f"{base}/admin/traces?id={tid}")
        assert tid in water and "search" in water

        body = json.loads(_get(f"{base}/admin/traces?format=json"))
        assert any(t["trace_id"] == tid for t in body["recent"])
        assert any(t["trace_id"] == tid for t in body["slowlog"])
    finally:
        srv.stop()


def test_debug_echo_only_when_asked(tmp_path):
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    srv = SearchHTTPServer(tmp_path, port=0)
    srv.start()
    g_tracer.configure(sample_n=10 ** 9, slow_ms=1e9)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        out = json.loads(_get(f"{base}/search?q=x&format=json"))
        assert "traceId" not in out
        xml = _get(f"{base}/search?q=x&format=xml&debug=1")
        assert "<traceId>" in xml
    finally:
        srv.stop()


def test_statsdb_corrupt_lines_tolerated(tmp_path):
    """A torn/garbage statsdb line is counted and skipped; the good
    samples still load (satellite: crash-consistent statsdb reload)."""
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    good = json.dumps([time.time(), {"qps": 1.0}])
    (Path(tmp_path) / "statsdb.jsonl").write_text(
        good + "\n" + "{torn json li\n" + "\n" + good + "\n")
    srv = SearchHTTPServer(tmp_path, port=0)
    g_stats.reset()
    g_stats.timeseries.clear()
    srv._load_statsdb()
    assert len(g_stats.timeseries) == 2
    assert g_stats.snapshot()["counters"]["statsdb.corrupt_lines"] == 1


def test_timed_span_feeds_both_planes():
    g_tracer.configure(sample_n=1, slow_ms=1e9)
    g_stats.reset()
    with g_tracer.start("q", sampled=True) as t:
        with tm.timed_span("stage.x"):
            pass
    tr = g_tracer.find(t.trace_id)
    assert any(n["name"] == "stage.x" for n in _walk(tr["root"]))
    assert "stage.x" in g_stats.snapshot()["latencies"]
    # outside any trace the stats half still records
    g_stats.reset()
    with tm.timed_span("stage.y"):
        pass
    assert "stage.y" in g_stats.snapshot()["latencies"]
