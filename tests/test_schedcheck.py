"""Deterministic schedule explorer — arming contract, determinism,
seeded historical-bug regressions, and the five scenario suites.

Two halves:

* **Always-run** (tier-1, armed or not): the jitwatch/lockcheck no-op
  contract — outside an active ``explore()`` every factory returns the
  plain ``threading`` primitive and ``sched_point`` is free.
* **Armed-only** (``OSSE_SCHED=1``, check.sh schedcheck step): the
  explorer itself — byte-identical replay, toy lost-update found and
  shrunk, ABBA deadlock detection, both seeded historical bugs
  (PR 4 generation stamping, PR 13 lone-hog displacement) found within
  a bounded budget, and the five protocol scenario suites clean at
  ``OSSE_SCHED_BUDGET`` schedules.
"""

import functools
import os
import threading

import pytest

from open_source_search_engine_tpu.utils import lockcheck, schedcheck, threads

from tests import sched_scenarios

BUDGET = int(os.environ.get("OSSE_SCHED_BUDGET", "64"))

armed = pytest.mark.skipif(
    not schedcheck.ENABLED,
    reason="schedule exploration needs OSSE_SCHED=1 at import")


# --- the no-op contract (always runs) --------------------------------------


class TestUnarmedNoOp:
    """Outside an active explore() the plane must cost nothing: plain
    primitives, no wrappers, sched_point a no-op — whether or not
    OSSE_SCHED=1 is set (arming alone must not perturb tier-1)."""

    def test_factories_return_plain_primitives_when_idle(self):
        assert schedcheck._active is None
        assert not isinstance(lockcheck.make_lock("t.l"),
                              schedcheck.SchedLock)
        assert not isinstance(lockcheck.make_rlock("t.rl"),
                              schedcheck.SchedRLock)
        assert isinstance(lockcheck.make_condition("t.cv"),
                          threading.Condition)
        assert isinstance(lockcheck.make_event("t.ev"), threading.Event)
        t = threads.make_thread("t.th", lambda: None)
        assert type(t) is threading.Thread

    def test_sched_point_and_settle_are_noops_when_idle(self):
        schedcheck.sched_point("anywhere")
        schedcheck.settle()  # returns immediately, no virtual clock

    def test_explore_requires_arming(self):
        if schedcheck.ENABLED:
            pytest.skip("armed session")
        with pytest.raises(RuntimeError, match="OSSE_SCHED"):
            schedcheck.explore(lambda: None, schedules=1)

    def test_monotonic_unpatched_when_idle(self):
        import time
        assert time.monotonic is schedcheck._REAL_MONOTONIC


# --- toy workloads for the explorer itself ---------------------------------


def _toy_lost_update():
    counter = {"v": 0}

    def bump(name):
        v = counter["v"]
        schedcheck.sched_point(f"{name}.read")
        counter["v"] = v + 1

    ts = [threads.make_thread(f"w{i}",
                              functools.partial(bump, f"w{i}"))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert counter["v"] == 2, f"lost update: counter={counter['v']}"


def _toy_locked_update():
    counter = {"v": 0}
    mu = lockcheck.make_lock("toy.mu")

    def bump(name):
        with mu:
            v = counter["v"]
            schedcheck.sched_point(f"{name}.read")
            counter["v"] = v + 1

    ts = [threads.make_thread(f"w{i}",
                              functools.partial(bump, f"w{i}"))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert counter["v"] == 2


def _toy_abba():
    a = lockcheck.make_lock("toy.A")
    b = lockcheck.make_lock("toy.B")

    def t1():
        with a:
            schedcheck.sched_point("t1.holds.A")
            with b:
                pass

    def t2():
        with b:
            schedcheck.sched_point("t2.holds.B")
            with a:
                pass

    ts = [threads.make_thread("t1", t1), threads.make_thread("t2", t2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


@armed
class TestExplorer:
    def test_same_seed_byte_identical_trace(self):
        """One seed = one exact interleaving, replayable forever."""
        t1 = schedcheck.trace_of(_toy_lost_update, seed=7)
        t2 = schedcheck.trace_of(_toy_lost_update, seed=7)
        assert t1 == t2
        assert any("sched_point" in ln or ".read" in ln for ln in t1)

    def test_toy_race_found_and_shrunk(self):
        with pytest.raises(schedcheck.ScheduleFailure) as ei:
            schedcheck.explore(_toy_lost_update, schedules=BUDGET)
        f = ei.value
        assert f.schedules_run <= BUDGET
        # shrunk to a minimal preemption trace: one forced switch
        # between the read and the write is sufficient
        assert len(f.decisions) <= 2, f.decisions
        assert ".read" in str(f), "timeline must name the racing point"

    def test_locked_toy_survives_exploration(self):
        out = schedcheck.explore(_toy_locked_update, schedules=32)
        assert out["failures"] == 0
        assert out["yield_points"] > 0

    def test_abba_deadlock_detected(self):
        with pytest.raises(schedcheck.ScheduleFailure) as ei:
            schedcheck.explore(_toy_abba, schedules=BUDGET)
        assert "deadlock" in str(ei.value)

    def test_failure_replay_reproduces(self):
        """The seed in a ScheduleFailure replays to the same failure."""
        with pytest.raises(schedcheck.ScheduleFailure) as ei:
            schedcheck.explore(_toy_lost_update, schedules=BUDGET)
        seed = ei.value.seed
        with pytest.raises(schedcheck.ScheduleFailure) as ei2:
            schedcheck.explore(_toy_lost_update, schedules=1, seed=seed)
        assert ei2.value.seed == seed


# --- the five protocol scenario suites -------------------------------------


@armed
class TestScenarioSuites:
    @pytest.mark.parametrize("name", sorted(sched_scenarios.SCENARIOS))
    def test_scenario_clean_under_budget(self, name):
        fn = sched_scenarios.SCENARIOS[name]
        out = schedcheck.explore(fn, schedules=BUDGET)
        assert out["failures"] == 0
        assert out["schedules"] == BUDGET
        assert out["yield_points"] > 0, "scenario never hit the plane?"


# --- seeded historical-bug regressions -------------------------------------


@armed
class TestSeededRegressions:
    """The explorer must rediscover the races this repo actually
    shipped, from test-local buggy subclasses — within budget, with
    shrunk traces that name the racing points."""

    def test_pr4_generation_stamp_race_found(self):
        # PR 4: cache entry stamped with the generation re-read at put
        # time instead of captured at entry — a write landing between
        # compute and put masquerades the stale value as fresh
        fn = functools.partial(
            sched_scenarios.scenario_cache_generation,
            cache_cls=sched_scenarios.make_buggy_cache_cls())
        with pytest.raises(schedcheck.ScheduleFailure) as ei:
            schedcheck.explore(fn, schedules=BUDGET)
        f = ei.value
        assert f.schedules_run <= BUDGET
        msg = str(f)
        assert "gen.bump" in msg and "buggy.put" in msg, msg

    def test_pr13_lone_hog_displacement_found(self):
        # PR 13: _displace_locked computed the victim's share without
        # counting the displacer — a lone hog's share came out
        # unbounded, so the quiet tenant shed queue_full instead
        fn = functools.partial(
            sched_scenarios.scenario_admission_quota,
            gate_cls=sched_scenarios.make_buggy_gate_cls())
        with pytest.raises(schedcheck.ScheduleFailure) as ei:
            schedcheck.explore(fn, schedules=BUDGET)
        f = ei.value
        assert f.schedules_run <= BUDGET
        assert "queue_full" in str(f)


@armed
@pytest.mark.slow
class TestDeepExploration:
    """The BENCH_SCHED=1 deep run's pytest twin: 1024 schedules per
    scenario, still zero findings."""

    @pytest.mark.parametrize("name", sorted(sched_scenarios.SCENARIOS))
    def test_scenario_clean_deep(self, name):
        out = schedcheck.explore(sched_scenarios.SCENARIOS[name],
                                 schedules=1024)
        assert out["failures"] == 0
