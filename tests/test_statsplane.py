"""Metrics-plane tests: mergeable log-linear histograms, the 2-shard
fleet scrape, SLO error budgets under a chaos latency wedge, and the
exemplar-linked /admin/perf + /metrics surfacing."""

import json
import random
import re
import urllib.request

import pytest

from open_source_search_engine_tpu.utils import stats as stats_mod
from open_source_search_engine_tpu.utils.slo import SloTracker
from open_source_search_engine_tpu.utils.stats import (LatencyStat,
                                                       Stats, g_stats,
                                                       merge_wire)

#: one bucket's relative error (1/_SUB) plus interpolation slack
REL_ERR = 1.0 / stats_mod._SUB + 0.02


def _true_quantile(vals, q):
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(q * len(vs)))]


class TestHistogram:
    def test_sub_ms_samples_resolve_below_1ms(self):
        # the old log2 floor reported 1.0ms for ANY sub-ms sample
        st = LatencyStat()
        for _ in range(200):
            st.add(0.003)
        assert 0.0025 < st.quantile(0.5) < 0.0035
        assert st.to_dict()["p99_ms"] < 0.01

    def test_quantile_interpolates_within_bucket(self):
        # 70ms everywhere must report ~70, not the 128 the old
        # bucket-upper-bound answer gave
        st = LatencyStat()
        for _ in range(100):
            st.add(70.0)
        assert abs(st.quantile(0.99) - 70.0) / 70.0 <= REL_ERR

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_merge_matches_combined_stream(self, seed):
        rng = random.Random(seed)
        vals = [rng.lognormvariate(1.0, 2.0) for _ in range(4000)]
        cut = rng.randrange(1, len(vals) - 1)
        a, b, both = LatencyStat(), LatencyStat(), LatencyStat()
        for v in vals[:cut]:
            a.add(v)
        for v in vals[cut:]:
            b.add(v)
        for v in vals:
            both.add(v)
        a.merge(b)
        assert a.count == len(vals)
        for q in (0.5, 0.9, 0.99):
            # merged == the recorder that saw the whole stream...
            assert abs(a.quantile(q) - both.quantile(q)) < 1e-9
            # ...and both track the exact stream within one bucket
            true = _true_quantile(vals, q)
            assert abs(a.quantile(q) - true) / true <= REL_ERR, q

    def test_wire_roundtrip_and_merge_wire(self):
        ga, gb = Stats(), Stats()
        rng = random.Random(3)
        vals = [rng.uniform(0.1, 50.0) for _ in range(600)]
        for v in vals[:300]:
            ga.record_ms("m", v)
        for v in vals[300:]:
            gb.record_ms("m", v)
        ga.count("c", 2)
        gb.count("c", 5)
        gb.gauge("g", 7.0)
        # wire forms must survive JSON (what /rpc/stats actually ships)
        wires = [json.loads(json.dumps(ga.wire())),
                 json.loads(json.dumps(gb.wire()))]
        fleet = merge_wire(wires)
        assert fleet["counters"]["c"] == 7
        assert fleet["gauges"]["g"] == 7.0
        st = fleet["latencies"]["m"]
        assert st.count == 600
        true = _true_quantile(vals, 0.99)
        assert abs(st.quantile(0.99) - true) / true <= REL_ERR

    def test_count_over(self):
        st = LatencyStat()
        for v in (1.0, 2.0, 100.0, 200.0):
            st.add(v)
        assert st.count_over(50.0) == 2
        assert st.count_over(0.001) == 4
        assert st.count_over(1e9) == 0

    def test_exemplar_pins_to_bucket(self):
        st = LatencyStat()
        st.add(5.0)
        st.add(500.0, exemplar="t-slow")
        idx = stats_mod._bucket_index(500.0)
        assert st.exemplars[idx][0] == "t-slow"
        # merge carries exemplars across
        other = LatencyStat()
        other.merge(st)
        assert other.exemplars[idx][0] == "t-slow"

    def test_reset_preserves_gauges(self):
        g = Stats()
        g.count("c")
        g.record_ms("l", 5.0)
        g.gauge("pool_size", 16.0)
        g.reset()
        snap = g.snapshot()
        assert snap["counters"] == {} and snap["latencies"] == {}
        assert snap["gauges"] == {"pool_size": 16.0}
        g.reset_gauges()
        assert g.snapshot()["gauges"] == {}


class TestSlo:
    def test_burn_and_recovery_with_injected_clock(self):
        reg = Stats()
        slo = SloTracker(registry=reg)
        slo.declare_latency("query_p99", "q", threshold_ms=100.0,
                            target=0.9, window_s=60.0)
        now = 1000.0
        for _ in range(50):
            reg.record_ms("q", 5.0)
        st = slo.evaluate(now=now)["query_p99"]
        assert st["burn_rate"] == 0.0 and st["budget_remaining"] == 1.0
        assert not slo.degraded()
        # the wedge: everything over threshold
        for _ in range(50):
            reg.record_ms("q", 500.0)
        st = slo.evaluate(now=now + 1)["query_p99"]
        assert st["burn_rate"] > 1.0
        assert slo.degraded() and slo.degraded("query_p99")
        assert reg.snapshot()["gauges"]["slo.query_p99.burn_rate"] > 1.0
        # recovery: fault gone, window rolls past the bad deltas
        for _ in range(50):
            reg.record_ms("q", 5.0)
        st = slo.evaluate(now=now + 120.0)["query_p99"]
        assert st["burn_rate"] <= 1.0
        assert not slo.degraded()
        assert reg.snapshot()["gauges"]["slo.degraded"] == 0.0

    def test_availability_objective(self):
        reg = Stats()
        slo = SloTracker(registry=reg)
        slo.declare_availability("avail", "rpc.ok", "rpc.err",
                                 target=0.999, window_s=60.0)
        reg.count("rpc.ok", 999)
        st = slo.evaluate(now=10.0)["avail"]
        assert st["burn_rate"] == 0.0
        reg.count("rpc.err", 10)
        st = slo.evaluate(now=11.0)["avail"]
        assert st["burn_rate"] > 1.0


def _mk_cluster(tmp_path, n_nodes=2, docs_per_node=6):
    from open_source_search_engine_tpu.parallel import cluster as cl
    nodes = []
    for i in range(n_nodes):
        node = cl.ShardNodeServer(tmp_path / f"n{i}")
        for d in range(docs_per_node):
            node.handle("/rpc/index", {
                "url": f"http://t.test/{i}-{d}",
                "content": (f"<html><body><p>alpha bravo words "
                            f"token{i}x{d}</p></body></html>")})
        node.start()
        nodes.append(node)
    conf = cl.HostsConf.parse(
        "num-mirrors: 0\n"
        + "\n".join(f"127.0.0.1:{n.port}" for n in nodes))
    client = cl.ClusterClient(conf, use_heartbeat=False)
    return nodes, client


class TestFleetScrape:
    def test_two_shard_scrape_matches_ground_truth(self, tmp_path):
        nodes, client = _mk_cluster(tmp_path)
        try:
            # private per-node registries: in one process both nodes
            # would otherwise serve the same g_stats singleton and the
            # merge would be the singleton merged with itself
            for n in nodes:
                n.stats_registry = Stats()
            rng = random.Random(11)
            ground = LatencyStat()
            vals = []
            for n in nodes:
                n.stats_registry.count("node.queries", 100)
                for _ in range(400):
                    v = rng.lognormvariate(1.5, 1.2)
                    vals.append(v)
                    n.stats_registry.record_ms("node.query", v)
                    ground.add(v)
            sc = client.scrape()
            assert all(w is not None for w in sc["hosts"].values())
            fleet = sc["fleet"]
            assert fleet["counters"]["node.queries"] == 200
            st = fleet["latencies"]["node.query"]
            assert st.count == 800
            for q in (0.5, 0.99):
                # merged fleet == ground-truth single recorder...
                assert abs(st.quantile(q) - ground.quantile(q)) < 1e-9
                # ...and the exact stream within one bucket's error
                true = _true_quantile(vals, q)
                assert abs(st.quantile(q) - true) / true <= REL_ERR
        finally:
            client.close()
            for n in nodes:
                n.stop()

    def test_dead_host_scrapes_as_none(self, tmp_path):
        nodes, client = _mk_cluster(tmp_path)
        try:
            nodes[1].stop()
            # generous timeout: the live host must answer even on a
            # loaded CI box — the DEAD host is detected by refusal
            # (closed port), not by racing this budget
            sc = client.scrape(timeout=2.0)
            vals = list(sc["hosts"].values())
            assert sum(1 for w in vals if w is None) == 1
            assert sum(1 for w in vals if w is not None) == 1
        finally:
            client.close()
            nodes[0].stop()

    def test_chaos_wedge_burns_budget_then_recovers(self, tmp_path):
        from open_source_search_engine_tpu.utils.chaos import g_chaos
        nodes, client = _mk_cluster(tmp_path)
        slo = SloTracker(registry=g_stats)
        slo.declare_latency("query_p99", "cluster.query",
                            threshold_ms=30.0, target=0.95,
                            window_s=60.0)
        now = 5000.0
        try:
            # warm the stack (JAX compiles, pools), then drop the
            # warmup latencies so only steady-state samples are judged
            for k in range(8):
                client.search(f"alpha warm{k}", topk=5)
            g_stats.reset()
            for k in range(20):
                client.search(f"alpha h{k}", topk=5)
            st = slo.evaluate(now=now)["query_p99"]
            assert st["burn_rate"] <= 1.0, st
            # the wedge: every node leg slowwalks well past threshold
            g_chaos.enable(4242, rate=0.0)
            g_chaos.configure("cluster.node", rate=1.0,
                              kinds=("slowwalk",), delay_s=0.08)
            for k in range(10):
                client.search(f"alpha w{k}", topk=5)
            assert g_chaos.fired("cluster.node").get("slowwalk", 0) > 0
            st = slo.evaluate(now=now + 1)["query_p99"]
            assert st["burn_rate"] > 1.0, st
            assert slo.degraded() and slo.degraded("query_p99")
            gauges = g_stats.snapshot()["gauges"]
            assert gauges["slo.query_p99.burn_rate"] > 1.0
            # fault removed: fresh healthy traffic + the window
            # rolling past the wedge recovers the budget
            g_chaos.disable()
            for k in range(20):
                client.search(f"alpha r{k}", topk=5)
            st = slo.evaluate(now=now + 120.0)["query_p99"]
            assert st["burn_rate"] <= 1.0, st
            assert not slo.degraded()
        finally:
            g_chaos.disable()
            client.close()
            for n in nodes:
                n.stop()


DOC = ("<html><head><title>Perf page</title></head><body>"
       "<p>solar panels convert sunlight efficiently</p></body></html>")


@pytest.fixture()
def server(tmp_path):
    from open_source_search_engine_tpu.serve import serve
    s = serve(tmp_path / "srv", port=0)
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}") as r:
        return r.status, r.read().decode(), r.headers.get_content_type()


class TestPerfSurfacing:
    def test_perf_metrics_json_and_exemplar_resolves(self, server):
        from open_source_search_engine_tpu.utils.trace import (
            DEFAULT_SAMPLE_N, g_tracer)
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}"
            "/inject?u=http://perf.example.com/p", data=DOC.encode())
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        g_tracer.configure(sample_n=1)
        try:
            for k in range(4):
                _get(server, f"/search?q=sunlight+x{k}")
        finally:
            g_tracer.configure(sample_n=DEFAULT_SAMPLE_N)

        # /admin/perf?format=json: merged view with exemplars
        _, body, ctype = _get(server, "/admin/perf?format=json")
        assert ctype == "application/json"
        perf = json.loads(body)
        lat = perf["fleet"]["latencies"]["serve.search"]
        assert lat["count"] >= 4
        assert lat["exemplars"], "sampled traces must pin exemplars"
        tid = lat["exemplars"][-1]["trace_id"]

        # the exemplar trace id resolves on /admin/traces
        status, tbody, _ = _get(server, f"/admin/traces?id={tid}")
        assert status == 200 and tid in tbody

        # /admin/perf HTML: fleet table + a live exemplar link
        _, html, ctype = _get(server, "/admin/perf")
        assert ctype == "text/html"
        assert "serve.search" in html and "fleet" in html
        m = re.search(r'href="/admin/traces\?id=([a-f0-9]+)', html)
        assert m is not None
        status, _, _ = _get(server, f"/admin/traces?id={m.group(1)}")
        assert status == 200

        # /metrics: Prometheus exposition with histogram + exemplar
        _, text, ctype = _get(server, "/metrics")
        assert ctype == "text/plain"
        assert 'osse_latency_ms_bucket{name="serve.search"' in text
        assert "trace_id=" in text
        assert 'osse_counter{name="query"}' in text
