"""Deterministic golden-QA fixture corpus (the reference's magic "test"
collection, Test.h:10 — fixed inputs, diffable outputs)."""

WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot",
         "golf", "hotel", "india", "juliet", "kilo", "lima"]


def golden_docs():
    """~40 docs over 8 sites with controlled term placement: titles,
    headings, repeated words, phrases, plurals (synonym targets), and a
    couple of near-duplicates for checksum dedup."""
    docs = {}
    for i in range(36):
        w1 = WORDS[i % len(WORDS)]
        w2 = WORDS[(i * 5 + 2) % len(WORDS)]
        w3 = WORDS[(i * 7 + 5) % len(WORDS)]
        title = f"{w1.capitalize()} {w2} report {i}"
        body = (f"<h2>{w2} overview</h2>"
                f"<p>The {w1} {w2} study number{i} covers {w3} topics. "
                + (f"{w1} " * (i % 4 + 1))
                + f"appears often. {w2} {w3} closing remarks.</p>")
        if i % 6 == 0:
            body += f"<p>Plural forms: {w1}s and {w2}s everywhere.</p>"
        docs[f"http://site{i % 8}.golden.test/page{i}"] = (
            f"<html><head><title>{title}</title></head><body>{body}"
            "</body></html>")
    # exact near-duplicates (content-hash dedup targets)
    dup = ("<html><head><title>Duplicate lima kilo</title></head><body>"
           "<p>lima kilo duplicate content block.</p></body></html>")
    docs["http://site1.golden.test/dup-a"] = dup
    docs["http://site2.golden.test/dup-b"] = dup
    return docs


GOLDEN_QUERIES = [
    # single terms (incl. synonym targets)
    "alpha", "bravo", "kilo", "alphas", "report",
    # conjunctive AND
    "alpha bravo", "charlie delta report", "echo foxtrot",
    "india juliet kilo",
    # phrases
    '"alpha bravo"', '"closing remarks"', '"lima kilo"',
    '"bravo overview"',
    # negation
    "report -alpha", "bravo -charlie", "kilo -lima",
    # site filters
    "site:site0.golden.test alpha", "site:site3.golden.test report",
    "inurl:page7 report",
    # boolean trees
    "alpha AND bravo", "alpha OR bravo", "alpha AND NOT bravo",
    "(alpha OR bravo) AND charlie", "alpha AND (bravo OR charlie)",
    "report AND NOT (alpha OR bravo)", "alpha AND -bravo",
    "lima OR (kilo AND juliet)",
    # mixed operators
    '"alpha bravo" -charlie', 'site:site1.golden.test "lima kilo"',
    "alpha bravo charlie delta",
    # synonyms / plurals
    "study", "studies", "topic", "topics", "form", "forms",
    # misses and edge cases
    "zulu", "alpha zulu", "-alpha", "report number3",
    "number12 charlie", "echo echo echo",
    # deeper multi-term
    "delta echo foxtrot golf", "overview closing",
    "bravo study number0", "juliet report -echo",
    "alpha OR zulu", "zulu OR yankee", "NOT alpha",
    "site:site0.golden.test OR kilo",  # filter-only matches via OR
]
