"""jitwatch — runtime compile/retrace/transfer attribution.

The runtime half of the jit analysis plane: a forced retrace must be
attributed to its call site, explicit transfers must be counted and
keyed by site, OSSE_JITWATCH=0 must be a true no-op (no patched
entry points, no log handlers, no config flip, no counters), and
enable/disable must restore every hook exactly.
"""

import json
import logging
import subprocess
import sys
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from open_source_search_engine_tpu.utils import jitwatch
from open_source_search_engine_tpu.utils.stats import g_stats

ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture
def watch():
    """Enabled watcher with a fresh table; restores the pre-test
    enablement (tier-1 runs both with and without OSSE_JITWATCH=1)."""
    was = jitwatch.enabled()
    jitwatch.enable()
    jitwatch.reset()
    yield jitwatch.g_jitwatch
    jitwatch.reset()
    if not was:
        jitwatch.disable()


def test_retrace_attributed_to_call_site(watch):
    @jax.jit
    def _probe(x):
        return x + 1

    small = jnp.ones((4,), jnp.float32)
    big = jnp.ones((16,), jnp.float32)  # built pre-reset: jnp.ones
    # itself cold-traces an internal broadcast per shape
    _probe(small)  # cold: first trace
    jitwatch.reset()
    _probe(big)  # new shape: retrace
    snap = jitwatch.snapshot()
    assert snap["totals"]["retraces"] == 1
    assert snap["totals"]["first_traces"] == 0
    assert snap["totals"]["compiles"] >= 1
    ev = [e for e in snap["events"] if e["kind"] == "retrace"]
    assert ev, snap["events"]
    # the site is THIS file and the miss explanation names the cause
    assert "test_jitwatch.py" in ev[0]["site"]
    assert "never seen" in ev[0]["last"]
    ctr = g_stats.snapshot()["counters"]
    assert any(k.startswith("jit.retrace.") for k in ctr)


def test_steady_state_is_quiet(watch):
    @jax.jit
    def _probe2(x):
        return x * 2

    _probe2(jnp.ones((8,), jnp.float32))
    jitwatch.reset()
    for _ in range(4):
        _probe2(jnp.ones((8,), jnp.float32))  # warm: same shape
    t = jitwatch.snapshot()["totals"]
    assert t["compiles"] == 0 and t["retraces"] == 0


def test_transfer_events_counted_and_sited(watch):
    x = jnp.ones((8,), jnp.float32)
    x.block_until_ready()
    jitwatch.reset()
    jax.device_get(x)
    snap = jitwatch.snapshot()
    assert snap["totals"]["transfers"] == 1
    ev = [e for e in snap["events"] if e["kind"] == "transfer"]
    assert ev[0]["fn"] == "device_get"
    assert "test_jitwatch.py" in ev[0]["site"]
    assert ev[0]["bytes"] == 32
    # tests/ is not a blessed device-boundary module
    assert not ev[0]["boundary"]
    assert snap["totals"]["transfers_offboundary"] == 1
    assert not jitwatch.is_boundary_site(ev[0]["site"])
    assert jitwatch.is_boundary_site("query/devindex.py:1582")


def test_enable_disable_restores_hooks():
    was = jitwatch.enabled()
    jitwatch.enable()
    assert not jax.device_get.__module__.startswith("jax")
    jitwatch.disable()
    # entry points, handlers, and logger state all restored
    assert jax.device_get.__module__.startswith("jax")
    assert jax.device_put.__module__.startswith("jax")
    for name in jitwatch._JAX_LOGGERS:
        lg = logging.getLogger(name)
        assert jitwatch.g_jitwatch._handler not in lg.handlers
    if was:
        jitwatch.enable()


def test_off_is_true_noop():
    """With OSSE_JITWATCH unset, importing the device layer must not
    patch jax, hook loggers, flip config, or mint jit.* counters."""
    code = (
        "import os\n"
        "os.environ.pop('OSSE_JITWATCH', None)\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import logging\n"
        "import jax\n"
        "from open_source_search_engine_tpu.utils import jitwatch\n"
        "from open_source_search_engine_tpu.query import devindex\n"
        "assert not jitwatch.enabled()\n"
        "assert jax.device_get.__module__.startswith('jax')\n"
        "assert jax.device_put.__module__.startswith('jax')\n"
        "assert not jax.config.jax_explain_cache_misses\n"
        "for n in jitwatch._JAX_LOGGERS:\n"
        "    assert not logging.getLogger(n).handlers\n"
        "from open_source_search_engine_tpu.utils.stats import g_stats\n"
        "ctr = g_stats.snapshot()['counters']\n"
        "assert not any(k.startswith('jit.') for k in ctr), ctr\n"
        "print('NOOP-OK')\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr
    assert "NOOP-OK" in proc.stdout


def test_env_enables_via_device_layer_import():
    """OSSE_JITWATCH=1 + importing devindex turns the watcher on —
    no entry point has to opt in."""
    code = (
        "import os\n"
        "os.environ['OSSE_JITWATCH'] = '1'\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from open_source_search_engine_tpu.query import devindex\n"
        "from open_source_search_engine_tpu.utils import jitwatch\n"
        "assert jitwatch.enabled()\n"
        "print('ON-OK')\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr
    assert "ON-OK" in proc.stdout


def test_admin_jit_page(tmp_path, watch):
    """/admin/jit serves the attribution table in HTML and JSON."""
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    jax.device_get(jnp.ones((4,), jnp.float32))
    s = SearchHTTPServer(tmp_path, port=0)
    s.start()
    try:
        base = f"http://127.0.0.1:{s._httpd.server_port}"
        html = urllib.request.urlopen(f"{base}/admin/jit").read()
        assert b"jit plane" in html and b"watcher enabled" in html
        js = json.loads(urllib.request.urlopen(
            f"{base}/admin/jit?format=json").read())
        assert js["enabled"]
        assert js["totals"]["transfers"] >= 1
        assert any(e["kind"] == "transfer" for e in js["events"])
        assert any(k.startswith("jit.transfer.")
                   for k in js["counters"])
    finally:
        s.stop()
