"""Native (C++) build-plane parity: libdoccore vs the Python tokenizer.

The native path must be bit-identical to the Python reference path for
ASCII documents — same token columns, same term ids, same packed posdb
keys — so a collection indexed by either path (or a cluster mixing
both) produces identical postings. Reference seam: XmlDoc::hashAll
(XmlDoc.cpp:28957) and the Words.cpp/Pos.cpp tokenizer, whose host
plane is likewise C++.
"""

import os

import numpy as np
import pytest

from open_source_search_engine_tpu import native
from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.build import tokenizer as T
from open_source_search_engine_tpu.utils import ghash

pytestmark = pytest.mark.skipif(
    native.get_doccore() is None, reason="native doccore unavailable")

GNARLY = """<html><head><title>Tiger &amp; Friends: a Story</title>
<meta name="description" content="All about tigers; and lions.">
<meta property="article:published_time" content="2021-03-04T10:00:00">
<meta name="keywords" content="tiger lion habitat">
</head><body>
<nav><ul><li><a href="/home">Home page</a></li>
<li><a href="/about?x=1&amp;y=2">About us</a></li></ul></nav>
<h1>Tiger Habitat</h1>
<div class="main"><p>Tigers live in forests. They hunt deer, boar; and fish!
Are tigers endangered? Yes: very much so...</p>
<p>Second paragraph with <b>bold text</b> and
<a href="http://x.test/z">an external link</a>.</p></div>
<script>var x = "<p>ignored</p>";</script>
<style>.c { color: red; }</style>
<!-- a comment with <p>tags</p> inside -->
<table><tr><td>cell one</td><td>cell two</td></tr></table>
<footer><p>Copyright 2021 Tiger Site. All rights reserved.</p></footer>
<br/>trailing text
</body></html>"""

URL = "http://example.com/tigers-page_1"


def _both(html, url):
    os.environ["OSSE_NATIVE_TOKENIZE"] = "0"
    try:
        py = T.tokenize_html(html, url)
    finally:
        os.environ["OSSE_NATIVE_TOKENIZE"] = "1"
    nat = T.tokenize_html(html, url)
    assert getattr(nat, "native", None) is not None
    return py, nat


class TestTokenizerParity:
    def test_columns_identical(self):
        py, nat = _both(GNARLY, URL)
        assert py.words == nat.words
        assert py.wordpos == nat.wordpos
        assert py.hashgroups == nat.hashgroups
        assert py.sentence_ids == nat.sentence_ids
        assert py.section_ids == nat.section_ids

    def test_strings_identical(self):
        py, nat = _both(GNARLY, URL)
        assert py.title == nat.title
        assert py.meta_description == nat.meta_description
        assert py.meta_date == nat.meta_date
        assert py.text == nat.text
        assert py.links == nat.links

    def test_termids_match_ghash(self):
        _, nat = _both(GNARLY, URL)
        tids = np.array([ghash.term_id(w) for w in nat.words], np.uint64)
        assert (tids == nat.native.termid).all()

    def test_punctuation_edges(self):
        for frag in ("a.b", "...x", "x...", "a.!?b", "", ".",
                     "one two. three"):
            py, nat = _both(f"<p>{frag}</p>", None)
            assert py.words == nat.words, frag
            assert py.wordpos == nat.wordpos, frag
            assert py.sentence_ids == nat.sentence_ids, frag

    def test_edge_cases_parity(self):
        # stray '<' as data, entities, NUL bytes, unicode whitespace,
        # no-semicolon charrefs; unknowns must FALL BACK, not diverge
        cases = ["<p>1 < 2 > 3 and a<b</p>",
                 "<p>caf&eacute; and 5&times;3</p>",
                 "<p>a&nbsp;b</p>",
                 "<p>hello \x00 world this is text</p>",
                 "<p>x&#65 y</p>",
                 "<p>AT&T and &ampx</p>",          # legacy prefix → punt
                 "<p>x &hellip; y &frobnicate; z</p>"]  # unknown → punt
        for html in cases:
            os.environ["OSSE_NATIVE_TOKENIZE"] = "0"
            try:
                py = T.tokenize_html(html, None)
            finally:
                os.environ["OSSE_NATIVE_TOKENIZE"] = "1"
            nat = T.tokenize_html(html, None)  # may legally punt
            assert py.words == nat.words, html
            assert py.wordpos == nat.wordpos, html
            assert py.text == nat.text, html
            assert py.sentence_ids == nat.sentence_ids, html

    def test_unquoted_attr_trailing_slash_not_selfclose(self):
        # html.parser treats the '/' in <a href=foo/> as the TAIL OF
        # THE UNQUOTED VALUE (href="foo/"), not a self-closing slash —
        # a native parser that reads it as self-close drops the anchor
        # text out of the <a> scope (no link tuple, wrong hashgroups)
        cases = [
            "<a href=foo/>anchor text</a> tail",     # '/' in the value
            "<a href=foo />anchor</a>",              # real self-close
            '<a href="foo"/>anchor</a>',             # quoted + '/'
            "<a href=/>anchor</a>",                  # bare-slash value
            "<a checked/>anchor</a>",                # boolean attr
            "<a href=a/ b=c/>anchor</a>",            # '/' mid-list
        ]
        for frag in cases:
            html = f"<html><body>{frag}</body></html>"
            py, nat = _both(html, URL)
            assert py.words == nat.words, frag
            assert py.links == nat.links, frag
            assert py.hashgroups == nat.hashgroups, frag
            assert py.wordpos == nat.wordpos, frag
        # non-vacuous: the first case really keeps the '/' in the value
        # and the anchor text inside the link
        py, _ = _both("<html><body><a href=foo/>anchor text</a>"
                      "</body></html>", URL)
        assert ("foo/", "anchor text") in py.links

    def test_plain_text_parity(self):
        os.environ["OSSE_NATIVE_TOKENIZE"] = "0"
        try:
            py = T.tokenize_text("Plain text. With sentences! And words")
        finally:
            os.environ["OSSE_NATIVE_TOKENIZE"] = "1"
        nat = T.tokenize_text("Plain text. With sentences! And words")
        assert py.words == nat.words
        assert py.wordpos == nat.wordpos


class TestHashParity:
    def test_hash64(self):
        lib = native.get_doccore()
        for s in (b"tiger", b"a", b"", b"word123", b"x" * 1024):
            expect = ghash._FNV_OFFSET
            # recompute via the pure-python loop (bypass the native
            # dispatch inside ghash.hash64)
            h = ghash._FNV_OFFSET
            for b in s:
                h ^= b
                h = (h * ghash._FNV_PRIME) & ghash._MASK64
            h ^= h >> 33
            h = (h * 0xFF51AFD7ED558CCD) & ghash._MASK64
            h ^= h >> 33
            h = (h * 0xC4CEB9FE1A85EC53) & ghash._MASK64
            h ^= h >> 33
            assert native.hash64_native(s) == h


class TestMetaListParity:
    def test_posdb_keys_identical(self):
        inl = [("big tiger story", 5), ("tiger", 3)]
        os.environ["OSSE_NATIVE_TOKENIZE"] = "0"
        try:
            a = docproc.build_meta_list(URL, GNARLY, siterank=3,
                                        inlinks=inl)
        finally:
            os.environ["OSSE_NATIVE_TOKENIZE"] = "1"
        b = docproc.build_meta_list(URL, GNARLY, siterank=3, inlinks=inl)
        ka = np.sort(a.posdb_keys, order=("n2", "n1", "n0"))
        kb = np.sort(b.posdb_keys, order=("n2", "n1", "n0"))
        assert len(ka) == len(kb)
        assert (ka == kb).all()
        assert a.sections == b.sections
        assert a.langid == b.langid
        assert a.docid == b.docid

    def test_boiler_demotion_parity(self):
        # same section across "pages" — demote via explicit boiler set
        sect_py = None
        os.environ["OSSE_NATIVE_TOKENIZE"] = "0"
        try:
            t = T.tokenize_html(GNARLY, URL)
            sect_py = docproc.doc_section_hashes(t)
            boiler = list(sect_py.values())[:1]
            a = docproc.build_meta_list(URL, GNARLY, siterank=0,
                                        boiler_sections=boiler)
        finally:
            os.environ["OSSE_NATIVE_TOKENIZE"] = "1"
        t2 = T.tokenize_html(GNARLY, URL)
        sect_nat = docproc.doc_section_hashes(t2)
        assert sect_py == sect_nat
        b = docproc.build_meta_list(URL, GNARLY, siterank=0,
                                    boiler_sections=boiler)
        ka = np.sort(a.posdb_keys, order=("n2", "n1", "n0"))
        kb = np.sort(b.posdb_keys, order=("n2", "n1", "n0"))
        assert (ka == kb).all()
