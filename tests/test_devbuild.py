"""Device ingest-plane tests — bit-exactness against the host oracle.

The host NumPy pipeline in ``devindex._build_base``/``_build_delta`` is
the parity oracle for ``build/devbuild.py`` (same role the host-merge
path plays for mesh serving): every derived base column, directory
table and f16 impact must match *bitwise*, across corpora that exercise
tombstone annihilation, the ``occ < P`` store cap and multi-run merges.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import devbuild, docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query.devindex import DeviceIndex
from open_source_search_engine_tpu.utils import ghash
from open_source_search_engine_tpu.utils.stats import g_stats


def _mkdoc(rng, words, i, repeat=None):
    n = int(rng.integers(20, 160))
    toks = list(rng.choice(words, size=n))
    if repeat is not None:
        # one term far past the positions-per-(term,doc) store cap
        toks += [repeat] * 30
    return (f"http://h{i % 17}.example.com/p{i}",
            f"<html><title>{' '.join(rng.choice(words, size=4))}</title>"
            f"<body><p>{' '.join(toks)}</p></body></html>")


def _seed_corpus(tmp_path, seed, name="pb"):
    """Multi-run corpus with tombstones, re-adds and an over-cap term."""
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(300)]
    c = Collection(name, tmp_path / f"{name}{seed}")
    docs = [_mkdoc(rng, words, i) for i in range(100)]
    docs[7] = _mkdoc(rng, words, 7, repeat="capstone")
    docproc.index_batch(c, docs[:60])
    c.posdb.dump()
    c.titledb.dump()
    docproc.index_batch(c, docs[60:90])
    c.posdb.dump()
    # run 3: tombstones for docs living in runs 1 and 2, plus a re-add
    # (annihilation must collapse across run boundaries, newest wins)
    docproc.remove_document(c, docs[3][0])
    docproc.remove_document(c, docs[65][0])
    docproc.index_document(c, *docs[5])
    c.posdb.dump()
    return c, docs


_BASE_COLS = ("d_payload", "d_docc", "d_doc", "d_rs", "d_cnt",
              "d_siterank", "d_doclang", "d_cube", "d_dense_rs",
              "d_dense_cnt")


def _assert_columns_equal(host, dev):
    for name in ("dir_termids", "base_df", "dir_dstart", "dir_pstart",
                 "base_docids", "h_doc_col"):
        assert np.array_equal(getattr(host, name), getattr(dev, name)), name
    assert (host.Nb, host.Mb, host.N2, host.M2, host.D_cap) == \
           (dev.Nb, dev.Mb, dev.N2, dev.M2, dev.D_cap)
    for name in _BASE_COLS:
        a, b = np.asarray(getattr(host, name)), np.asarray(getattr(dev, name))
        assert a.shape == b.shape and np.array_equal(a, b), name
    # impacts compare as raw f16 bit patterns: the demotion rounding is
    # part of the contract, not an approximation
    for name in ("d_imp", "d_dense_imp"):
        a = np.asarray(getattr(host, name)).view(np.uint16)
        b = np.asarray(getattr(dev, name)).view(np.uint16)
        assert np.array_equal(a, b), name


class TestBaseBitExact:
    @pytest.mark.parametrize("seed", [7, 23, 101])
    def test_device_base_matches_host_oracle(self, tmp_path, monkeypatch,
                                             seed):
        c, _ = _seed_corpus(tmp_path, seed)
        # device first: the device plane never writes the disk cache, so
        # the host build below derives from scratch (a cache hit would
        # make this test compare the cache against itself)
        monkeypatch.setenv("OSSE_DEVBUILD", "1")
        before = g_stats.counters.get("build.devbuild_fallback", 0)
        dev = DeviceIndex(c)
        assert g_stats.counters.get("build.devbuild_fallback", 0) == before
        monkeypatch.setenv("OSSE_DEVBUILD", "0")
        host = DeviceIndex(c)
        assert host._base_fp == dev._base_fp
        _assert_columns_equal(host, dev)

    def test_store_cap_applied(self, tmp_path, monkeypatch):
        """The over-cap doc keeps exactly P positions of the repeated
        term on both paths (occ < P store cap)."""
        c, _ = _seed_corpus(tmp_path, 7, name="cap")
        monkeypatch.setenv("OSSE_DEVBUILD", "1")
        dev = DeviceIndex(c)
        tid = ghash.term_id("capstone")
        i = int(np.searchsorted(dev.dir_termids, np.uint64(tid)))
        assert dev.dir_termids[i] == np.uint64(tid)
        d0, d1 = int(dev.dir_dstart[i]), int(dev.dir_dstart[i + 1])
        assert d1 - d0 == 1  # one (term, doc) pair
        p0, p1 = int(dev.dir_pstart[i]), int(dev.dir_pstart[i + 1])
        assert p1 - p0 == dev.P  # 30 occurrences capped to P stored


class TestDeltaFold:
    QUERIES = ["w1", "w2 w3", '"w4 w5"', "w1 -w2", "capstone"]

    def test_delta_fold_equals_full_rebuild(self, tmp_path, monkeypatch):
        """Folding unflushed writes as a device delta tile must rank
        identically to dumping them and rebuilding the base."""
        monkeypatch.setenv("OSSE_DEVBUILD", "1")
        rng = np.random.default_rng(31)
        words = [f"w{i}" for i in range(120)]
        c, docs = _seed_corpus(tmp_path, 31, name="df")
        folded = DeviceIndex(c)
        # unflushed writes: adds + a tombstone for a base doc
        extra = [_mkdoc(rng, words, 1000 + i) for i in range(20)]
        docproc.index_batch(c, extra)
        docproc.remove_document(c, docs[10][0])
        before = g_stats.counters.get("build.device_delta", 0)
        deltas = folded.delta_rebuilds
        assert folded.refresh()
        assert folded.delta_rebuilds == deltas + 1
        assert folded.full_rebuilds == 1  # the fold never rebuilt the base
        assert g_stats.counters.get("build.device_delta", 0) == before + 1
        # oracle: dump the memtable and full-rebuild from the runs
        c.posdb.dump()
        c.titledb.dump()
        rebuilt = DeviceIndex(c)
        assert rebuilt.full_rebuilds == 1
        for q in self.QUERIES:
            a = folded.search(q, topk=32)
            b = rebuilt.search(q, topk=32)
            assert a[2] == b[2], q
            ka = sorted(zip([round(float(s), 3) for s in a[1][:a[2]]],
                            a[0][:a[2]]))
            kb = sorted(zip([round(float(s), 3) for s in b[1][:b[2]]],
                            b[0][:b[2]]))
            assert ka == kb, q

    def test_delta_matches_host_delta(self, tmp_path, monkeypatch):
        """Device delta columns bit-exact vs the host delta oracle."""
        c, docs = _seed_corpus(tmp_path, 57, name="dh")
        rng = np.random.default_rng(57)
        words = [f"w{i}" for i in range(120)]
        extra = [_mkdoc(rng, words, 2000 + i) for i in range(15)]

        monkeypatch.setenv("OSSE_DEVBUILD", "1")
        dev = DeviceIndex(c)
        docproc.index_batch(c, extra)
        docproc.remove_document(c, docs[11][0])
        assert dev.refresh()

        monkeypatch.setenv("OSSE_DEVBUILD", "0")
        host = DeviceIndex(c)

        for name in ("dir2_termids", "delta_df", "dir2_dstart",
                     "dir2_pstart", "all_docids"):
            assert np.array_equal(getattr(host, name), getattr(dev, name)), \
                name
        _assert_columns_equal(host, dev)
        assert np.array_equal(np.asarray(host.d_dead),
                              np.asarray(dev.d_dead))


class TestCacheSwap:
    def test_crash_during_save_keeps_old_cache(self, tmp_path, monkeypatch):
        """Regression: the stale-fingerprint unlink must happen AFTER
        the new cache file lands — a crash mid-save used to leave no
        cache at all, forcing a full rebuild on next boot."""
        monkeypatch.setenv("OSSE_DEVBUILD", "0")  # host path writes cache
        c, _ = _seed_corpus(tmp_path, 13, name="cs")
        idx = DeviceIndex(c)
        old_cache = idx._cache_path(idx._base_fp)
        assert old_cache.exists()

        # run-set moves → new fingerprint; crash while saving its cache
        docproc.index_batch(c, [("http://x.example.com/new",
                                 "<html><body><p>fresh words here"
                                 "</p></body></html>")])
        c.posdb.dump()

        def boom(*a, **k):
            raise OSError("disk full")
        monkeypatch.setattr(np, "savez", boom)
        with pytest.raises(OSError):
            DeviceIndex(c)
        # the old fingerprint's cache must have survived the crash
        assert old_cache.exists()

        monkeypatch.undo()
        monkeypatch.setenv("OSSE_DEVBUILD", "0")
        idx2 = DeviceIndex(c)
        new_cache = idx2._cache_path(idx2._base_fp)
        assert new_cache.exists()
        assert not old_cache.exists()  # stale fingerprint reaped
