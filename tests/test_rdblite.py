"""Rdb-lite tests — modeled on the reference's component test binaries
``rdbtest``/``mergetest``/``treetest``/``bucketstest`` (SURVEY §4.3):
add/dump/merge/read cycles, tombstone annihilation, crash-restart
persistence."""

import numpy as np
import pytest

from open_source_search_engine_tpu.index import posdb, rdblite
from open_source_search_engine_tpu.index.rdblite import (
    MemTable, RecordBatch, Rdb, Run, merge_batches, searchsorted_keys,
)


def make_keys(termids, docids, wordpos=0, delbit=1):
    return posdb.pack(termid=termids, docid=docids, wordpos=wordpos,
                      delbit=delbit)


class TestSearchsorted:
    def test_matches_flat_searchsorted_on_random_keys(self):
        rng = np.random.default_rng(1)
        n = 5000
        keys = posdb.pack(
            termid=rng.integers(0, 50, n), docid=rng.integers(0, 1000, n),
            wordpos=rng.integers(0, 100, n),
        )
        keys = keys[rdblite.key_sort_order(keys)]
        # flat integer image for ground truth: (n2, n1, n0) as python tuples
        flat = [(int(k["n2"]), int(k["n1"]), int(k["n0"])) for k in keys]
        probes = keys[rng.integers(0, n, 64)]
        for side in ("left", "right"):
            got = searchsorted_keys(keys, probes, side)
            import bisect
            for g, p in zip(got, probes):
                t = (int(p["n2"]), int(p["n1"]), int(p["n0"]))
                want = (bisect.bisect_left if side == "left"
                        else bisect.bisect_right)(flat, t)
                assert g == want

    def test_empty_sorted_array(self):
        keys = make_keys([1], [1])
        out = searchsorted_keys(keys[:0], keys)
        assert out.tolist() == [0]


class TestRecordBatch:
    def test_from_records_sorts(self):
        keys = make_keys([2, 1, 1], [5, 9, 3])
        b = RecordBatch.from_records(keys)
        f = posdb.unpack(b.keys)
        assert f["termid"].tolist() == [1, 1, 2]
        assert f["docid"].tolist() == [3, 9, 5]

    def test_payloads_follow_sort(self):
        keys = make_keys([2, 1], [1, 1])
        b = RecordBatch.from_records(keys, [b"two", b"one"])
        assert b.payloads() == [b"one", b"two"]

    def test_range_read(self):
        keys = make_keys([1, 2, 2, 3], [1, 1, 2, 1])
        b = RecordBatch.from_records(keys)
        sub = b.range(posdb.start_key(2), posdb.end_key(2))
        f = posdb.unpack(sub.keys)
        assert f["termid"].tolist() == [2, 2]
        assert f["docid"].tolist() == [1, 2]


class TestMerge:
    def test_annihilation_negative_kills_positive(self):
        """A tombstone in a newer source annihilates the positive record
        (reference RdbList merge_r semantics)."""
        old = RecordBatch.from_records(make_keys([1, 1], [10, 20]))
        neg = RecordBatch.from_records(make_keys([1], [10], delbit=0))
        out = merge_batches([old, neg])
        f = posdb.unpack(out.keys)
        assert f["docid"].tolist() == [20]

    def test_positive_readd_after_delete_survives(self):
        """delete then re-add: newest wins, record comes back."""
        v1 = RecordBatch.from_records(make_keys([1], [10]))
        neg = RecordBatch.from_records(make_keys([1], [10], delbit=0))
        v2 = RecordBatch.from_records(make_keys([1], [10]))
        out = merge_batches([v1, neg, v2])
        assert len(out) == 1
        assert posdb.unpack(out.keys)["delbit"].tolist() == [1]

    def test_keep_tombstones_intermediate_merge(self):
        v1 = RecordBatch.from_records(make_keys([1], [10]))
        neg = RecordBatch.from_records(make_keys([1], [10], delbit=0))
        out = merge_batches([v1, neg], keep_tombstones=True)
        assert len(out) == 1
        assert posdb.unpack(out.keys)["delbit"].tolist() == [0]

    def test_payload_newest_wins(self):
        k = make_keys([1], [10])
        out = merge_batches([
            RecordBatch.from_records(k.copy(), [b"old"]),
            RecordBatch.from_records(k.copy(), [b"new"]),
        ])
        assert out.payloads() == [b"new"]

    def test_merge_is_sorted_and_distinct_positions_survive(self):
        """Same (termid,docid) at different wordpos are distinct records."""
        a = RecordBatch.from_records(make_keys([1, 1], [10, 10], [3, 7]))
        b = RecordBatch.from_records(make_keys([1], [10], [5]))
        out = merge_batches([a, b])
        f = posdb.unpack(out.keys)
        assert f["wordpos"].tolist() == [3, 5, 7]

    def test_all_empty_preserves_dtype(self):
        empty = RecordBatch.from_records(make_keys([], []))
        out = merge_batches([empty])
        assert out.keys.dtype == posdb.KEY_DTYPE


class TestMemTable:
    def test_append_then_sorted_read(self):
        mt = MemTable(posdb.KEY_DTYPE, has_data=False)
        mt.add(make_keys([3], [1]))
        mt.add(make_keys([1, 2], [1, 1]))
        f = posdb.unpack(mt.batch().keys)
        assert f["termid"].tolist() == [1, 2, 3]

    def test_tombstone_retained_in_ram(self):
        mt = MemTable(posdb.KEY_DTYPE, has_data=False)
        mt.add(make_keys([1], [5]))
        mt.add(make_keys([1], [5], delbit=0))
        b = mt.batch()
        assert len(b) == 1
        assert posdb.unpack(b.keys)["delbit"].tolist() == [0]


class TestRdb:
    def test_add_dump_read_cycle(self, tmp_path):
        db = Rdb("posdb", tmp_path, posdb.KEY_DTYPE)
        db.add(make_keys([1, 2], [10, 20]))
        db.dump()
        db.add(make_keys([1], [11]))
        lst = db.get_list(posdb.start_key(1), posdb.end_key(1))
        f = posdb.unpack(lst.keys)
        assert sorted(f["docid"].tolist()) == [10, 11]

    def test_delete_across_dump_boundary(self, tmp_path):
        db = Rdb("posdb", tmp_path, posdb.KEY_DTYPE)
        db.add(make_keys([7], [100]))
        db.dump()
        db.delete(make_keys([7], [100]))
        lst = db.get_list(posdb.start_key(7), posdb.end_key(7))
        assert len(lst) == 0

    def test_merge_bounds_run_count(self, tmp_path):
        db = Rdb("posdb", tmp_path, posdb.KEY_DTYPE, max_runs=3)
        for i in range(5):
            db.add(make_keys([i], [i]))
            db.dump()
        assert len(db.runs) <= 3 + 1
        all_recs = db.get_all()
        assert len(all_recs) == 5

    def test_payload_db(self, tmp_path):
        db = Rdb("titledb", tmp_path, posdb.KEY_DTYPE, has_data=True)
        db.add(make_keys([1], [10]), [b"hello world"])
        db.dump()
        db.add(make_keys([1], [11]), [b"second"])
        lst = db.get_list(posdb.start_key(1), posdb.end_key(1))
        assert lst.payloads() == [b"hello world", b"second"]

    def test_restart_recovers_runs_and_memtable(self, tmp_path):
        """Crash-restart: dumped runs + saved memtable reload losslessly
        (reference -saved.dat semantics, Process.cpp:1444)."""
        db = Rdb("posdb", tmp_path, posdb.KEY_DTYPE)
        db.add(make_keys([1], [10]))
        db.dump()
        db.add(make_keys([1], [11]))  # stays in memtable
        db.save()
        db2 = Rdb("posdb", tmp_path, posdb.KEY_DTYPE)
        lst = db2.get_list(posdb.start_key(1), posdb.end_key(1))
        f = posdb.unpack(lst.keys)
        assert sorted(f["docid"].tolist()) == [10, 11]

    def test_auto_dump_on_budget(self, tmp_path):
        db = Rdb("posdb", tmp_path, posdb.KEY_DTYPE,
                 max_memtable_bytes=1000)
        db.add(make_keys(np.arange(200), np.arange(200)))
        assert len(db.runs) >= 1

    def test_large_roundtrip_with_merge(self, tmp_path):
        rng = np.random.default_rng(2)
        db = Rdb("posdb", tmp_path, posdb.KEY_DTYPE)
        seen = set()
        for batch_i in range(4):
            tids = rng.integers(0, 20, 2000)
            dids = rng.integers(0, 500, 2000)
            wps = rng.integers(0, 50, 2000)
            db.add(make_keys(tids, dids, wps))
            seen.update(zip(tids.tolist(), dids.tolist(), wps.tolist()))
            db.dump()
        db.attempt_merge(force=True)
        assert len(db.runs) == 1
        out = db.get_all()
        f = posdb.unpack(out.keys)
        got = set(zip(f["termid"].tolist(), f["docid"].tolist(),
                      f["wordpos"].tolist()))
        assert got == seen


class TestMergePolicy:
    """attemptMerge write-amp policy (RdbBase.cpp:1400): only the newest
    suffix of runs merges; the big old base run is not rewritten."""

    def test_suffix_merge_keeps_base_run(self, tmp_path):
        import numpy as np

        from open_source_search_engine_tpu.index import posdb
        from open_source_search_engine_tpu.index.rdblite import Rdb

        rdb = Rdb("posdb", tmp_path, posdb.KEY_DTYPE, max_runs=3)
        # one big base run + several small dumps
        big = posdb.pack(termid=1, docid=np.arange(1, 5001, dtype=np.uint64),
                         wordpos=1, densityrank=1, siterank=0, hashgroup=0,
                         langid=1)
        rdb.add(big)
        rdb.dump()
        base_name = rdb.runs[0].path.name
        for i in range(4):
            small = posdb.pack(termid=10 + i,
                               docid=np.arange(1, 51, dtype=np.uint64),
                               wordpos=2, densityrank=1, siterank=0,
                               hashgroup=0, langid=1)
            rdb.add(small)
            rdb.dump()
        assert len(rdb.runs) <= 3 + 1
        rdb.attempt_merge()
        assert len(rdb.runs) <= 3
        # the base run was never rewritten
        assert rdb.runs[0].path.name == base_name
        # every record still served
        assert len(rdb.get_all()) == 5000 + 4 * 50

    def test_forced_full_merge(self, tmp_path):
        import numpy as np

        from open_source_search_engine_tpu.index import posdb
        from open_source_search_engine_tpu.index.rdblite import Rdb

        rdb = Rdb("posdb", tmp_path, posdb.KEY_DTYPE, max_runs=8)
        for t in range(3):
            rdb.add(posdb.pack(termid=t + 1,
                               docid=np.arange(1, 11, dtype=np.uint64),
                               wordpos=1, densityrank=1, siterank=0,
                               hashgroup=0, langid=1))
            rdb.dump()
        rdb.attempt_merge(force=True)
        assert len(rdb.runs) == 1
        assert len(rdb.get_all()) == 30

    def test_merged_runs_reload_in_order(self, tmp_path):
        import numpy as np

        from open_source_search_engine_tpu.index import posdb
        from open_source_search_engine_tpu.index.rdblite import Rdb

        rdb = Rdb("posdb", tmp_path, posdb.KEY_DTYPE, max_runs=2)
        for t in range(5):
            rdb.add(posdb.pack(termid=t + 1,
                               docid=np.arange(1, 6, dtype=np.uint64),
                               wordpos=1, densityrank=1, siterank=0,
                               hashgroup=0, langid=1))
            rdb.dump()
        names = [r.path.name for r in rdb.runs]
        rdb2 = Rdb("posdb", tmp_path, posdb.KEY_DTYPE, max_runs=2)
        assert [r.path.name for r in rdb2.runs] == names
        assert len(rdb2.get_all()) == len(rdb.get_all()) == 25


class TestTermlistCache:
    """RdbCache-style termlist cache: hits on repeat queries, version-
    keyed so a write can never serve a stale list."""

    def test_hits_and_version_invalidation(self, tmp_path):
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import Collection
        from open_source_search_engine_tpu.query import engine
        from open_source_search_engine_tpu.utils.stats import g_stats

        c = Collection("tc", tmp_path)
        docproc.index_document(
            c, "http://t.test/a",
            "<html><head><title>Cache</title></head><body>"
            "<p>cache me twice.</p></body></html>")
        g_stats.counters.pop("termlist_cache.hit", None)
        engine.search(c, "cache", topk=5, with_snippets=False)
        h0 = g_stats.counters.get("termlist_cache.hit", 0)
        engine.search(c, "cache", topk=5, with_snippets=False)
        assert g_stats.counters.get("termlist_cache.hit", 0) > h0
        # a write bumps the version: fresh results, no stale serve
        docproc.index_document(
            c, "http://t.test/b",
            "<html><head><title>Cache two</title></head><body>"
            "<p>cache again here.</p></body></html>")
        res = engine.search(c, "cache", topk=5, with_snippets=False)
        assert res.total_matches == 2
