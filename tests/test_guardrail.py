"""Guardrail plane: host memory budget + on-device checkify harness.

Host plane (utils/membudget.py — the Mem.cpp ``g_mem``/``m_maxMem``
gate): over-budget work degrades (defer/shrink the merge, flush the
memtable, shed the batch) instead of OOM-killing the process, and every
refusal is counted. Device plane (query/devcheck.py — SURVEY §5's
checkify/debug_nans equivalent): injected NaN scores, out-of-range
docids and corrupt cube tiles trip loud, labeled errors in BOTH eager
("interpret") and jitted modes under JAX_PLATFORMS=cpu (conftest pins
the backend). ``/admin/mem`` serves the live breakdown.
"""

import gc
import json

import numpy as np
import pytest

from open_source_search_engine_tpu.index import posdb, rdblite
from open_source_search_engine_tpu.query import devcheck
from open_source_search_engine_tpu.utils.membudget import g_membudget
from open_source_search_engine_tpu.utils.stats import g_stats

BIG = 1 << 62


@pytest.fixture(autouse=True)
def _fresh_guardrails():
    """The budget governor and check flag are process singletons —
    restore them around every test so a tiny limit can't leak."""
    g_membudget.reset()
    g_membudget.set_limit(BIG)
    devcheck.set_enabled(None)
    yield
    g_membudget.reset()
    g_membudget.set_limit(BIG)
    devcheck.set_enabled(None)


def _mk(tmp_path, **kw):
    return rdblite.Rdb("t", tmp_path, posdb.KEY_DTYPE, **kw)


def _keys(n, seed=0):
    # distinct termids → no newest-wins dedup: merge preserves count
    rng = np.random.default_rng(seed)
    return posdb.pack(termid=np.arange(1, n + 1) + (seed << 24),
                      docid=rng.integers(1, 1 << 30, n),
                      wordpos=rng.integers(0, 1000, n))


def _docs(n):
    return [(f"http://site{i}.test/p",
             f"<html><head><title>budget doc {i}</title></head><body>"
             f"<p>guardrail memory governor test words {i} tpu kernels"
             "</p></body></html>") for i in range(n)]


# ------------------------------------------------------- host plane

class TestMemBudget:
    def test_reserve_release_and_labels(self):
        g_membudget.set_limit(1 << 20)
        assert g_membudget.reserve("pack", 512 << 10)
        assert g_membudget.used("pack") == 512 << 10
        assert not g_membudget.reserve("merge", 768 << 10)
        snap = g_membudget.snapshot()
        assert snap["rejections"] == 1
        assert snap["labels"]["merge"]["rejections"] == 1
        g_membudget.release("pack", 512 << 10)
        assert g_membudget.used() == 0
        assert g_membudget.snapshot()["high_water"] == 512 << 10

    def test_reserving_context_releases(self):
        g_membudget.set_limit(1 << 20)
        with g_membudget.reserving("docproc", 1 << 18) as ok:
            assert ok and g_membudget.used("docproc") == 1 << 18
        assert g_membudget.used() == 0

    def test_reject_counts_in_stats(self):
        base = g_stats.snapshot()["counters"].get("membudget.reject", 0)
        g_membudget.set_limit(16)
        assert not g_membudget.reserve("pack", 1 << 20)
        c = g_stats.snapshot()["counters"]
        assert c["membudget.reject"] == base + 1
        assert c.get("membudget.reject.pack", 0) >= 1

    def test_pressure_handler_runs_and_weakref_drops(self):
        class Owner:
            calls = 0

            def relieve(self, need):
                Owner.calls += 1
                g_membudget.set_limit(1 << 30)  # "free" memory
                return 1 << 30

        o = Owner()
        g_membudget.add_pressure_handler(o.relieve)
        g_membudget.set_limit(16)
        assert g_membudget.reserve("merge", 1 << 20)  # relief saved it
        assert Owner.calls == 1
        g_membudget.release("merge", 1 << 20)
        del o
        gc.collect()
        g_membudget.set_limit(16)
        assert not g_membudget.reserve("merge", 1 << 20)
        assert Owner.calls == 1  # dead handler was dropped, not called

    def test_memtable_gauge_tracks_adds_and_dumps(self, tmp_path):
        r = _mk(tmp_path)
        r.add(_keys(500, seed=3))
        assert g_membudget.used("memtable") > 0
        r.dump()
        assert g_membudget.used("memtable") == 0


class TestOverBudgetMerge:
    def test_merge_defers_then_succeeds_data_intact(self, tmp_path):
        r = _mk(tmp_path, max_memtable_bytes=1 << 30, max_runs=99)
        keys = _keys(3000, seed=7)
        for a in range(0, 3000, 500):
            r.add(keys[a:a + 500])
            r.dump()
        assert len(r.runs) == 6
        ks = np.sort(keys, order=("n2", "n1", "n0"))

        g_membudget.set_limit(16)  # nothing fits: merge must DEFER
        r.attempt_merge(force=True)
        assert len(r.runs) == 6  # deferred, process alive
        assert g_membudget.snapshot()["rejections"] > 0
        assert len(r.get_list(ks[0], ks[-1])) == 3000  # data intact

        g_membudget.set_limit(BIG)  # pressure gone: merge proceeds
        r.attempt_merge(force=True)
        assert len(r.runs) == 1
        assert len(r.get_list(ks[0], ks[-1])) == 3000
        assert g_membudget.used("merge") == 0  # reservation released

    def test_merge_shrinks_suffix_under_partial_budget(self, tmp_path):
        r = _mk(tmp_path, max_memtable_bytes=1 << 30, max_runs=99)
        keys = _keys(4000, seed=8)
        for a in range(0, 4000, 500):
            r.add(keys[a:a + 500])
            r.dump()
        assert len(r.runs) == 8
        # room for a ~2-3 run merge but nowhere near all 8
        one_run = int(r.runs[0].keys.nbytes)
        g_membudget.set_limit(g_membudget.used() + 7 * one_run)
        r.attempt_merge(force=True)
        assert 1 < len(r.runs) < 8  # merged a shrunken newest suffix
        ks = np.sort(keys, order=("n2", "n1", "n0"))
        assert len(r.get_list(ks[0], ks[-1])) == 4000


class TestStaleJournalTruncation:
    def test_disabled_open_truncates_stale_journal(self, tmp_path):
        r = _mk(tmp_path)
        r.add(_keys(100, seed=9))  # journaled, never dumped
        jp = r.dir / "addsinprogress.bin"
        assert jp.stat().st_size > 0
        del r
        r2 = rdblite.Rdb("t", tmp_path, posdb.KEY_DTYPE, journal=False)
        assert jp.stat().st_size == 0  # stale batches gone
        assert len(r2.mem.batch()) == 0
        del r2
        # a later journal-ENABLED open must not resurrect anything
        r3 = _mk(tmp_path)
        assert len(r3.mem.batch()) == 0


class TestBuildAndPackDegrade:
    def test_index_batch_sheds_but_indexes_everything(self, tmp_path):
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import \
            Collection
        coll = Collection("main", tmp_path)
        g_membudget.set_limit(256)  # every phase-C reserve refused
        out = docproc.index_batch(coll, _docs(12))
        assert sum(1 for v in out if v is not None) == 12
        assert coll.num_docs == 12
        assert g_stats.snapshot()["counters"].get(
            "membudget.reject.docproc", 0) > 0

    def test_search_correct_under_tiny_budget(self, tmp_path):
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.query import engine
        coll = Collection("main", tmp_path)
        docproc.index_batch(coll, _docs(8))
        want = engine.search(coll, "guardrail governor", topk=8,
                             with_snippets=False)
        g_membudget.set_limit(1)  # pack shrinks to 1-doc passes
        got = engine.search(coll, "guardrail governor", topk=8,
                            with_snippets=False)
        assert got.total_matches == want.total_matches == 8
        assert [r.docid for r in got.results] == \
            [r.docid for r in want.results]

    def test_sharded_pressure_handler_dumps_memtables(self, tmp_path):
        from open_source_search_engine_tpu.parallel.sharded import \
            ShardedCollection
        sc = ShardedCollection("main", tmp_path, n_shards=2)
        fat = sc.grid[0][0].posdb
        fat.add(_keys(80000, seed=11))  # ≥ 1 MB memtable
        assert fat.mem.nbytes >= 1 << 20
        freed = sc._relieve_memory(1)
        assert freed >= 1 << 20
        assert fat.mem.nbytes == 0 and len(fat.runs) == 1
        # weakref: a collected ShardedCollection leaves no live handler
        # (entries are (priority, seq, key, ref) since label caps)
        del sc, fat
        gc.collect()
        assert all(e[3]() is None for e in g_membudget._pressure)


# ----------------------------------------------------- device plane

class TestDevcheckHarness:
    @pytest.mark.parametrize("use_jit", [True, False],
                             ids=["jit", "interpret"])
    def test_clean_topk_passes(self, use_jit):
        devcheck.set_enabled(True)
        devcheck.check_topk(np.array([5.0, 3.0, 3.0, 0.0], np.float32),
                            np.array([2, 0, 1, 0], np.int32), 4,
                            route="f1", use_jit=use_jit)

    @pytest.mark.parametrize("use_jit", [True, False],
                             ids=["jit", "interpret"])
    @pytest.mark.parametrize("kind,pattern", [
        ("nan", "non-finite"),
        ("oob_docid", "out-of-range docid"),
    ])
    def test_injected_fault_caught(self, use_jit, kind, pattern):
        devcheck.set_enabled(True)
        scores = np.array([5.0, 3.0, 1.0, 0.0], np.float32)
        idx = np.array([2, 0, 1, 0], np.int32)
        with devcheck.inject(kind):
            idx, scores = devcheck.apply_fault(idx, scores, 4)
        base = g_stats.snapshot()["counters"].get("devcheck.trip", 0)
        with pytest.raises(devcheck.DeviceCheckError, match=pattern):
            devcheck.check_topk(scores, idx, 4, route="f1",
                                use_jit=use_jit)
        c = g_stats.snapshot()["counters"]
        assert c["devcheck.trip"] == base + 1
        assert c.get("devcheck.trip.f1", 0) >= 1

    @pytest.mark.parametrize("use_jit", [True, False],
                             ids=["jit", "interpret"])
    def test_monotonicity_violation_caught(self, use_jit):
        devcheck.set_enabled(True)
        with pytest.raises(devcheck.DeviceCheckError,
                           match="monotonic"):
            devcheck.check_topk(
                np.array([3.0, 5.0, 1.0], np.float32),
                np.array([0, 1, 2], np.int32), 3, use_jit=use_jit)

    @pytest.mark.parametrize("use_jit", [True, False],
                             ids=["jit", "interpret"])
    def test_corrupt_tile_caught(self, use_jit):
        devcheck.set_enabled(True)
        cube = np.zeros((2, 4, 8), np.uint32)
        cube[0, 0, 0] = (3 << 18) | 5  # legal payload
        devcheck.check_cube(cube, route="fd", use_jit=use_jit)
        with devcheck.inject("corrupt_tile"):
            bad = devcheck.apply_cube_fault(cube)
        with pytest.raises(devcheck.DeviceCheckError,
                           match="corrupt position-cube tile"):
            devcheck.check_cube(bad, route="fd", use_jit=use_jit)

    def test_disabled_is_noop(self):
        devcheck.set_enabled(None)
        nan = np.array([np.nan], np.float32)
        devcheck.check_topk(nan, np.array([99], np.int32), 1)  # silent


class TestDevcheckFullRoute:
    """Faults injected at the devindex emit hook trip on a REAL device
    search — in eager and jitted check modes (CPU via conftest)."""

    @pytest.fixture(scope="class")
    def dev(self, tmp_path_factory):
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.index.collection import \
            Collection
        from open_source_search_engine_tpu.query import engine
        tmp = tmp_path_factory.mktemp("devroute")
        coll = Collection("main", tmp)
        docproc.index_batch(coll, _docs(16))
        coll.dump_all()
        return engine.get_device_index(coll)

    @pytest.mark.parametrize("interpret", ["0", "1"],
                             ids=["jit", "interpret"])
    @pytest.mark.parametrize("kind,pattern", [
        ("nan", "non-finite"),
        ("oob_docid", "out-of-range docid"),
    ])
    def test_search_batch_trips(self, dev, monkeypatch, interpret,
                                kind, pattern):
        monkeypatch.setenv("OSSE_CHECKIFY_INTERPRET", interpret)
        devcheck.set_enabled(True)
        with devcheck.inject(kind):
            with pytest.raises(devcheck.DeviceCheckError,
                               match=pattern):
                dev.search_batch(["guardrail governor"], topk=5)

    def test_clean_search_no_trip(self, dev):
        devcheck.set_enabled(True)
        base = g_stats.snapshot()["counters"].get("devcheck.trip", 0)
        out = dev.search_batch(["guardrail governor", "tpu kernels"],
                               topk=5)
        assert all(nm > 0 for _, _, nm in out)
        assert g_stats.snapshot()["counters"].get(
            "devcheck.trip", 0) == base


# ------------------------------------------------------- serve plane

class TestAdminMem:
    def test_admin_mem_live_breakdown_under_load(self, tmp_path):
        from open_source_search_engine_tpu.build import docproc
        from open_source_search_engine_tpu.serve.server import \
            SearchHTTPServer
        s = SearchHTTPServer(str(tmp_path))
        # load: index through the server's collection — the memtable
        # gauge and (via a forced refusal) the reject counters light up
        coll = s.colldb.get("main")
        docproc.index_batch(coll, _docs(6))
        g_membudget.set_limit(16)
        assert not g_membudget.reserve("merge", 1 << 20)
        g_membudget.set_limit(BIG)

        code, body, ctype = s.handle(
            "GET", "/admin/mem", {"format": "json"}, b"")
        assert code == 200 and ctype == "application/json"
        snap = json.loads(body)
        assert snap["used"] > 0
        assert snap["labels"]["memtable"]["gauged"] > 0
        assert snap["limit"] == BIG
        assert snap["counters"].get("membudget.reject", 0) >= 1
        # HTML flavor + admin index link
        code, page, ctype = s.handle("GET", "/admin/mem", {}, b"")
        assert code == 200 and "memory budget" in page
        _, idx, _ = s.handle("GET", "/admin", {}, b"")
        assert "/admin/mem" in idx

    def test_parm_updates_flow_to_guardrails(self, tmp_path):
        from open_source_search_engine_tpu.serve.server import \
            SearchHTTPServer
        s = SearchHTTPServer(str(tmp_path))
        s.conf.max_mem = 123 << 20
        assert g_membudget.limit == 123 << 20
        assert not devcheck.enabled()
        s.conf.checkify = True
        assert devcheck.enabled()
        s.conf.checkify = False
        assert not devcheck.enabled()
