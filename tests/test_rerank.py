"""PostQueryRerank + AutoBan + least-loaded replica reads.

Reference: ``PostQueryRerank.cpp`` demotion factors over the merged
top window; ``AutoBan.cpp`` per-IP query rate bans; Multicast's
prefer-less-loaded twin for reads.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import engine
from open_source_search_engine_tpu.query.engine import Result
from open_source_search_engine_tpu.query.rerank import post_query_rerank


def _r(docid, score, url):
    return Result(docid=docid, score=score, url=url)


def test_same_domain_results_demote_geometrically():
    rs = [_r(1, 100.0, "http://a.test/1"),
          _r(2, 99.0, "http://a.test/2"),
          _r(3, 98.0, "http://b.test/1"),
          _r(4, 97.0, "http://a.test/3")]
    post_query_rerank(rs, site_demote=0.5, depth_demote=1.0)
    by_id = {r.docid: r.score for r in rs}
    assert by_id[1] == 100.0          # first of its domain: untouched
    assert by_id[2] == pytest.approx(99.0 * 0.5)    # 2nd a.test
    assert by_id[3] == 98.0
    assert by_id[4] == pytest.approx(97.0 * 0.25)   # 3rd a.test
    assert [r.docid for r in rs] == [1, 3, 2, 4]    # re-sorted


def test_depth_demotion_prefers_canonical_pages():
    rs = [_r(1, 100.0, "http://a.test/x/y/z/deep.html"),
          _r(2, 100.0, "http://b.test/")]
    post_query_rerank(rs, site_demote=1.0, depth_demote=0.9)
    assert rs[0].docid == 2  # the root page wins the tie


def test_language_demotion_uses_lookup():
    rs = [_r(1, 100.0, "http://a.test/"), _r(2, 99.0, "http://b.test/")]
    post_query_rerank(rs, qlang=1, lang_demote=0.5, site_demote=1.0,
                      depth_demote=1.0,
                      langid_of=lambda d: 2 if d == 1 else 1)
    assert rs[0].docid == 2 and rs[1].score == pytest.approx(50.0)


def test_pqr_window_keeps_pages_consistent(tmp_path):
    """Pages still partition the full list with PQR on: the rerank
    window is fixed by rank, not by the requested page."""
    coll = Collection("pqr", tmp_path)
    for i in range(20):
        docproc.index_document(
            coll, f"http://s{i % 5}.test/a/b{i % 3}/p{i}",
            f"<html><title>t{i}</title><body><p>pqr shared words "
            f"uniq{i}</p></body></html>")
    full = engine.search(coll, "pqr shared", topk=20,
                         with_snippets=False)
    pages = [engine.search(coll, "pqr shared", topk=5, offset=off,
                           with_snippets=False)
             for off in (0, 5, 10)]
    got = [r.url for p in pages for r in p.results]
    assert got == [r.url for r in full.results][: len(got)]


def test_pqr_disabled_by_parm(tmp_path):
    coll = Collection("pqr2", tmp_path)
    coll.conf.pqr_enabled = False
    for i in range(4):
        docproc.index_document(
            coll, f"http://one.test/deep/path/p{i}",
            f"<html><title>t</title><body><p>parm words u{i}</p>"
            "</body></html>")
    res = engine.search(coll, "parm words", topk=4,
                        with_snippets=False, site_cluster=False)
    # all same domain + deep paths: with PQR off, raw kernel order and
    # no demotion-induced score changes (scores strictly nonincreasing)
    scores = [r.score for r in res.results]
    assert scores == sorted(scores, reverse=True)


def test_autoban_429(tmp_path):
    from open_source_search_engine_tpu.serve.server import \
        SearchHTTPServer
    srv = SearchHTTPServer(tmp_path, port=0)
    coll = srv.colldb.get("main")
    coll.conf.autoban_qps = 3
    docproc.index_document(coll, "http://x.test/",
                           "<html><body>ban corpus words</body></html>")
    # trip the limiter directly (no query latency in the loop: the
    # window math must not depend on how long searches take)
    verdicts = [srv._autobanned("9.9.9.9", 3) for _ in range(8)]
    assert verdicts[0] is False and verdicts[-1] is True
    # a banned client's /search is refused BEFORE any query work
    assert srv.handle("GET", "/search", {"q": "ban corpus"}, b"",
                      client_ip="9.9.9.9")[0] == 429
    # a different client is unaffected
    assert srv.handle("GET", "/search", {"q": "ban corpus"}, b"",
                      client_ip="8.8.8.8")[0] == 200
    # other pages unaffected even for the banned ip
    assert srv.handle("GET", "/admin/stats", {}, b"",
                      client_ip="9.9.9.9")[0] == 200


def test_read_ewma_prefers_faster_twin():
    from open_source_search_engine_tpu.parallel.cluster import (
        ClusterClient, HostsConf)
    conf = HostsConf(n_shards=1, n_replicas=2,
                     addresses=[["127.0.0.1:1", "127.0.0.1:2"]])
    cc = ClusterClient(conf, use_heartbeat=False)
    try:
        cc.hostmap.rtt_s[0, 0] = 0.5   # slow twin
        cc.hostmap.rtt_s[0, 1] = 0.01  # fast twin
        assert cc.hostmap.twin_order(0) == [1, 0]
        cc.hostmap.mark_dead(0, 1)  # liveness dominates latency
        assert cc.hostmap.twin_order(0) == [0, 1]
    finally:
        cc.close()
