"""Crawlbot REST API (PageCrawlBot.cpp): create a crawl job over REST,
watch status, search the crawled corpus, pause/delete."""

import json
import time
import urllib.error
import urllib.request

import pytest

from open_source_search_engine_tpu.serve.server import SearchHTTPServer
from open_source_search_engine_tpu.spider.fetcher import (Fetcher,
                                                          FetchResult)

PAGES = {
    "http://cb.test/": "<html><body><p>crawlbot start page "
                       '<a href="/a">a</a> <a href="/b">b</a>'
                       "</p></body></html>",
    "http://cb.test/a": "<html><body><p>crawlbot alpha words"
                        "</p></body></html>",
    "http://cb.test/b": "<html><body><p>crawlbot beta words"
                        "</p></body></html>",
}


class FakeFetcher(Fetcher):
    def __init__(self):
        super().__init__(cache_ttl_s=0)

    def fetch_many(self, urls, **kw):
        return [FetchResult(url=u, status=200,
                            content=PAGES.get(u.rstrip("/") if
                                              u.rstrip("/") in PAGES
                                              else u, ""),
                            content_type="text/html") for u in urls]


@pytest.fixture
def srv(tmp_path):
    s = SearchHTTPServer(tmp_path, port=0)
    s.crawl_fetcher_factory = FakeFetcher
    s.start()
    yield s
    s.stop()


def _get(srv, path):
    try:
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{srv._httpd.server_port}{path}")
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_crawlbot_lifecycle(srv):
    st, body = _get(srv, "/crawlbot")
    assert st == 200 and body["jobs"] == []
    st, body = _get(srv, "/crawlbot?name=shop&seeds=http://cb.test/"
                         "&maxpages=10&maxhops=3")
    assert st == 200 and body["name"] == "shop"
    # duplicate create → 409
    st, _ = _get(srv, "/crawlbot?name=shop&seeds=http://cb.test/")
    assert st == 409
    for _ in range(100):
        st, body = _get(srv, "/crawlbot?name=shop")
        if body["done"]:
            break
        time.sleep(0.2)
    assert body["indexed"] == 3 and body["links_found"] >= 2
    # the crawled corpus answers through the normal search surface
    st, res = _get(srv, "/search?q=crawlbot+alpha&c=crawl_shop"
                        "&format=json")
    assert st == 200 and res["totalMatches"] == 1
    assert res["results"][0]["url"] == "http://cb.test/a"
    st, body = _get(srv, "/crawlbot?name=shop&action=delete")
    assert st == 200 and body["deleted"]
    st, _ = _get(srv, "/crawlbot?name=shop")
    assert st == 404


def test_crawlbot_requires_auth_when_password_set(srv):
    srv.conf.master_password = "pw"
    st, _ = _get(srv, "/crawlbot")
    assert st == 401
    st, body = _get(srv, "/crawlbot?pwd=pw")
    assert st == 200
    srv.conf.master_password = ""
