"""CLI tests — the reference's `gb` command verbs (main.cpp:1084-3887)
as `python -m open_source_search_engine_tpu {inject,search,save,serve}`.

The quickstart contract: inject docs, query, save, restart losslessly —
all from a shell with no Python written.
"""

import json
import subprocess
import sys

REPO = str(__import__("pathlib").Path(__file__).resolve().parent.parent)


def run_cli(tmp_path, *argv: str, stdin: str | None = None):
    proc = subprocess.run(
        [sys.executable, "-m", "open_source_search_engine_tpu", *argv],
        capture_output=True, text=True, input=stdin, cwd=tmp_path,
        env={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin", "HOME": str(tmp_path)},
        timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_inject_search_save_restart(tmp_path):
    out = run_cli(
        tmp_path, "inject", "--dir", "d", "http://cli.test/a",
        stdin="<html><head><title>Apple pie</title></head><body>"
              "<p>apple pie recipe with cinnamon.</p></body></html>")
    assert out["docs"] == 1 and out["docid"] > 0

    out = run_cli(
        tmp_path, "inject", "--dir", "d", "http://cli.test/b",
        stdin="<html><head><title>Banana bread</title></head><body>"
              "<p>banana bread recipe, moist.</p></body></html>")
    assert out["docs"] == 2

    out = run_cli(tmp_path, "search", "--dir", "d", "recipe", "--json")
    assert out["total"] == 2
    urls = {r["url"] for r in out["results"]}
    assert urls == {"http://cli.test/a", "http://cli.test/b"}

    out = run_cli(tmp_path, "save", "--dir", "d")
    assert "main" in out["saved"]

    # a fresh process (the restart) still sees everything
    out = run_cli(tmp_path, "search", "--dir", "d", "banana", "--json")
    assert out["total"] == 1
    assert out["results"][0]["url"] == "http://cli.test/b"


def test_proxy_mode_registered():
    """gb proxy (main.cpp:1691): the CLI exposes the front-proxy mode."""
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, "-m", "open_source_search_engine_tpu",
         "proxy", "--help"],
        capture_output=True, text=True, timeout=60,
        env={"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
             "PATH": "/usr/bin:/bin"})
    assert out.returncode == 0 and "cluster" in out.stdout
