"""Boolean expression + synonym tests (VERDICT round-2 item 4).

Reference: Query.h:266 boolean truth tables; Synonyms.cpp conjugate
forms with SYNONYM_WEIGHT=0.90 (Posdb.h:21 FORM_CONJUGATE). The same
plan must produce identical results on the host-packed, resident
(two-phase/full-cube), and sharded paths.
"""

import numpy as np
import pytest

from open_source_search_engine_tpu.build import docproc
from open_source_search_engine_tpu.index.collection import Collection
from open_source_search_engine_tpu.query import compiler, engine
from open_source_search_engine_tpu.query.engine import search_device

DOCS = {
    "http://b.test/apple": "<html><head><title>Apple</title></head>"
        "<body><p>apple orchard rows in autumn.</p></body></html>",
    "http://b.test/banana": "<html><head><title>Banana</title></head>"
        "<body><p>banana plantation by the coast.</p></body></html>",
    "http://b.test/both": "<html><head><title>Fruit stand</title></head>"
        "<body><p>apple and banana smoothies daily.</p></body></html>",
    "http://b.test/cherry": "<html><head><title>Cherry</title></head>"
        "<body><p>cherry pie season starts now.</p></body></html>",
    "http://b.test/apples": "<html><head><title>Apples galore</title>"
        "</head><body><p>apples piled high at market.</p></body></html>",
}


@pytest.fixture(scope="module")
def coll(tmp_path_factory):
    c = Collection("bool", tmp_path_factory.mktemp("bool"))
    for u, h in DOCS.items():
        docproc.index_document(c, u, h)
    return c


def urls(res):
    return {r.url for r in res.results}


class TestBooleanCompile:
    def test_truth_table(self):
        p = compiler.compile_query("a AND (b OR c) AND NOT d")
        assert p.bool_table is not None
        t = p.bool_table
        bit = {g.display: i for i, g in enumerate(p.groups)}
        def m(*names):
            return t[sum(1 << bit[n] for n in names)]
        assert m("a", "b")
        assert m("a", "c")
        assert m("a", "b", "c")
        assert not m("a")
        assert not m("b", "c")
        assert not m("a", "b", "d")

    def test_pure_not_rejected(self):
        p = compiler.compile_query("NOT apple")
        # unservable boolean → falls back to plain words, not a crash
        assert p.bool_table is None

    def test_malformed_falls_back(self):
        p = compiler.compile_query("apple AND")
        assert p.bool_table is None
        assert len(p.groups) >= 1


class TestBooleanSearch:
    QUERIES = [
        ("apple OR banana",
         {"http://b.test/apple", "http://b.test/banana",
          "http://b.test/both", "http://b.test/apples"}),
        ("apple AND banana", {"http://b.test/both"}),
        ("apple AND NOT banana",
         {"http://b.test/apple", "http://b.test/apples"}),
        ("(apple OR cherry) AND NOT banana",
         {"http://b.test/apple", "http://b.test/apples",
          "http://b.test/cherry"}),
        ("banana OR (cherry AND pie)",
         {"http://b.test/banana", "http://b.test/both",
          "http://b.test/cherry"}),
    ]

    def test_host_path_semantics(self, coll):
        for q, expected in self.QUERIES:
            res = engine.search(coll, q, topk=10, site_cluster=False)
            assert urls(res) == expected, q
            assert res.total_matches == len(expected), q

    def test_resident_parity(self, coll):
        for q, expected in self.QUERIES:
            host = engine.search(coll, q, topk=10, site_cluster=False)
            dev = search_device(coll, q, topk=10, site_cluster=False)
            assert urls(dev) == expected, q
            assert dev.total_matches == host.total_matches, q
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted(map(key, dev.results)) == \
                   sorted(map(key, host.results)), q

    def test_sharded_parity(self, tmp_path):
        from open_source_search_engine_tpu.parallel import (
            ShardedCollection, make_mesh, sharded_search)
        sc = ShardedCollection("bools", tmp_path, n_shards=4)
        for u, h in DOCS.items():
            sc.index_document(u, h)
        mesh = make_mesh(4)
        flat = Collection("boolf", tmp_path / "flat")
        for u, h in DOCS.items():
            docproc.index_document(flat, u, h)
        for q, expected in self.QUERIES:
            res = sharded_search(sc, q, mesh=mesh, topk=10,
                                 site_cluster=False)
            assert urls(res) == expected, q
            host = engine.search(flat, q, topk=10, site_cluster=False)
            assert res.total_matches == host.total_matches, q


class TestSynonyms:
    def test_conjugate_matches_with_discount(self, coll):
        # query "apple" matches the "apples" doc via the synonym sublist
        res = engine.search(coll, "apple", topk=10, site_cluster=False)
        assert "http://b.test/apples" in urls(res)
        by_url = {r.url: r.score for r in res.results}
        # identical structure (title + body) but the synonym form scores
        # ×0.90² — strictly below the literal match
        assert by_url["http://b.test/apples"] < by_url["http://b.test/apple"]

    def test_synonym_weight_visible(self, coll):
        """The 0.90 weight shows up as an exact ×0.81 on the synonym
        doc's single-term score vs compiling without synonyms."""
        plan_syn = compiler.compile_query("apple")
        plan_lit = compiler.compile_query("apple", synonyms=False)
        r_syn = engine.search(coll, plan_syn, topk=10, site_cluster=False)
        r_lit = engine.search(coll, plan_lit, topk=10, site_cluster=False)
        assert "http://b.test/apples" in urls(r_syn)
        assert "http://b.test/apples" not in urls(r_lit)

    def test_parity_on_synonym_queries(self, coll):
        for q in ["apple", "apples", "banana smoothie"]:
            host = engine.search(coll, q, topk=10, site_cluster=False)
            dev = search_device(coll, q, topk=10, site_cluster=False)
            assert dev.total_matches == host.total_matches, q
            key = lambda r: (-round(r.score, 3), r.docid)
            assert sorted(map(key, dev.results)) == \
                   sorted(map(key, host.results)), q

    def test_negative_stays_literal(self, coll):
        # "-apple" must not exclude the "apples" doc (negatives literal)
        res = engine.search(coll, "market -apple", topk=10,
                            site_cluster=False)
        assert "http://b.test/apples" in urls(res)
